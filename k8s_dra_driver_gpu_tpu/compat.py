# Copyright 2025 The tpu-dra-driver Authors.
# SPDX-License-Identifier: Apache-2.0
"""Version-compatibility shims for the pinned accelerator toolchain.

jax.shard_map is the stable spelling only in newer JAX releases; the
toolchain baked into CI (0.4.x) still ships it under
jax.experimental.shard_map. Every in-tree user imports the symbol from
here so the version probe lives in exactly one place.
"""

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pragma: no cover - version-dependent import
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, **kwargs):
        # The replication-check kwarg was renamed check_rep -> check_vma
        # when shard_map stabilized; callers use the new spelling.
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _experimental_shard_map(f, **kwargs)
