# Copyright 2025 The tpu-dra-driver Authors.
# SPDX-License-Identifier: Apache-2.0
"""Version-compatibility shims for the pinned accelerator toolchain.

jax.shard_map is the stable spelling only in newer JAX releases; the
toolchain baked into CI (0.4.x) still ships it under
jax.experimental.shard_map. Every in-tree user imports the symbol from
here so the version probe lives in exactly one place.
"""

import os
import re

import jax


def _xla_bridge():
    """jax's backend registry module (stable private location across
    the versions this repo spans); None-ish object when it moves."""
    try:
        from jax._src import xla_bridge  # noqa: PLC0415

        return xla_bridge
    except ImportError:  # pragma: no cover - future jax relayout
        return None


def force_cpu_devices(n: int) -> None:
    """Force an ``n``-device CPU backend for THIS process. Must run
    before any JAX backend initialization (jax.devices(), first op).

    Newer JAX spells this as the ``jax_num_cpu_devices`` config option;
    the pinned 0.4.x toolchain predates it and only honors the
    ``--xla_force_host_platform_device_count`` XLA flag, which is read
    from the environment at backend init. Raises RuntimeError when a
    backend is already live (0.4.x accepts the config mutations
    without complaint and then silently ignores them -- a silent no-op
    here would leave the caller on the wrong backend with the wrong
    device count), so callers keep one except clause either way.
    """
    backends = getattr(
        getattr(_xla_bridge(), "_backends", None), "keys", lambda: ())()
    if backends:
        raise RuntimeError(
            f"JAX backend(s) {sorted(backends)} already initialized; "
            "cannot force CPU device count")
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:  # pre-option JAX: go through XLA_FLAGS
        flag = f"--xla_force_host_platform_device_count={n}"
        flags = os.environ.get("XLA_FLAGS", "")
        flags, subs = re.subn(
            r"--xla_force_host_platform_device_count=\d+", flag, flags)
        if not subs:
            flags = f"{flags} {flag}".strip()
        os.environ["XLA_FLAGS"] = flags


if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pragma: no cover - version-dependent import
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, **kwargs):
        # The replication-check kwarg was renamed check_rep -> check_vma
        # when shard_map stabilized; callers use the new spelling.
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _experimental_shard_map(f, **kwargs)
