"""Ulysses-style all-to-all sequence parallelism.

The complement to ring attention for long sequences: instead of rotating
K/V chunks, two all-to-alls re-shard activations between
sequence-sharded and head-sharded layouts around the attention core --
each device then computes FULL-sequence attention for a subset of heads.
Communication volume is O(S*D/n) per all-to-all (independent of step
count), which beats the ring when heads divide evenly and the sequence
fits per-device HBM after the swap; the ring wins at extreme sequence
lengths. Both ride the same sp axis ICI neighborhood.

Layout contract (inside shard_map over axis "sp", n = axis size):
  in:  q/k/v [B, S/n, H, hd]  (sequence-sharded)
  mid: q/k/v [B, S, H/n, hd]  (head-sharded, after all-to-all)
  out:       [B, S/n, H, hd]  (sequence-sharded, after the inverse)
"""

from __future__ import annotations

from functools import partial

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compat import shard_map

from ..ops.attention import attention


def _seq_to_heads(x: jax.Array, axis_name: str) -> jax.Array:
    """[B, S/n, H, hd] -> [B, S, H/n, hd] via all_to_all over heads."""
    # Split the head dim across devices, gather the sequence dim.
    return jax.lax.all_to_all(
        x, axis_name, split_axis=2, concat_axis=1, tiled=True
    )


def _heads_to_seq(x: jax.Array, axis_name: str) -> jax.Array:
    """[B, S, H/n, hd] -> [B, S/n, H, hd] (inverse all_to_all)."""
    return jax.lax.all_to_all(
        x, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def ulysses_attention(
    q: jax.Array,  # [B, S/n, H, hd] inside shard_map
    k: jax.Array,  # [B, S/n, K, hd]
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
    impl: str = "auto",
) -> jax.Array:
    n = jax.lax.psum(1, axis_name)
    H = q.shape[2]
    K = k.shape[2]
    if H % n or K % n:
        raise ValueError(
            f"Ulysses needs heads divisible by the sp size: H={H} K={K} n={n}"
        )
    qh = _seq_to_heads(q, axis_name)
    kh = _seq_to_heads(k, axis_name)
    vh = _seq_to_heads(v, axis_name)
    # After the all_to_all each device holds FULL-sequence q/k/v for
    # its head subset -- exactly the regime where the dispatcher picks
    # the pallas flash kernel (S >= FLASH_MIN_SEQ, hd % 128 == 0): at
    # S >= 4096 the einsum path cannot even materialize its S x S
    # scores, so Ulysses long-context is only viable through it.
    out = attention(qh, kh, vh, causal=causal, impl=impl)
    return _heads_to_seq(out, axis_name)


def make_ulysses_attention(mesh: Mesh, axis_name: str = "sp",
                           causal: bool = True, impl: str = "auto"):
    """jitted [B, S, H, hd] attention with S sharded over ``axis_name``
    (same surface as make_ring_attention)."""
    spec = P(None, axis_name, None, None)

    @jax.jit
    def fn(q, k, v):
        return shard_map(
            partial(ulysses_attention, axis_name=axis_name, causal=causal,
                    impl=impl),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            # pallas_call outputs carry no varying-mesh-axes annotation;
            # every input/output here shares one spec, so the vma check
            # adds nothing (the flash path would otherwise need per-axis
            # vma on its ShapeDtypeStructs).
            check_vma=False,
        )(q, k, v)

    def place(x):
        return jax.device_put(x, NamedSharding(mesh, spec))

    return fn, place
