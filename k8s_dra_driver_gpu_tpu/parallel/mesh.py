"""Device-mesh construction from ICI slice topologies.

Bridges the driver side and the workload side: tpulib enumerates a slice
topology like "2x2x4"; this module turns the same topology into a
jax.sharding.Mesh whose axes ride ICI. Axis sizing follows the
scaling-book recipe: put the fastest-varying (most-communicating) axis
("tp") innermost so its collectives stay on-chip-adjacent ICI links, data
parallelism outermost.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical logical axis names used across the workload stack.
DATA_AXIS = "dp"
FSDP_AXIS = "fsdp"
TENSOR_AXIS = "tp"
SEQUENCE_AXIS = "sp"
EXPERT_AXIS = "ep"
PIPELINE_AXIS = "pp"


@dataclass(frozen=True)
class MeshPlan:
    """A factorization of the device count over logical axes."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.fsdp * self.tp * self.sp

    def axis_names(self) -> tuple[str, ...]:
        # tp is the innermost (fastest-varying) axis so tensor-parallel
        # collectives -- the most communication-intensive -- land on
        # ICI-adjacent chips; sp sits just outside it.
        return (DATA_AXIS, FSDP_AXIS, SEQUENCE_AXIS, TENSOR_AXIS)

    def shape(self) -> tuple[int, ...]:
        return (self.dp, self.fsdp, self.sp, self.tp)


def _factor(n: int, max_tp: int) -> MeshPlan:
    """Default factorization: tp = largest power of two <= max_tp dividing
    n (tensor parallelism wants the tightest ICI neighborhood), fsdp takes
    the next factor up to 8, dp absorbs the rest."""
    tp = 1
    while tp * 2 <= max_tp and n % (tp * 2) == 0:
        tp *= 2
    rem = n // tp
    fsdp = 1
    while fsdp * 2 <= 8 and rem % (fsdp * 2) == 0:
        fsdp *= 2
    dp = rem // fsdp
    return MeshPlan(dp=dp, fsdp=fsdp, tp=tp)


def plan_for(n_devices: int, tp: int | None = None, sp: int = 1) -> MeshPlan:
    """Pick a MeshPlan for n_devices, honoring an explicit tp if given."""
    if tp is None:
        plan = _factor(n_devices // sp, max_tp=4)
        return MeshPlan(dp=plan.dp, fsdp=plan.fsdp, tp=plan.tp, sp=sp)
    if n_devices % (tp * sp):
        raise ValueError(f"{n_devices} devices not divisible by tp={tp}*sp={sp}")
    plan = _factor(n_devices // (tp * sp), max_tp=1)
    return MeshPlan(dp=plan.dp * plan.fsdp, fsdp=1, tp=tp, sp=sp)


def build_mesh(
    plan: MeshPlan | None = None,
    devices: list | None = None,
) -> Mesh:
    """Build a Mesh over ``devices`` (default: all) shaped by ``plan``.

    Device order is row-major over the plan shape; on real TPU slices
    jax.devices() is already ICI-topology-ordered, so the innermost mesh
    axis lands on ICI-adjacent chips.
    """
    devs = devices if devices is not None else jax.devices()
    if plan is None:
        plan = plan_for(len(devs))
    if plan.size != len(devs):
        raise ValueError(
            f"mesh plan {plan.shape()} needs {plan.size} devices, have {len(devs)}"
        )
    arr = np.asarray(devs).reshape(plan.shape())
    return Mesh(arr, plan.axis_names())


DCN_AXIS = "dcn"


def build_multislice_mesh(
    num_slices: int,
    plan: MeshPlan | None = None,
    devices: list | None = None,
) -> Mesh:
    """Multislice: a leading DCN axis over ICI slices.

    Cross-slice traffic rides the data-center network, so only gradient
    data-parallelism belongs on the "dcn" axis; tp/fsdp/sp stay inside a
    slice (each slice's devices form a contiguous block). On real
    multislice jobs jax.devices() groups by slice already; the CPU mesh
    simulates that by block-partitioning.
    """
    devs = devices if devices is not None else jax.devices()
    if len(devs) % num_slices:
        raise ValueError(
            f"{len(devs)} devices not divisible by {num_slices} slices"
        )
    per_slice = len(devs) // num_slices
    if plan is None:
        plan = plan_for(per_slice)
    if plan.size != per_slice:
        raise ValueError(
            f"plan {plan.shape()} needs {plan.size} devices/slice, "
            f"have {per_slice}"
        )
    arr = np.asarray(devs).reshape((num_slices,) + plan.shape())
    return Mesh(arr, (DCN_AXIS,) + plan.axis_names())


def build_pipeline_mesh(
    pp: int,
    dp: int | None = None,
    devices: list | None = None,
) -> Mesh:
    """A ("pp", "dp") mesh for pipeline-parallel training.

    Pipeline stage-to-stage traffic is point-to-point activations (small
    vs the dp gradient all-reduce), so "pp" is the OUTERMOST axis: dp
    replicas of one stage stay ICI-adjacent and the gradient all-reduce
    rides the tight neighborhood, while the per-tick ppermute tolerates
    the longer hops. (Scaling-book recipe: give the weakest links to the
    least bandwidth-hungry axis.)
    """
    devs = devices if devices is not None else jax.devices()
    if dp is None:
        if len(devs) % pp:
            raise ValueError(f"{len(devs)} devices not divisible by pp={pp}")
        dp = len(devs) // pp
    if pp * dp != len(devs):
        raise ValueError(
            f"pp={pp} x dp={dp} needs {pp * dp} devices, have {len(devs)}")
    arr = np.asarray(devs).reshape((pp, dp))
    return Mesh(arr, (PIPELINE_AXIS, DATA_AXIS))


def mesh_from_topology(topology: str, tp: int | None = None) -> Mesh:
    """Build a mesh for an ICI topology string ("2x2x4") as enumerated by
    tpulib / published in ResourceSlice attributes."""
    n = math.prod(int(d) for d in topology.split("x"))
    return build_mesh(plan_for(n, tp=tp), devices=jax.devices()[:n])
