"""Parallelism layer: device meshes, sharding rules, sequence parallelism.

The reference driver orchestrates fabric domains but ships no collective
code (SURVEY.md §2.9); its fabric is exercised by external NCCL jobs. The
TPU build ships the workload side in-tree: meshes built from the same ICI
topologies tpulib enumerates, SPMD sharding rules, and ring attention for
long sequences -- all via jax.sharding + shard_map over XLA collectives.
"""
