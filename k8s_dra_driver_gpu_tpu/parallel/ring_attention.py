"""Ring attention: sequence-parallel causal attention over ICI.

Long-context first-class: the sequence dimension is sharded over the
"sp" mesh axis. Each device holds a local q/k/v shard; K/V chunks rotate
around the ring via ppermute while every device accumulates its local
queries' attention with online log-sum-exp merging. Communication is
overlapped ring traffic on ICI neighbors -- exactly the layout
build_mesh gives the sp axis.

Causality across shards: chunk c (absolute sequence offset c * S_local)
is attended with a full/partial/empty mask depending on its position
relative to the local q shard, computed per step from the rotating
source index.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compat import shard_map

NEG_INF = -1e30


def _chunk_attention(q, k, v, q_offset, k_offset, causal):
    """fp32 partial attention of a local q shard vs one k/v chunk.

    Returns (o_unnormalized [B,S,H,hd], m [B,S,H,1], l [B,S,H,1]).
    """
    B, Sq, H, hd = q.shape
    K = k.shape[2]
    group = H // K
    qg = q.reshape(B, Sq, K, group, hd).astype(jnp.float32)
    kf = k.astype(jnp.float32)
    s = jnp.einsum("bqkgh,bskh->bkgqs", qg, kf) / jnp.sqrt(
        jnp.asarray(hd, jnp.float32))
    if causal:
        Sk = k.shape[1]
        q_pos = q_offset + jnp.arange(Sq)[:, None]
        k_pos = k_offset + jnp.arange(Sk)[None, :]
        mask = q_pos >= k_pos  # [Sq, Sk]
        s = jnp.where(mask[None, None, None], s, NEG_INF)
    # The m/l stats are scaling factors that cancel exactly in the final
    # o/l ratio, so they carry NO gradient -- stop_gradient them fully.
    # (Stopping m only inside exp(s - m) while _merge differentiates its
    # alphas through the raw m leaves a spurious non-canceling term that
    # corrupts dq/dk.)
    m = jax.lax.stop_gradient(
        jnp.maximum(jnp.max(s, axis=-1, keepdims=True), NEG_INF / 2)
    )  # [B,K,g,Sq,1]; the maximum() keeps exp() finite on masked rows
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bkgqs,bskh->bkgqh", p, v.astype(jnp.float32))
    # -> [B, Sq, H, ...]
    o = o.reshape(B, H, Sq, hd).transpose(0, 2, 1, 3)
    m = m.reshape(B, H, Sq, 1).transpose(0, 2, 1, 3)
    l = l.reshape(B, H, Sq, 1).transpose(0, 2, 1, 3)
    return o, m, l


def _merge(acc, new):
    """Online log-sum-exp merge of two partial attention results."""
    o_a, m_a, l_a = acc
    o_n, m_n, l_n = new
    m = jnp.maximum(m_a, m_n)
    alpha_a = jnp.exp(m_a - m)
    alpha_n = jnp.exp(m_n - m)
    return (o_a * alpha_a + o_n * alpha_n,
            m,
            l_a * alpha_a + l_n * alpha_n)


def ring_attention(
    q: jax.Array,  # [B, S_local, H, hd] (already sp-sharded inside shard_map)
    k: jax.Array,  # [B, S_local, K, hd]
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
) -> jax.Array:
    """Runs INSIDE shard_map over the sp axis."""
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    B, S, H, hd = q.shape
    q_offset = my * S

    # The carry must be device-varying over the ring axis from the
    # start (shard_map vma typing), since the loop outputs are.
    def vary(x):
        if hasattr(jax.lax, "pcast"):  # jax >= the pvary deprecation
            return jax.lax.pcast(x, (axis_name,), to="varying")
        if hasattr(jax.lax, "pvary"):
            return jax.lax.pvary(x, (axis_name,))
        # Pre-vma JAX (experimental shard_map, check_rep=False): the
        # varying annotation doesn't exist and isn't needed.
        return x

    o0 = vary(jnp.zeros((B, S, H, hd), jnp.float32))
    m0 = vary(jnp.full((B, S, H, 1), NEG_INF, jnp.float32))
    l0 = vary(jnp.zeros((B, S, H, 1), jnp.float32))

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(i, carry):
        acc, kv = carry
        k_cur, v_cur = kv
        # After i rotations we hold the chunk of device (my - i) mod n.
        src = (my - i) % n
        new = _chunk_attention(q, k_cur, v_cur, q_offset, src * S, causal)
        acc = _merge(acc, new)
        kv = jax.tree_util.tree_map(
            lambda x: jax.lax.ppermute(x, axis_name, perm), (k_cur, v_cur)
        )
        return acc, kv

    (o, _, l), _ = jax.lax.fori_loop(0, n, step, ((o0, m0, l0), (k, v)))
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def make_ring_attention(mesh: Mesh, axis_name: str = "sp", causal: bool = True):
    """jitted [B, S, H, hd] attention with S sharded over ``axis_name``."""
    spec = P(None, axis_name, None, None)

    @jax.jit
    def fn(q, k, v):
        return shard_map(
            partial(ring_attention, axis_name=axis_name, causal=causal),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            # Replication is argued by the vary() annotations on the
            # fori_loop carry (vma-capable JAX); the pre-vma checker
            # cannot see through the DIFFERENTIATED loop (the grad's
            # scan carry mixes replicated cotangents into the varying
            # ring state) and rejects a correct program.
            check_vma=False,
        )(q, k, v)

    def place(x):
        return jax.device_put(x, NamedSharding(mesh, spec))

    return fn, place
