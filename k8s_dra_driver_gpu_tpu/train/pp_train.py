"""Pipeline-parallel (GPipe-schedule) Llama training step.

TPU-first collective pipelining, not a stage-per-process port: the whole
step runs inside one ``shard_map`` over a ("pp", "dp") mesh. The stacked
layer parameters ([L, ...] leaves) shard their leading dim over "pp", so
each device holds a contiguous block of L/pp layers; microbatches stream
through a ``lax.scan`` over M + pp - 1 ticks, and after every tick the
activations rotate one stage forward with ``lax.ppermute`` on ICI.
Embedding lives on stage 0 and the LM head + loss on the last stage
(both leaves are replicated for simplicity; only the owning stage's
compute touches them, and a psum over "pp" folds their gradients).

Why this shape for TPU/XLA:
- One jitted SPMD program; the schedule is a compiler-visible ``scan``
  with static trip count, not host-side stage orchestration.
- Stage-to-stage transfer is a single ``ppermute`` of the [mb, S, D]
  activation block per tick -- point-to-point on ICI, overlappable by
  XLA with the next tick's compute (schedule per the GPipe paper,
  arXiv:1811.06965).
- Autodiff runs INSIDE the shard_map: the transpose of ``ppermute`` is
  the reverse rotation, so backward ticks stream cotangents stage
  pp-1 -> 0 with the same collective, giving the classic
  forward-then-backward GPipe schedule with bubble fraction
  (pp-1)/(M+pp-1). ``cfg.remat`` applies to the stage body, so per-tick
  activation memory is O(carry), the GPipe rematerialization trade.

Reference parity note: the reference driver has no pipeline engine
in-tree (SURVEY.md §2.9 -- its workloads bring their own); this module
is part of the workload-side parallelism surface the TPU framework
ships so a prepared multi-chip claim can be driven by every major
parallelism family (dp/fsdp/tp/sp/ep/pp).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compat import shard_map

from ..models import llama
from ..parallel.mesh import DATA_AXIS, PIPELINE_AXIS
from .train import TrainState, make_optimizer


def pp_param_specs(cfg: llama.LlamaConfig,
                   pp_axis: str = PIPELINE_AXIS) -> dict:
    """PartitionSpecs for pipeline training: stacked layer leaves shard
    their leading (layer) dim over ``pp_axis``; everything else is
    replicated."""
    specs = jax.tree.map(
        lambda _: P(), llama.param_specs(cfg),
        is_leaf=lambda x: isinstance(x, P))
    specs["layers"] = jax.tree.map(
        lambda _: P(pp_axis), specs["layers"],
        is_leaf=lambda x: isinstance(x, P))
    return specs


def make_pp_train(
    mesh: Mesh,
    cfg: llama.LlamaConfig,
    n_microbatches: int,
    optimizer: optax.GradientTransformation | None = None,
    pp_axis: str = PIPELINE_AXIS,
    dp_axis: str = DATA_AXIS,
):
    """Returns (init_fn, step_fn, batch_sharding, place_params).

    Tokens are [M, B, S+1]: M microbatches per optimizer step, batch
    sharded over ``dp_axis``, replicated over ``pp_axis`` (each stage
    reads only the slice its role needs: stage 0 the inputs, the last
    stage the targets). The update equals a plain synchronous step on
    the concatenated M*B batch -- GPipe is exact data parallelism over
    microbatches, there is no staleness.
    """
    pp = mesh.shape[pp_axis]
    M = n_microbatches
    if cfg.n_layers % pp:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by pp={pp}")
    if M < 1:
        raise ValueError("need at least one microbatch")
    optimizer = optimizer or make_optimizer()
    specs = pp_param_specs(cfg, pp_axis)
    param_shard = jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))
    token_spec = P(None, dp_axis, None)
    batch_shard = NamedSharding(mesh, token_spec)
    dt = cfg.dtype

    def stage_fn(layers_local, x, positions):
        """Apply this stage's L/pp layers ([L/pp, ...] local leaves)."""
        body = lambda carry, lp: (  # noqa: E731
            llama._layer(cfg, carry, lp, positions), None)
        x, _ = jax.lax.scan(llama.apply_remat(body, cfg.remat), x,
                            layers_local)
        return x

    def local_loss(params, tokens):
        """This device's contribution to the global mean loss.

        Only the last stage produces a nonzero value; the caller psums
        over ``pp_axis`` to recover the full mean (and pmeans over
        ``dp_axis`` for the batch shards).
        """
        idx = jax.lax.axis_index(pp_axis)
        inputs, targets = tokens[..., :-1], tokens[..., 1:]
        mb, S = inputs.shape[1], inputs.shape[2]
        positions = jnp.arange(S)[None, :]
        x0 = jnp.zeros((mb, S, cfg.d_model), dt)
        fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]

        def head_loss(x, m):
            h = llama.rms_norm(x, params["final_norm"], cfg.norm_eps)
            logits = (h @ params["lm_head"].astype(dt)).astype(jnp.float32)
            tgt = targets[jnp.clip(m, 0, M - 1)]
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tgt).mean()

        def tick(carry, t):
            x, loss_sum = carry
            # Stage 0 ingests microbatch t's embedding (bubble ticks
            # t >= M re-feed a clipped batch whose output never reaches
            # a counted loss); later stages keep the rotated-in value.
            fresh = params["embed"].astype(dt)[inputs[jnp.clip(t, 0, M - 1)]]
            x = jnp.where(idx == 0, fresh, x)
            x = stage_fn(params["layers"], x, positions)
            # Last stage scores microbatch m = t - (pp-1) once it has
            # traversed all stages. lax.cond skips the V-sized head
            # matmul at runtime on every other (stage, tick).
            m = t - (pp - 1)
            valid = (idx == pp - 1) & (m >= 0) & (m < M)
            loss_t = jax.lax.cond(
                valid, head_loss, lambda x, m: jnp.float32(0.0), x, m)
            x = jax.lax.ppermute(x, pp_axis, fwd_perm)
            return (x, loss_sum + loss_t), None

        (_, loss_sum), _ = jax.lax.scan(
            tick, (x0, jnp.float32(0.0)), jnp.arange(M + pp - 1))
        return loss_sum / M

    def local_value_and_grad(params, tokens):
        loss, grads = jax.value_and_grad(local_loss)(params, tokens)
        # Stage-owned layer grads: cotangents already arrived via the
        # reverse ppermute, so they are totals for this stage's layers;
        # average the dp batch shards only. Replicated leaves (embed,
        # head, final norm): nonzero only on the owning stage -- psum
        # over pp makes every copy the true total.
        grads = jax.lax.pmean(grads, dp_axis)
        repl = jax.tree.map(
            lambda g, s: jax.lax.psum(g, pp_axis) if s == P() else g,
            grads, specs, is_leaf=lambda x: isinstance(x, P))
        loss = jax.lax.pmean(jax.lax.psum(loss, pp_axis), dp_axis)
        return loss, repl

    @partial(jax.jit, in_shardings=(param_shard,))
    def init_fn(params):
        return TrainState(
            params=params,
            opt_state=optimizer.init(params),
            step=jnp.zeros((), jnp.int32),
        )

    @partial(jax.jit, donate_argnums=(0,))
    def step_fn(state: TrainState, tokens):
        # Static at trace time; without this the clipped microbatch
        # gathers below would silently re-count batches on a mismatch.
        if tokens.ndim != 3 or tokens.shape[0] != M:
            raise ValueError(
                f"tokens must be [M={M}, B, S+1], got {tokens.shape}")
        loss, grads = shard_map(
            local_value_and_grad,
            mesh=mesh,
            in_specs=(specs, token_spec),
            out_specs=(P(), specs),
            check_vma=False,  # replication argued in local_value_and_grad
        )(state.params, tokens)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    def place_params(params):
        return jax.device_put(params, param_shard)

    return init_fn, step_fn, batch_shard, place_params
