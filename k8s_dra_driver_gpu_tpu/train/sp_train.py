"""Sequence-parallel (long-context) Llama training step.

TPU-first manual-SPMD: the whole train step runs inside shard_map over a
(dp, sp) mesh. The sequence dimension is sharded over "sp"; attention is
ring attention (K/V chunks rotating over ICI neighbors via ppermute) or
Ulysses (two all-to-alls re-sharding seq<->heads), both from
``parallel/``. Everything else (norms, MLPs, rope with GLOBAL position
offsets) is local to the shard; gradients are pmean-ed over (dp, sp), so
the update is identical on every device and parameters stay replicated.

This is the analog of the reference's long-context surface (SURVEY §5:
the reference has none in-tree; its workloads bring their own). The
graft gate (dryrun_multichip) runs one step of this on the virtual mesh
so a regression in the sp sharding contract fails the driver check.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compat import shard_map

from ..models import llama
from ..parallel.mesh import DATA_AXIS, SEQUENCE_AXIS
from ..parallel.ring_attention import ring_attention
from ..parallel.ulysses import ulysses_attention
from .train import TrainState, make_optimizer

ATTN_IMPLS = {
    "ring": ring_attention,
    "ulysses": ulysses_attention,
}


def make_sp_train(
    mesh: Mesh,
    cfg: llama.LlamaConfig,
    attn: str = "ring",
    optimizer: optax.GradientTransformation | None = None,
    dp_axis: str = DATA_AXIS,
    sp_axis: str = SEQUENCE_AXIS,
):
    """Returns (init_fn, step_fn, batch_sharding, place_params).

    Tokens are [B, n_sp * S_local + 1] (the +1 supplies the next-token
    target for the last local position of the final shard): sharded over
    ``dp_axis`` on batch, replicated over ``sp_axis`` -- each device
    slices its own sequence chunk by axis index, so no host-side seq
    splitting is needed. Parameters are replicated; sp communication
    happens inside the attention core only.
    """
    if attn not in ATTN_IMPLS:
        raise ValueError(f"attn must be one of {sorted(ATTN_IMPLS)}")
    attn_core = partial(ATTN_IMPLS[attn], axis_name=sp_axis, causal=True)
    optimizer = optimizer or make_optimizer()
    n_sp = mesh.shape[sp_axis]

    token_spec = P(dp_axis, None)
    batch_shard = NamedSharding(mesh, token_spec)
    repl = NamedSharding(mesh, P())

    def local_loss(params, tokens):
        """Loss of the local (batch-shard, seq-shard) block."""
        sp_i = jax.lax.axis_index(sp_axis)
        s_local = (tokens.shape[1] - 1) // n_sp
        inputs = jax.lax.dynamic_slice_in_dim(
            tokens, sp_i * s_local, s_local, axis=1)
        targets = jax.lax.dynamic_slice_in_dim(
            tokens, sp_i * s_local + 1, s_local, axis=1)
        positions = sp_i * s_local + jnp.arange(s_local)[None, :]
        logits = llama.forward(
            params, inputs, cfg, attn_fn=attn_core, positions=positions)
        losses = optax.softmax_cross_entropy_with_integer_labels(
            logits, targets)
        return losses.mean()

    def local_step(state: TrainState, tokens):
        loss, grads = jax.value_and_grad(local_loss)(state.params, tokens)
        # Equal shard sizes: the mean of local grads IS the grad of the
        # global mean loss. After pmean the update is device-invariant.
        grads = jax.lax.pmean(grads, (dp_axis, sp_axis))
        loss = jax.lax.pmean(loss, (dp_axis, sp_axis))
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    @jax.jit
    def init_fn(params):
        return TrainState(
            params=params,
            opt_state=optimizer.init(params),
            step=jnp.zeros((), jnp.int32),
        )

    @partial(jax.jit, donate_argnums=(0,))
    def step_fn(state, tokens):
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(P(), token_spec),
            out_specs=(P(), P()),
            check_vma=False,  # replicated-update invariance argued above
        )(state, tokens)

    def place_params(params):
        return jax.device_put(params, repl)

    return init_fn, step_fn, batch_shard, place_params
