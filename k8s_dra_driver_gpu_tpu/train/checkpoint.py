"""Training checkpoint/resume via orbax.

The reference's "checkpoint/resume" is driver-state only (SURVEY.md §5);
the workload side of this framework adds model/optimizer checkpointing
so a gang-scheduled training job survives slice preemption: save on a
cadence, restore on restart, sharding-preserving (orbax restores each
leaf with its original NamedSharding when a mesh is supplied).
"""

from __future__ import annotations

import os

import jax
import orbax.checkpoint as ocp

from .train import TrainState


class TrainCheckpointer:
    def __init__(self, directory: str, max_to_keep: int = 3):
        self._mngr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state: TrainState, wait: bool = True) -> None:
        self._mngr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mngr.wait_until_finished()

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def restore(self, state_like: TrainState, step: int | None = None) -> TrainState:
        """Restore into the structure/shardings of ``state_like`` (an
        abstract or concrete TrainState from make_sharded_train)."""
        step = step if step is not None else self._mngr.latest_step()
        if step is None:
            raise FileNotFoundError("no checkpoint to restore")
        abstract = jax.tree_util.tree_map(
            ocp.utils.to_shape_dtype_struct, state_like
        )
        return self._mngr.restore(
            step, args=ocp.args.StandardRestore(abstract)
        )

    def close(self) -> None:
        self._mngr.close()
