"""Training launcher: consumes the env contract the DRA driver injects.

This is the workload side of the whole pipeline: a pod whose claim was
prepared by tpu.dra.dev (+ a ComputeDomain channel for multi-host) runs

    python -m k8s_dra_driver_gpu_tpu.train.main --model tiny --steps 100

and the launcher wires everything from the injected environment:
  TPU_COORDINATOR_ADDRESS / TPU_PROCESS_ID / TPU_NUM_PROCESSES
      -> jax.distributed.initialize (multi-host gangs; absent = single
         process)
  TPU_TOPOLOGY / TPU_VISIBLE_DEVICES -> mesh planning
  CHECKPOINT_DIR -> orbax save/restore (resume after preemption)

North star (BASELINE.json): a 32-chip ResourceClaim runs Llama-3-8B
training on a v5p slice with no GPU in the loop.
"""

from __future__ import annotations

import argparse
import logging
import os
import time

logger = logging.getLogger(__name__)


class GangEnvError(ValueError):
    """The injected ComputeDomain gang env is inconsistent.

    Raised BEFORE touching jax.distributed: every one of these
    misconfigurations would otherwise surface as a hang (a gang member
    waiting for peers that never come) or a silently wrong mesh.
    """


def validate_gang_env(env=os.environ) -> dict | None:
    """Check the injected env contract; None when not in a gang.

    Returns {"coordinator", "process_id", "num_processes"} when the
    pod carries a ComputeDomain channel. The contract (injected by the
    CD plugin, plugin/device_state.py:_prepare_channel):
      - TPU_COORDINATOR_ADDRESS implies TPU_PROCESS_ID and
        TPU_NUM_PROCESSES (a partial contract means a broken prepare,
        not a single-process run -- fail loudly, don't guess),
      - TPU_WORKER_HOSTNAMES, when present, is positional by process
        id, so its length must equal TPU_NUM_PROCESSES,
      - 0 <= process_id < num_processes.
    """
    coordinator = env.get("TPU_COORDINATOR_ADDRESS", "")
    if not coordinator:
        return None
    missing = [k for k in ("TPU_PROCESS_ID", "TPU_NUM_PROCESSES")
               if not env.get(k)]
    if missing:
        raise GangEnvError(
            f"TPU_COORDINATOR_ADDRESS is set but {', '.join(missing)} "
            "missing: the ComputeDomain channel env is partial (broken "
            "prepare?); refusing to guess single-process defaults")
    try:
        process_id = int(env["TPU_PROCESS_ID"])
        num_processes = int(env["TPU_NUM_PROCESSES"])
    except ValueError as e:
        raise GangEnvError(f"non-integer gang env: {e}") from e
    if not 0 <= process_id < num_processes:
        raise GangEnvError(
            f"TPU_PROCESS_ID={process_id} out of range for "
            f"TPU_NUM_PROCESSES={num_processes}")
    hostnames = env.get("TPU_WORKER_HOSTNAMES", "")
    if hostnames:
        n = len(hostnames.split(","))
        if n != num_processes:
            raise GangEnvError(
                f"TPU_WORKER_HOSTNAMES lists {n} worker(s) but "
                f"TPU_NUM_PROCESSES={num_processes}; the list is "
                "positional by process id and must match exactly")
    # rpartition: the host may be a bracketed IPv6 literal
    # ("[fd00::1]:8476") -- only the LAST colon separates the port.
    host, _, port = coordinator.rpartition(":")
    if not host or not port.isdigit():
        raise GangEnvError(
            f"TPU_COORDINATOR_ADDRESS={coordinator!r} is not host:port")
    return {
        "coordinator": coordinator,
        "process_id": process_id,
        "num_processes": num_processes,
    }


def initialize_distributed(env=os.environ) -> bool:
    """jax.distributed from the ComputeDomain channel env, if present.

    Returns True when a gang was joined. TPU_INIT_TIMEOUT_S bounds the
    rendezvous (default jax's 300 s) so an unreachable coordinator is a
    clear error, not an indefinite hang.
    """
    import jax

    gang = validate_gang_env(env)
    if gang is None:
        return False
    # A gang on the CPU backend (CI / the mock e2e tier) needs the gloo
    # cross-process collectives; without them every psum dies with
    # "Multiprocess computations aren't implemented on the CPU
    # backend". Must be set before initialize(). Best-effort: jaxlibs
    # without gloo keep the old behavior.
    plats = str(getattr(jax.config, "jax_platforms", "") or "")
    if "cpu" in plats.split(","):
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except (AttributeError, ValueError):
            pass
    timeout = int(env.get("TPU_INIT_TIMEOUT_S", "300"))
    jax.distributed.initialize(
        coordinator_address=gang["coordinator"],
        num_processes=gang["num_processes"],
        process_id=gang["process_id"],
        initialization_timeout=timeout,
    )
    logger.info(
        "joined gang: process %s/%s via %s",
        gang["process_id"], gang["num_processes"], gang["coordinator"],
    )
    return True


def run(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="tpu-train")
    p.add_argument("--model",
                   choices=["tiny", "flagship", "llama3-8b", "moe-tiny"],
                   default="tiny")
    p.add_argument("--mu-dtype", choices=["f32", "bf16"], default=None,
                   help="Adam first-moment dtype; bf16 frees one "
                        "2-bytes/param buffer (the flagship single-chip "
                        "default -- see models.llama.LlamaConfig."
                        "flagship)")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--checkpoint-dir",
                   default=os.environ.get("CHECKPOINT_DIR", ""))
    p.add_argument("--checkpoint-every", type=int, default=100)
    p.add_argument("--tp", type=int, default=None,
                   help="tensor-parallel size (default: planned)")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline-parallel stages (GPipe schedule over a "
                        "(pp, dp) mesh; layers must divide evenly)")
    p.add_argument("--microbatches", type=int, default=None,
                   help="microbatches per optimizer step in pp mode "
                        "(default: pp; more microbatches shrink the "
                        "pipeline bubble)")
    p.add_argument("--data-file", default=os.environ.get("DATA_FILE", ""),
                   help="flat binary token file; synthetic data when "
                        "unset [DATA_FILE]")
    p.add_argument("--data-dtype", default=os.environ.get(
                       "DATA_DTYPE", "uint16"),
                   choices=["uint16", "uint32", "int32"],
                   help="token file dtype (llama3 vocab 128k needs "
                        "uint32) [DATA_DTYPE]")
    p.add_argument("--profile-dir",
                   default=os.environ.get("PROFILE_DIR", ""),
                   help="capture a jax.profiler trace (XLA/TPU timeline) "
                        "of steps 2..4 into this dir")
    p.add_argument("--steps-per-call", type=int,
                   default=int(os.environ.get("STEPS_PER_CALL", "1")),
                   help="optimizer steps per compiled dispatch "
                        "(lax.scan pipeline; amortizes host round-trips "
                        "-- see train.scanned_train_step) [STEPS_PER_CALL]")
    args = p.parse_args(argv)
    if args.steps_per_call < 1:
        p.error("--steps-per-call must be >= 1")
    if args.pp < 1:
        p.error("--pp must be >= 1")
    if args.pp > 1 and args.steps_per_call > 1:
        p.error("--steps-per-call composes with the auto-sharded trainer "
                "only; in pp mode the microbatch scan already amortizes "
                "dispatch (use --microbatches)")
    if args.pp > 1 and args.tp and args.tp != 1:
        p.error("--tp and --pp are mutually exclusive (the pp trainer "
                "runs over a (pp, dp) mesh)")
    if args.microbatches is not None:
        if args.pp == 1:
            p.error("--microbatches requires --pp > 1")
        if args.microbatches < 1:
            p.error("--microbatches must be >= 1")
    if args.mu_dtype and args.model == "moe-tiny":
        p.error("--mu-dtype applies to the dense families only "
                "(the MoE trainer builds its own optimizer)")
    if args.model == "flagship" and args.seq_len % 128:
        p.error("--seq-len must be a multiple of 128 for the flagship "
                "config (its chunked loss scans 128-position chunks)")
    # Multi-host pp is supported: pp_batch_for assembles the GLOBAL
    # microbatch stream identically on every process (the pp axis
    # replicates the batch, so replicas must agree bitwise -- see the
    # comment there). Stage-to-host mapping follows device order: each
    # process's devices form whole pp rows when pp >= process count.
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )

    initialize_distributed()

    import jax
    import jax.numpy as jnp

    from ..models import llama
    from ..parallel.mesh import build_mesh, plan_for
    from .train import make_sharded_train

    devices = jax.devices()
    logger.info("devices: %d x %s", len(devices), devices[0].platform)

    def dense_cfg():
        if args.model == "tiny":
            return llama.LlamaConfig.tiny()
        if args.model == "flagship":
            return llama.LlamaConfig.flagship()
        return llama.LlamaConfig.llama3_8b()

    # The flagship single-chip recipe defaults to the bf16 first
    # moment; every other config keeps fp32 unless asked.
    mu = args.mu_dtype or ("bf16" if args.model == "flagship" else "f32")
    optimizer = None
    if mu == "bf16":
        from .train import make_optimizer  # noqa: PLC0415

        optimizer = make_optimizer(mu_dtype=jnp.bfloat16)

    if args.model == "moe-tiny":
        # Expert-parallel family: a (dp, ep) mesh; ep takes as many
        # devices as divide both the device count and the expert count.
        import numpy as np  # noqa: PLC0415

        from ..models import llama_moe  # noqa: PLC0415
        from jax.sharding import Mesh  # noqa: PLC0415

        if args.tp and args.tp != 1:
            p.error("--tp applies to the dense families only; "
                    "--model moe-tiny uses a (dp, ep) mesh")
        if args.steps_per_call > 1:
            p.error("--steps-per-call applies to the dense families "
                    "only (the MoE trainer is manual-SPMD)")
        if args.pp > 1:
            p.error("--pp applies to the dense families only")
        cfg = llama_moe.LlamaMoEConfig.tiny()
        ep = min(len(devices), cfg.n_experts)
        while ep > 1 and (len(devices) % ep or cfg.n_experts % ep):
            ep -= 1
        dp = len(devices) // ep
        if args.batch_size % dp:
            p.error(f"--batch-size {args.batch_size} must be divisible "
                    f"by dp={dp} ({len(devices)} devices / ep={ep})")
        mesh = Mesh(np.asarray(devices[:dp * ep]).reshape(dp, ep),
                    ("dp", "ep"))
        logger.info("mesh: %s", dict(zip(mesh.axis_names,
                                         mesh.devices.shape)))
        init_fn, step_fn, batch_shard, place = llama_moe.make_moe_train(
            mesh, cfg)
        scan_fn = scan_batch_shard = None
        pp_m = 0
        state = init_fn(place(llama_moe.init(jax.random.PRNGKey(0), cfg)))
    elif args.pp > 1:
        from ..parallel.mesh import build_pipeline_mesh  # noqa: PLC0415
        from .pp_train import make_pp_train  # noqa: PLC0415

        cfg = dense_cfg()
        if len(devices) % args.pp:
            p.error(f"--pp {args.pp} does not divide "
                    f"{len(devices)} devices")
        if cfg.n_layers % args.pp:
            p.error(f"--pp {args.pp} does not divide "
                    f"{cfg.n_layers} layers")
        pp_m = (args.microbatches if args.microbatches is not None
                else args.pp)
        dp = len(devices) // args.pp
        gang_n = int(os.environ.get("TPU_NUM_PROCESSES", "1"))
        if (args.batch_size * gang_n) % dp:
            p.error(f"global batch {args.batch_size}x{gang_n} must be "
                    f"divisible by dp={dp} "
                    f"({len(devices)} devices / pp={args.pp})")
        mesh = build_pipeline_mesh(args.pp, devices=devices)
        logger.info("mesh: %s microbatches=%d",
                    dict(zip(mesh.axis_names, mesh.devices.shape)), pp_m)
        init_fn, step_fn, batch_shard, place = make_pp_train(
            mesh, cfg, n_microbatches=pp_m, optimizer=optimizer)
        scan_fn = scan_batch_shard = None
        state = init_fn(place(llama.init(jax.random.PRNGKey(0), cfg)))
    else:
        mesh = build_mesh(plan_for(len(devices), tp=args.tp),
                          devices=devices)
        logger.info("mesh: %s", dict(zip(mesh.axis_names,
                                         mesh.devices.shape)))
        cfg = dense_cfg()
        init_fn, step_fn, batch_shard, place = make_sharded_train(
            mesh, cfg, optimizer=optimizer)
        scan_fn = scan_batch_shard = None
        pp_m = 0
        if args.steps_per_call > 1:
            from .train import make_scanned_sharded_train  # noqa: PLC0415

            _, scan_fn, scan_batch_shard, _ = make_scanned_sharded_train(
                mesh, cfg, optimizer=optimizer)
        state = init_fn(place(llama.init(jax.random.PRNGKey(0), cfg)))

    ckpt = None
    if args.checkpoint_dir:
        from .checkpoint import TrainCheckpointer  # noqa: PLC0415

        ckpt = TrainCheckpointer(args.checkpoint_dir)
        if ckpt.latest_step() is not None:
            state = ckpt.restore(state)
            logger.info("resumed from step %d", int(state.step))

    # --batch-size is PER PROCESS in both modes; the global batch is
    # batch_size * TPU_NUM_PROCESSES, so synthetic-vs-real comparisons
    # use identical compiled shapes and throughput accounting.
    num_shards = int(os.environ.get("TPU_NUM_PROCESSES", "1"))
    shard_id = int(os.environ.get("TPU_PROCESS_ID", "0"))
    global_batch = args.batch_size * num_shards
    if args.data_file:
        # Host-sharded deterministic loading keyed by the injected gang
        # env; batch(step) is pure, so checkpoint resume replays exactly.
        from ..data.loader import ShardedBatchIterator, TokenDataset  # noqa: PLC0415

        ds = TokenDataset(args.data_file, args.seq_len,
                          dtype=args.data_dtype)
        it = ShardedBatchIterator(ds, global_batch=global_batch)
        # Out-of-vocab ids anywhere in the file would silently NaN the
        # loss (out-of-bounds embedding gather); fail loudly instead.
        # The scan result is sidecar-cached so preemption resumes don't
        # re-read huge files.
        file_max = ds.max_token()
        if file_max >= cfg.vocab_size:
            raise SystemExit(
                f"--data-file contains token id {file_max} >= model "
                f"vocab {cfg.vocab_size}; retokenize, fix --data-dtype, "
                "or pick the right --model"
            )

        # Sibling iterators (pp-replica feeding, below) are built once
        # per shard id, not per step: batch() is pure, so every process
        # reconstructs identical rows from the cached iterator.
        siblings = {shard_id: it}

        def shard_batch(step: int, sid: int):
            other = siblings.get(sid)
            if other is None:
                other = siblings[sid] = ShardedBatchIterator(
                    ds, global_batch=global_batch,
                    num_shards=num_shards, shard_id=sid)
            return other.batch(step)

        def local_batch(step: int):
            return it.batch(step)
    else:
        # Synthetic next-token data: each process draws ITS shard's
        # slice (keyed by step and shard) so global semantics match the
        # data path exactly.
        def shard_batch(step: int, sid: int):
            import numpy as _np  # noqa: PLC0415

            rng = _np.random.RandomState(step * 65521 + sid)
            return rng.randint(
                0, cfg.vocab_size,
                (args.batch_size, args.seq_len + 1),
            ).astype(_np.int32)

        def local_batch(step: int):
            return shard_batch(step, shard_id)

    def batch_for(step: int):
        # Each process supplies ONLY its local shard; device_put's
        # same-on-all-hosts semantics would drop 1-1/N of every shard
        # on multi-host gangs.
        return jax.make_array_from_process_local_data(
            batch_shard, local_batch(step)
        )

    start_step = int(state.step)
    t0 = time.perf_counter()
    # Global tokens per step (all gang members), matching both modes;
    # a pp optimizer step consumes M microbatches of the global batch.
    tokens_per_step = global_batch * args.seq_len * (pp_m or 1)
    tracing = False

    def scan_batch_for(step: int, k: int):
        import numpy as _np  # noqa: PLC0415

        stacked = _np.stack([local_batch(step + i) for i in range(k)])
        return jax.make_array_from_process_local_data(
            scan_batch_shard, stacked)

    def pp_batch_for(step: int):
        # M distinct microbatches per optimizer step, deterministically
        # keyed so resume replays the same stream.
        #
        # The pp batch REPLICATES over the pp axis (token spec
        # P(None, dp, None)), so on a multi-host gang every process
        # must supply bitwise-identical microbatch content for the dp
        # columns its devices cover -- a process-id-keyed local slice
        # would make the pp replicas silently disagree (wrong grads).
        # So the GLOBAL batch is assembled on every process (same
        # shard-keyed rows, concatenated in shard order) and
        # make_array_from_callback hands each device its slice.
        import numpy as _np  # noqa: PLC0415

        stacked = _np.stack([
            _np.concatenate([shard_batch(step * pp_m + i, s)
                             for s in range(num_shards)])
            for i in range(pp_m)
        ])
        return jax.make_array_from_callback(
            stacked.shape, batch_shard, lambda idx: stacked[idx])

    step = start_step
    first_timed = None  # first step boundary after the compile call
    profiled = False  # the trace runs once, around steps ~2..4
    while step < args.steps:
        prev = step
        if (args.profile_dir and step >= start_step + 1
                and not tracing and not profiled):
            jax.profiler.start_trace(args.profile_dir)
            tracing = True
            profiled = True
        # Scan path: K full steps per dispatch while they fit; the tail
        # (and the per-step path) use the unscanned step_fn. Step
        # semantics are identical -- same batches per step, same order.
        k = args.steps_per_call
        if pp_m:
            state, loss = step_fn(state, pp_batch_for(step))
            step += 1
        elif scan_fn is not None and step + k <= args.steps:
            state, losses = scan_fn(state, scan_batch_for(step, k))
            loss = losses[-1]
            step += k
        else:
            state, loss = step_fn(state, batch_for(step))
            step += 1
        if tracing and step >= start_step + 3:
            jax.block_until_ready(loss)
            jax.profiler.stop_trace()
            tracing = False
            logger.info("profile trace written to %s", args.profile_dir)
        if first_timed is None:
            jax.block_until_ready(loss)  # exclude compile from timing
            t0 = time.perf_counter()
            first_timed = step
        if prev // 10 != step // 10 or step == args.steps:
            jax.block_until_ready(loss)
            dt = time.perf_counter() - t0
            done = step - first_timed
            tps = tokens_per_step * done / dt if dt > 0 and done > 0 else 0.0
            logger.info("step %d loss %.4f (%.0f tok/s)",
                        step, float(loss), tps)
        if ckpt and (prev // args.checkpoint_every
                     != step // args.checkpoint_every):
            ckpt.save(step, state)
    if tracing:
        # Short runs: close the trace before exit so it's usable.
        jax.block_until_ready(state.step)
        jax.profiler.stop_trace()
        logger.info("profile trace written to %s", args.profile_dir)
    if ckpt:
        ckpt.save(int(state.step), state)
        ckpt.close()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(run())
