"""Gang verification workload: prove the injected env runs a real job.

    python -m k8s_dra_driver_gpu_tpu.train.verify --require-gang

Reference analog: tests/bats/test_cd_mnnvl_workload.bats:18-52 -- the
reference proves its ComputeDomain stack by running a real NCCL
allreduce over the prepared IMEX domain from inside workload pods. The
TPU equivalent is jax.distributed: each gang member initializes ONLY
from the CDI-injected channel env (TPU_COORDINATOR_ADDRESS /
TPU_PROCESS_ID / TPU_NUM_PROCESSES), forms the global device mesh,
executes cross-process collectives and one real sharded train step,
and prints ONE JSON line so a harness (or operator) can compare the
results across pods:

  - ``devSum``  : psum of 1 per device == global device count -- every
                  device participated;
  - ``rankSum`` : psum of (process id + 1) per device -- data from
                  EVERY process crossed the collective (a gang that
                  silently degraded to one process gets this wrong);
  - ``loss``    : the loss after ``--steps`` real sharded train steps
                  on the tiny model -- identical on every pod iff the
                  gang executed one coherent global computation.

On TPU pods the backend is the real chips; ``--local-devices N``
forces an N-device CPU backend per process (the fake-cluster e2e and
the multi-process dry run use 4 x 2 processes = an 8-device global
mesh on one machine).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .main import initialize_distributed


def run(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="tpu-train-verify")
    p.add_argument("--local-devices", type=int, default=0,
                   help="force an N-device CPU backend for this process "
                        "(0 = use the real backend)")
    p.add_argument("--steps", type=int, default=1,
                   help="sharded train steps to run after the psum proof")
    p.add_argument("--batch-per-process", type=int, default=4)
    p.add_argument("--seq-len", type=int, default=16)
    p.add_argument("--require-gang", action="store_true",
                   help="fail unless the ComputeDomain channel env is "
                        "present (the e2e contract check)")
    args = p.parse_args(argv)
    if args.steps < 1:
        p.error("--steps must be >= 1 (the train-step proof is the "
                "point)")

    import jax

    if args.local_devices > 0:
        from ..compat import force_cpu_devices

        # Must precede any JAX backend initialization.
        force_cpu_devices(args.local_devices)

    joined = initialize_distributed()
    if args.require_gang and not joined:
        print("verify: no ComputeDomain channel env "
              "(TPU_COORDINATOR_ADDRESS unset) but --require-gang",
              file=sys.stderr)
        return 2

    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ..models import llama
    from ..parallel.mesh import (
        build_mesh,
        build_multislice_mesh,
        plan_for,
    )
    from .train import make_sharded_train

    devices = jax.devices()
    n = len(devices)
    local = len(jax.local_devices())
    pid = jax.process_index()

    # Cross-slice domain: the injected MEGASCALE-style env declares the
    # slice layout; the global mesh leads with a DCN axis over slices
    # (parallel/mesh.build_multislice_mesh), exactly the multislice
    # recipe -- driven here ONLY by what the driver injected.
    num_slices = int(os.environ.get("TPU_NUM_SLICES", "1"))
    if num_slices > 1:
        if n % num_slices:
            raise SystemExit(
                f"TPU_NUM_SLICES={num_slices} does not divide "
                f"{n} global devices")
        mesh = build_multislice_mesh(
            num_slices, plan_for(n // num_slices), devices=devices)
        batch_axes = ("dcn", "dp", "fsdp")
    else:
        mesh = build_mesh(plan_for(n), devices=devices)
        batch_axes = None

    # -- collective proof: every device AND every process contributed --
    flat = NamedSharding(mesh, P(mesh.axis_names))
    repl = NamedSharding(mesh, P())
    ones = jax.make_array_from_process_local_data(
        flat, jnp.ones((local,), jnp.float32))
    ranks = jax.make_array_from_process_local_data(
        flat, jnp.full((local,), pid + 1, jnp.float32))
    total = jax.jit(jnp.sum, out_shardings=repl)
    dev_sum = float(total(ones))
    rank_sum = float(total(ranks))

    # -- one real sharded training computation over the gang mesh ------
    cfg = llama.LlamaConfig.tiny()
    if batch_axes is not None:
        init_fn, step_fn, batch_shard, place = make_sharded_train(
            mesh, cfg, batch_axes=batch_axes)
    else:
        init_fn, step_fn, batch_shard, place = make_sharded_train(
            mesh, cfg)
    state = init_fn(place(llama.init(jax.random.PRNGKey(0), cfg)))
    loss = None
    for step in range(args.steps):
        import numpy as np

        rng = np.random.RandomState(step * 65521 + pid)
        local_rows = rng.randint(
            0, cfg.vocab_size,
            (args.batch_per_process, args.seq_len + 1)).astype(np.int32)
        tokens = jax.make_array_from_process_local_data(
            batch_shard, local_rows)
        state, loss = step_fn(state, tokens)
    jax.block_until_ready(loss)

    print(json.dumps({
        "processId": pid,
        "numProcesses": jax.process_count(),
        "globalDevices": n,
        "localDevices": local,
        "devSum": dev_sum,
        "rankSum": rank_sum,
        "steps": int(state.step),
        # Full repr: pods must agree BITWISE (one global computation).
        "loss": repr(float(loss)),
        "gang": joined,
        "numSlices": num_slices,
        "sliceId": int(os.environ.get("TPU_SLICE_ID", "0")),
        "mesh": dict(zip(mesh.axis_names,
                         (int(s) for s in mesh.devices.shape))),
        "env": {
            k: os.environ.get(k, "")
            for k in ("TPU_COORDINATOR_ADDRESS", "TPU_PROCESS_ID",
                      "TPU_NUM_PROCESSES", "TPU_WORKER_HOSTNAMES",
                      "TPU_DOMAIN_CHANNELS", "COMPUTE_DOMAIN_UUID",
                      "MEGASCALE_COORDINATOR_ADDRESS",
                      "MEGASCALE_NUM_SLICES", "MEGASCALE_SLICE_ID")
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(run())
