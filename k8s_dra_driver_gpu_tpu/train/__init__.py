"""Training loop: sharded train step over a ComputeDomain's mesh."""
