"""Sharded Llama training step.

TPU-first: one jitted SPMD step over a Mesh; parameters/optimizer state
sharded by the model's PartitionSpecs (fsdp/tp), batch sharded over
(dp, fsdp); XLA inserts the gradient all-reduces/reduce-scatters on ICI.
The optimizer state is initialized INSIDE jit so Adam moments inherit the
parameter shardings without hand-written placement rules.
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import llama


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jax.Array


def make_optimizer(lr: float = 3e-4,
                   mu_dtype=None) -> optax.GradientTransformation:
    """AdamW with global-norm clipping.

    ``mu_dtype=jnp.bfloat16`` stores the FIRST moment in bf16 (the
    second moment and master params stay fp32) -- a standard large-model
    memory trade that frees one 2-bytes/param buffer; on a 16 GB chip
    it is what lets the ~0.8B flagship config train at batch sizes past
    the HBM cliff (docs/benchmarks.md flagship section).
    """
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=0.1,
                    mu_dtype=mu_dtype),
    )


def loss_fn(params, tokens, cfg: llama.LlamaConfig) -> jax.Array:
    """Next-token cross-entropy over [B, S] token ids.

    cfg.loss_chunk > 0 switches to the chunked loss (ops/xent.py): the
    [B, S, V] logits never materialize, which is what lets flagship
    (~1B-param) configs train on a 16 GB chip -- see
    docs/benchmarks.md.
    """
    targets = tokens[:, 1:]
    if cfg.loss_chunk:
        from ..ops.xent import chunked_cross_entropy  # noqa: PLC0415

        hidden = llama.forward_hidden(params, tokens[:, :-1], cfg)
        return chunked_cross_entropy(
            hidden, params["lm_head"], targets, chunk=cfg.loss_chunk)
    logits = llama.forward(params, tokens[:, :-1], cfg)
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    return losses.mean()


def train_step(state: TrainState, tokens, *, cfg, optimizer):
    loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens, cfg)
    updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return TrainState(params, opt_state, state.step + 1), loss


def scanned_train_step(state: TrainState, tokens_kbs, *, cfg, optimizer):
    """K optimizer steps per dispatch: ``tokens_kbs`` is [K, B, S+1] and
    the K steps run under one ``lax.scan`` inside one compiled call,
    returning all K losses.

    TPU-first dispatch shape: one XLA program per macro-batch instead of
    one per step keeps the chip busy between host visits -- on a
    tunneled/remote chip this is the difference between 0.26 and 0.42+
    MFU (docs/benchmarks.md), and on local hardware it still removes
    K-1 dispatch/sync gaps per macro-batch. The loop stays
    compiler-friendly: scan compiles the body ONCE regardless of K."""
    def body(st, tokens):
        return train_step(st, tokens, cfg=cfg, optimizer=optimizer)

    return jax.lax.scan(body, state, tokens_kbs)


def make_sharded_train(mesh: Mesh, cfg: llama.LlamaConfig, optimizer=None,
                       batch_axes: tuple[str, ...] | None = None):
    """Returns (init_fn, step_fn, batch_sharding) jitted over ``mesh``.

    init_fn(params_on_host) -> TrainState placed/sharded on the mesh.
    step_fn(state, tokens) -> (state, loss), donated input state.

    ``batch_axes`` overrides the mesh axes the batch dim shards over --
    a multislice mesh passes ("dcn", "dp", "fsdp") so pure gradient data
    parallelism (and only it) crosses the data-center network while
    params stay replicated across slices; XLA then inserts the
    cross-slice gradient all-reduce on DCN and everything else on ICI.
    """
    optimizer = optimizer or make_optimizer()
    cfg = llama.pin_auto_attn_for_pjit(cfg, mesh)
    specs = llama.param_specs(cfg)
    param_shard = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    batch_spec = (P(batch_axes, None) if batch_axes is not None
                  else llama.batch_spec())
    batch_shard = NamedSharding(mesh, batch_spec)

    @partial(jax.jit, in_shardings=(param_shard,))
    def init_fn(params):
        return TrainState(
            params=params,
            opt_state=optimizer.init(params),
            step=jnp.zeros((), jnp.int32),
        )

    step_fn = jax.jit(
        partial(train_step, cfg=cfg, optimizer=optimizer),
        donate_argnums=(0,),
    )

    def place_params(params):
        return jax.device_put(params, param_shard)

    return init_fn, step_fn, batch_shard, place_params


def make_scanned_sharded_train(mesh: Mesh, cfg: llama.LlamaConfig,
                               optimizer=None,
                               batch_axes: tuple[str, ...] | None = None):
    """``make_sharded_train`` with K steps per dispatch (see
    ``scanned_train_step``). step_fn(state, tokens[K, B, S+1]) ->
    (state, losses[K]); the leading scan dim is unsharded (K is just the
    input's leading extent), the per-step batch shards exactly as in the
    unscanned path."""
    optimizer = optimizer or make_optimizer()
    cfg = llama.pin_auto_attn_for_pjit(cfg, mesh)
    init_fn, _, batch_shard, place_params = make_sharded_train(
        mesh, cfg, optimizer=optimizer, batch_axes=batch_axes)
    spec = batch_shard.spec
    scan_batch_shard = NamedSharding(mesh, P(None, *spec))
    step_fn = jax.jit(
        partial(scanned_train_step, cfg=cfg, optimizer=optimizer),
        donate_argnums=(0,),
    )
    return init_fn, step_fn, scan_batch_shard, place_params
