"""Attention ops, GQA-aware, causal, MXU-friendly.

The einsum formulation below is the portable baseline XLA fuses well on
TPU; a pallas flash-attention kernel is the drop-in upgrade path behind
the same signature.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


# Sequence length at which "auto" switches from einsum to the pallas
# flash kernel. Measured on v5e (docs/benchmarks.md flagship A/B, 738M
# config, training step fully synced): with bf16 MXU matmuls and the
# pallas backward (round 5), flash wins from S=1024 up -- 0.492 vs
# 0.449 at S=1024, 0.519 vs 0.330 at S=2048/B=8, 0.465 at S=4096 where
# einsum's O(B*H*S^2) fp32 score transient cannot even compile on a
# 16 GB chip. XLA's fused einsum still edges it at S=512 (0.525 vs
# 0.518), so the crossover sits at 1024.
FLASH_MIN_SEQ = 1024


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    impl: str = "auto",
) -> jax.Array:
    """Dispatch: pallas flash attention on TPU long-context shapes,
    einsum elsewhere.

    impl: "auto" | "flash" | "einsum".
    """
    if impl == "auto":
        from . import is_tpu_backend  # noqa: PLC0415

        # The pallas kernel wants MXU/VPU-aligned head dims (lane =
        # 128); small-head models (tests, toy configs) take einsum.
        # Aligned heads still take einsum below FLASH_MIN_SEQ -- the
        # measured crossover, not an assumption.
        impl = (
            "flash"
            if is_tpu_backend() and q.shape[-1] % 128 == 0
            and q.shape[1] >= FLASH_MIN_SEQ
            else "einsum"
        )
    if impl == "flash":
        from .flash_attention import flash_attention  # noqa: PLC0415

        return flash_attention(q, k, v, causal=causal)
    if impl != "einsum":
        # A typo ("Flash", "pallas") must not silently take the einsum
        # path -- at long S that materializes the O(S^2) scores the
        # flash kernel exists to avoid.
        raise ValueError(f"unknown attention impl {impl!r}: "
                         "want auto | flash | einsum")
    return dot_product_attention(q, k, v, causal=causal)


def dot_product_attention(
    q: jax.Array,  # [B, S, H, hd]
    k: jax.Array,  # [B, S, K, hd]
    v: jax.Array,  # [B, S, K, hd]
    causal: bool = True,
) -> jax.Array:
    """GQA attention: q-heads H grouped over kv-heads K (H % K == 0).

    Softmax runs in fp32; the two matmuls stay in the input dtype so they
    hit the MXU in bf16.
    """
    B, S, H, hd = q.shape
    K = k.shape[2]
    group = H // K
    qg = q.reshape(B, S, K, group, hd)

    scores = jnp.einsum("bqkgh,bskh->bkgqs", qg, k) / jnp.sqrt(
        jnp.asarray(hd, jnp.float32)
    ).astype(q.dtype)
    scores = scores.astype(jnp.float32)
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        scores = jnp.where(mask, scores, -jnp.inf)
    weights = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgqs,bskh->bqkgh", weights, v)
    return out.reshape(B, S, H, hd)
