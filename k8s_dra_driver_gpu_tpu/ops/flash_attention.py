"""Pallas flash attention for TPU: blocked online-softmax, causal, GQA,
with a pallas backward (flash-style dq/dk/dv from saved output + lse).

The MXU-friendly formulation: q blocks of (block_q, head_dim) stream
against the full K/V of their (batch, kv-head) pair held in VMEM; the
softmax runs online (running max + normalizer) in fp32 scratch while the
two matmuls stay in the INPUT dtype (bf16 on the training path --
fp32xfp32 runs the MXU at a fraction of bf16 throughput). Causal masking
skips whole k-blocks past the diagonal. GQA is expressed in the
BlockSpec index maps (q-head h reads kv-head h // group) -- no
materialized KV repetition.

The backward recomputes probabilities from the saved logsumexp (never
the full S x S tensor in HBM): a dq kernel walks k-blocks per q-block,
a dk/dv kernel walks q-blocks per k-block producing per-q-head partials
that are group-summed outside (group is small: 2 on the flagship).
``bwd_impl="chunked"`` keeps the einsum-recompute fallback.

Falls back to interpret mode off-TPU so the same code path runs in CPU
tests (mirroring the mock-backend strategy of the driver side).
Measured on v5e (docs/benchmarks.md): the einsum path is HBM-bound at
long S (it materializes the S x S scores); this kernel is the
long-context enabler and, from S >= 2048, also the faster forward.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401 - TPU lowering

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref=None, *,
                  block_k: int, causal: bool, sm_scale: float,
                  kv_len: int):
    """One (batch*head, q-block) program instance.

    q_ref: [1, block_q, hd]; k_ref/v_ref: [1, S_padded, hd] (padded to a
    block_k multiple; kv_len is the true length); o_ref like q_ref;
    lse_ref: [1, block_q, 1] logsumexp residual for the backward --
    absent on the forward-only (pure inference) variant, whose
    pallas_call declares a single output and so passes no lse ref.
    """
    _, block_q, hd = q_ref.shape
    seq_len = k_ref.shape[1]
    qi = pl.program_id(1)
    q_start = qi * block_q

    q = q_ref[0]

    def body(ki, carry):
        o_acc, m_prev, l_prev = carry
        k_start = ki * block_k
        k = k_ref[0, pl.ds(k_start, block_k), :]
        v = v_ref[0, pl.ds(k_start, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # [block_q, block_k] fp32
        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        # Padding keys never contribute.
        valid = k_pos < kv_len
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = jnp.logical_and(valid, q_pos >= k_pos)
        s = jnp.where(valid, s, NEG_INF)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        o_new = o_acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return o_new, m_new, l_new

    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        # Blocks strictly past the diagonal contribute nothing.
        num_k_blocks = jnp.minimum(
            num_k_blocks, pl.cdiv(q_start + block_q, block_k)
        )

    o_acc = jnp.zeros((block_q, hd), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    o_acc, m, l = jax.lax.fori_loop(0, num_k_blocks, body, (o_acc, m0, l0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (o_acc / l_safe).astype(o_ref.dtype)
    if lse_ref is not None:
        lse_ref[0] = m + jnp.log(l_safe)


def _flash_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref,
                     dq_ref, *, block_k: int, causal: bool,
                     sm_scale: float, kv_len: int):
    """dq for one (batch*head, q-block): walk k-blocks, probabilities
    rebuilt from the saved lse. dS = P * (dP - D); dq = scale * dS K."""
    _, block_q, hd = q_ref.shape
    seq_len = k_ref.shape[1]
    qi = pl.program_id(1)
    q_start = qi * block_q

    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]  # [block_q, 1] fp32
    dsum = dsum_ref[0]  # [block_q, 1] fp32

    def body(ki, dq_acc):
        k_start = ki * block_k
        k = k_ref[0, pl.ds(k_start, block_k), :]
        v = v_ref[0, pl.ds(k_start, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_pos < kv_len
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = jnp.logical_and(valid, q_pos >= k_pos)
        s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - dsum)
        return dq_acc + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        num_k_blocks = jnp.minimum(
            num_k_blocks, pl.cdiv(q_start + block_q, block_k)
        )
    dq = jax.lax.fori_loop(
        0, num_k_blocks, body, jnp.zeros((block_q, hd), jnp.float32)
    )
    dq_ref[0] = (dq * sm_scale).astype(dq_ref.dtype)


def _flash_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref,
                      dk_ref, dv_ref, *, block_q: int, causal: bool,
                      sm_scale: float, kv_len: int):
    """dk/dv partials for one (batch*q-head, k-block): walk q-blocks
    from the diagonal down. Per-Q-HEAD partials -- the GQA group sum
    happens outside the kernel (group is small)."""
    _, block_k, hd = k_ref.shape
    seq_len = q_ref.shape[1]
    ki = pl.program_id(1)
    k_start = ki * block_k

    k = k_ref[0]
    v = v_ref[0]

    def body(qi, carry):
        dk_acc, dv_acc = carry
        q_start = qi * block_q
        q = q_ref[0, pl.ds(q_start, block_q), :]
        do = do_ref[0, pl.ds(q_start, block_q), :]
        lse = lse_ref[0, pl.ds(q_start, block_q), :]
        dsum = dsum_ref[0, pl.ds(q_start, block_q), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # [block_q, block_k]
        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_pos < kv_len
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = jnp.logical_and(valid, q_pos >= k_pos)
        s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - lse)
        pc = p.astype(do.dtype)
        dv_acc = dv_acc + jax.lax.dot_general(
            pc, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_k, hd]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - dsum)).astype(q.dtype)
        dk_acc = dk_acc + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk_acc, dv_acc

    num_q_blocks = pl.cdiv(seq_len, block_q)
    # Causal: q blocks strictly above the diagonal see none of this
    # k block.
    first_q_block = (k_start // block_q) if causal else 0
    dk, dv = jax.lax.fori_loop(
        first_q_block, num_q_blocks, body,
        (jnp.zeros((block_k, hd), jnp.float32),
         jnp.zeros((block_k, hd), jnp.float32)),
    )
    dk_ref[0] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret",
                     "bwd_impl"),
)
def flash_attention(
    q: jax.Array,  # [B, S, H, hd]
    k: jax.Array,  # [B, S, K, hd]
    v: jax.Array,  # [B, S, K, hd]
    causal: bool = True,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool | None = None,
    bwd_impl: str = "flash",
) -> jax.Array:
    """Differentiable: forward AND backward run pallas kernels (the
    backward rebuilds probabilities from the saved logsumexp -- O(S)
    residuals, never the S x S score tensor). bwd_impl="chunked" uses
    the einsum-recompute fallback (_chunked_attention_bwd)."""
    return _flash_attention_vjp(q, k, v, causal, block_q, block_k,
                                interpret, bwd_impl)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention_vjp(q, k, v, causal, block_q, block_k, interpret,
                         bwd_impl):
    # Primal (never-differentiated) path: pallas_call outputs are not
    # dead-code-eliminated, so the forward-only variant declares NO lse
    # output -- pure-inference callers skip the [B*H, S_qpad, 1] fp32
    # HBM write the vjp forward pays for its backward residual.
    out, _ = _flash_attention_fwd_impl(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret, with_lse=False,
    )
    return out


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k, interpret, bwd_impl):
    out, lse = _flash_attention_fwd_impl(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    if bwd_impl == "chunked":
        # The chunked backward recomputes from (q, k, v) alone; keeping
        # out/lse alive would make the memory-fallback path heavier.
        return out, (q, k, v, None, None)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, block_q, block_k, interpret, bwd_impl,
                   residuals, g):
    q, k, v, out, lse = residuals
    if bwd_impl == "chunked":
        return _chunked_attention_bwd(q, k, v, g, causal=causal,
                                      block_q=block_q)
    return _flash_attention_bwd_impl(
        q, k, v, out, lse, g, causal=causal, block_q=block_q,
        block_k=block_k, interpret=interpret,
    )


def _chunked_attention_bwd(q, k, v, g, *, causal: bool, block_q: int):
    """Einsum-recompute backward: attention one q-chunk at a time
    (lax.scan), so peak transient memory is O(block_q * S) per layer --
    never the full S x S score tensor.

    Standard softmax-attention gradients:
      P = softmax(S'),  S' = scale * Q K^T
      dV = P^T dO
      dP = dO V^T
      dS' = P * (dP - rowsum(dP * P))
      dQ = scale * dS' K,   dK = scale * dS'^T Q
    """
    B, S, H, hd = q.shape
    K = k.shape[2]
    group = H // K
    scale = 1.0 / (hd ** 0.5)
    C = min(block_q, S)
    n_chunks = -(-S // C)
    S_pad = n_chunks * C

    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    if S_pad != S:
        pad = ((0, 0), (0, S_pad - S), (0, 0), (0, 0))
        qf, gf = jnp.pad(qf, pad), jnp.pad(gf, pad)

    # [n_chunks, B, C, H, hd] chunked views of q and dO.
    qc_all = qf.reshape(B, n_chunks, C, H, hd).swapaxes(0, 1)
    gc_all = gf.reshape(B, n_chunks, C, H, hd).swapaxes(0, 1)
    k_pos = jnp.arange(S)

    def chunk(carry, inputs):
        dk_acc, dv_acc = carry
        ci, qc, gc = inputs  # qc/gc: [B, C, H, hd]
        q_pos = ci * C + jnp.arange(C)
        qg = qc.reshape(B, C, K, group, hd)
        gg = gc.reshape(B, C, K, group, hd)
        s = jnp.einsum("bqkgh,bskh->bkgqs", qg, kf) * scale
        valid = (q_pos[:, None] < S) & (
            (q_pos[:, None] >= k_pos[None, :]) if causal
            else jnp.ones((C, S), bool)
        )
        s = jnp.where(valid[None, None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        dv_acc = dv_acc + jnp.einsum("bkgqs,bqkgh->bskh", p, gg)
        dp = jnp.einsum("bqkgh,bskh->bkgqs", gg, vf)
        ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
        dq_c = jnp.einsum("bkgqs,bskh->bqkgh", ds, kf) * scale
        dk_acc = dk_acc + jnp.einsum("bkgqs,bqkgh->bskh", ds, qg) * scale
        return (dk_acc, dv_acc), dq_c.reshape(B, C, H, hd)

    (dk, dv), dq_chunks = jax.lax.scan(
        chunk,
        (jnp.zeros_like(kf), jnp.zeros_like(vf)),
        (jnp.arange(n_chunks), qc_all, gc_all),
    )
    dq = dq_chunks.swapaxes(0, 1).reshape(B, S_pad, H, hd)[:, :S]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash_attention_vjp.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _head_major(x: jax.Array) -> jax.Array:
    """[B, S, N, hd] -> [B*N, S, hd]."""
    B, S, N, hd = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * N, S, hd)


def _flash_attention_fwd_impl(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool,
    block_q: int,
    block_k: int,
    interpret: bool | None,
    with_lse: bool = True,
) -> tuple[jax.Array, jax.Array | None]:
    """Returns (out [B,S,H,hd], lse [B*H, S_qpad, 1] fp32).

    ``with_lse=False`` is the forward-only variant: the pallas_call
    declares a single output, so the kernel never materializes (nor
    HBM-writes) the logsumexp residual only the backward needs. Same
    kernel body, bit-identical ``out``."""
    from . import is_tpu_backend  # noqa: PLC0415

    B, S, H, hd = q.shape
    K = k.shape[2]
    group = H // K
    if interpret is None:
        interpret = not is_tpu_backend()
    block_q = min(block_q, S)
    block_k = min(block_k, S)

    # Pad the kv sequence to a block_k multiple: a clamped pl.ds read on
    # a partial last block would re-read (and double-count) real keys
    # under wrong position labels. Padding keys are masked by kv_len.
    S_kpad = -(-S // block_k) * block_k

    # [B, H|K, S, hd] layout so the grid walks (batch*head, q-block).
    qt = _head_major(q)
    kt = _head_major(k)
    vt = _head_major(v)
    if S_kpad != S:
        pad = ((0, 0), (0, S_kpad - S), (0, 0))
        kt = jnp.pad(kt, pad)
        vt = jnp.pad(vt, pad)

    grid = (B * H, pl.cdiv(S, block_q))

    def q_index(bh, qi):
        return (bh, qi, 0)

    def kv_index(bh, qi):
        # GQA: q-head bh maps onto kv-head (bh % H) // group.
        b = bh // H
        h = bh % H
        return (b * K + h // group, 0, 0)

    def lse_index(bh, qi):
        return (bh, qi, 0)

    kernel = functools.partial(
        _flash_kernel,
        block_k=block_k,
        causal=causal,
        sm_scale=1.0 / (hd ** 0.5),
        kv_len=S,
    )
    in_specs = [
        pl.BlockSpec((1, block_q, hd), q_index),
        pl.BlockSpec((1, S_kpad, hd), kv_index),
        pl.BlockSpec((1, S_kpad, hd), kv_index),
    ]
    if not with_lse:
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((B * H, S, hd), q.dtype),
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, block_q, hd), q_index),
            interpret=interpret,
        )(qt, kt, vt)
        return out.reshape(B, H, S, hd).transpose(0, 2, 1, 3), None
    out, lse = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, hd), q.dtype),
            jax.ShapeDtypeStruct((B * H, -(-S // block_q) * block_q, 1),
                                 jnp.float32),
        ],
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, hd), q_index),
            pl.BlockSpec((1, block_q, 1), lse_index),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out.reshape(B, H, S, hd).transpose(0, 2, 1, 3), lse


def _flash_attention_bwd_impl(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    out: jax.Array,
    lse: jax.Array,  # [B*H, S_qpad, 1] fp32 from the forward
    g: jax.Array,
    *,
    causal: bool,
    block_q: int,
    block_k: int,
    interpret: bool | None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    from . import is_tpu_backend  # noqa: PLC0415

    B, S, H, hd = q.shape
    K = k.shape[2]
    group = H // K
    if interpret is None:
        interpret = not is_tpu_backend()
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    sm_scale = 1.0 / (hd ** 0.5)

    # One padded length serves both walk directions (the dkv kernel
    # slides q-blocks over the padded q stream, the dq kernel slides
    # k-blocks over the padded kv stream). dO pads with ZEROS, so
    # padded q rows contribute nothing to dk/dv regardless of their
    # (masked) probabilities; padded k columns are masked by kv_len.
    import math  # noqa: PLC0415

    S_pad = -(-S // math.lcm(block_q, block_k)) * math.lcm(block_q, block_k)

    def padq(x):  # [B*H, S, hd] -> [B*H, S_pad, hd]
        return jnp.pad(x, ((0, 0), (0, S_pad - x.shape[1]), (0, 0)))

    qt = padq(_head_major(q))
    dot_ = padq(_head_major(g))
    ot = padq(_head_major(out))
    kt = padq(_head_major(k))
    vt = padq(_head_major(v))
    # lse is [B*H, S_qpad, 1]; rows >= S are kernel output over
    # UNDEFINED padded q rows (can be NaN) -- force them to 0. With
    # zero-padded q/dO, p = exp(0 - 0) = 1 there, and every padded-row
    # contribution is p * dO_pad = 0 / sliced off, so 0 is safe.
    row = jnp.arange(lse.shape[1])[None, :, None]
    lse = jnp.where(row < S, lse, 0.0)
    lse_p = jnp.pad(lse, ((0, 0), (0, S_pad - lse.shape[1]), (0, 0)))
    # D = rowsum(dO * O) fp32 -- cheap elementwise, XLA fuses it.
    dsum = jnp.sum(dot_.astype(jnp.float32) * ot.astype(jnp.float32),
                   axis=-1, keepdims=True)

    def q_index(bh, i):
        return (bh, i, 0)

    def full_index(bh, i):
        return (bh, 0, 0)

    def kv_index(bh, i):
        b = bh // H
        h = bh % H
        return (b * K + h // group, 0, 0)

    n_qb = S_pad // block_q
    n_kb = S_pad // block_k

    dq = pl.pallas_call(
        functools.partial(
            _flash_dq_kernel, block_k=block_k, causal=causal,
            sm_scale=sm_scale, kv_len=S,
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, S_pad, hd), q.dtype),
        grid=(B * H, n_qb),
        in_specs=[
            pl.BlockSpec((1, block_q, hd), q_index),
            pl.BlockSpec((1, S_pad, hd), kv_index),
            pl.BlockSpec((1, S_pad, hd), kv_index),
            pl.BlockSpec((1, block_q, hd), q_index),
            pl.BlockSpec((1, block_q, 1), q_index),
            pl.BlockSpec((1, block_q, 1), q_index),
        ],
        out_specs=pl.BlockSpec((1, block_q, hd), q_index),
        interpret=interpret,
    )(qt, kt, vt, dot_, lse_p, dsum)

    def kblock_index(bh, i):
        b = bh // H
        h = bh % H
        return (b * K + h // group, i, 0)

    dkp, dvp = pl.pallas_call(
        functools.partial(
            _flash_dkv_kernel, block_q=block_q, causal=causal,
            sm_scale=sm_scale, kv_len=S,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S_pad, hd), jnp.float32),
            jax.ShapeDtypeStruct((B * H, S_pad, hd), jnp.float32),
        ],
        grid=(B * H, n_kb),
        in_specs=[
            pl.BlockSpec((1, S_pad, hd), full_index),
            pl.BlockSpec((1, block_k, hd), kblock_index),
            pl.BlockSpec((1, block_k, hd), kblock_index),
            pl.BlockSpec((1, S_pad, hd), full_index),
            pl.BlockSpec((1, S_pad, 1), full_index),
            pl.BlockSpec((1, S_pad, 1), full_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, hd), q_index),
            pl.BlockSpec((1, block_k, hd), q_index),
        ],
        interpret=interpret,
    )(qt, kt, vt, dot_, lse_p, dsum)

    # GQA group-sum of the per-q-head partials (group is 1-2 on the
    # model families here; the transient is group x the kv size).
    dk = dkp.reshape(B, K, group, S_pad, hd).sum(2)[:, :, :S]
    dv = dvp.reshape(B, K, group, S_pad, hd).sum(2)[:, :, :S]
    dq_out = dq.reshape(B, H, S_pad, hd)[:, :, :S].transpose(0, 2, 1, 3)
    return (
        dq_out.astype(q.dtype),
        dk.transpose(0, 2, 1, 3).astype(k.dtype),
        dv.transpose(0, 2, 1, 3).astype(v.dtype),
    )
