"""Pallas flash attention for TPU: blocked online-softmax, causal, GQA.

The MXU-friendly formulation: q blocks of (block_q, head_dim) stream
against the full K/V of their (batch, kv-head) pair held in VMEM; the
softmax runs online (running max + normalizer) in fp32 scratch while the
two matmuls stay in the input dtype. Causal masking skips whole k-blocks
past the diagonal. GQA is expressed in the BlockSpec index maps (q-head
h reads kv-head h // group) -- no materialized KV repetition.

Falls back to interpret mode off-TPU so the same code path runs in CPU
tests (mirroring the mock-backend strategy of the driver side).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  sm_scale: float, kv_len: int):
    """One (batch*head, q-block) program instance.

    q_ref: [1, block_q, hd]; k_ref/v_ref: [1, S_padded, hd] (padded to a
    block_k multiple; kv_len is the true length); o_ref like q_ref.
    """
    _, block_q, hd = q_ref.shape
    seq_len = k_ref.shape[1]
    qi = pl.program_id(1)
    q_start = qi * block_q

    q = q_ref[0].astype(jnp.float32) * sm_scale

    def body(ki, carry):
        o_acc, m_prev, l_prev = carry
        k_start = ki * block_k
        k = k_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        # Padding keys never contribute.
        valid = k_pos < kv_len
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = jnp.logical_and(valid, q_pos >= k_pos)
        s = jnp.where(valid, s, NEG_INF)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        o_new = o_acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return o_new, m_new, l_new

    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        # Blocks strictly past the diagonal contribute nothing.
        num_k_blocks = jnp.minimum(
            num_k_blocks, pl.cdiv(q_start + block_q, block_k)
        )

    o_acc = jnp.zeros((block_q, hd), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    o_acc, _, l = jax.lax.fori_loop(0, num_k_blocks, body, (o_acc, m0, l0))
    o_ref[0] = (o_acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def flash_attention(
    q: jax.Array,  # [B, S, H, hd]
    k: jax.Array,  # [B, S, K, hd]
    v: jax.Array,  # [B, S, K, hd]
    causal: bool = True,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool | None = None,
) -> jax.Array:
    """Differentiable: the forward runs the pallas kernel; the backward
    recomputes attention one q-chunk at a time under lax.scan
    (_chunked_attention_bwd) -- O(block_q * S) transient memory, never
    the full S x S score tensor, and no residuals beyond (q, k, v)."""
    return _flash_attention_vjp(q, k, v, causal, block_q, block_k, interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_vjp(q, k, v, causal, block_q, block_k, interpret):
    return _flash_attention_fwd_impl(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k, interpret):
    out = _flash_attention_fwd_impl(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return out, (q, k, v)


def _flash_vjp_bwd(causal, block_q, block_k, interpret, residuals, g):
    del block_k, interpret
    q, k, v = residuals
    return _chunked_attention_bwd(q, k, v, g, causal=causal,
                                  block_q=block_q)


def _chunked_attention_bwd(q, k, v, g, *, causal: bool, block_q: int):
    """Flash-style backward: recompute attention one q-chunk at a time
    (lax.scan), so peak transient memory is O(block_q * S) per layer --
    never the full S x S score tensor.

    Standard softmax-attention gradients:
      P = softmax(S'),  S' = scale * Q K^T
      dV = P^T dO
      dP = dO V^T
      dS' = P * (dP - rowsum(dP * P))
      dQ = scale * dS' K,   dK = scale * dS'^T Q
    """
    B, S, H, hd = q.shape
    K = k.shape[2]
    group = H // K
    scale = 1.0 / (hd ** 0.5)
    C = min(block_q, S)
    n_chunks = -(-S // C)
    S_pad = n_chunks * C

    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    if S_pad != S:
        pad = ((0, 0), (0, S_pad - S), (0, 0), (0, 0))
        qf, gf = jnp.pad(qf, pad), jnp.pad(gf, pad)

    # [n_chunks, B, C, H, hd] chunked views of q and dO.
    qc_all = qf.reshape(B, n_chunks, C, H, hd).swapaxes(0, 1)
    gc_all = gf.reshape(B, n_chunks, C, H, hd).swapaxes(0, 1)
    k_pos = jnp.arange(S)

    def chunk(carry, inputs):
        dk_acc, dv_acc = carry
        ci, qc, gc = inputs  # qc/gc: [B, C, H, hd]
        q_pos = ci * C + jnp.arange(C)
        qg = qc.reshape(B, C, K, group, hd)
        gg = gc.reshape(B, C, K, group, hd)
        s = jnp.einsum("bqkgh,bskh->bkgqs", qg, kf) * scale
        valid = (q_pos[:, None] < S) & (
            (q_pos[:, None] >= k_pos[None, :]) if causal
            else jnp.ones((C, S), bool)
        )
        s = jnp.where(valid[None, None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        dv_acc = dv_acc + jnp.einsum("bkgqs,bqkgh->bskh", p, gg)
        dp = jnp.einsum("bqkgh,bskh->bkgqs", gg, vf)
        ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
        dq_c = jnp.einsum("bkgqs,bskh->bqkgh", ds, kf) * scale
        dk_acc = dk_acc + jnp.einsum("bkgqs,bqkgh->bskh", ds, qg) * scale
        return (dk_acc, dv_acc), dq_c.reshape(B, C, H, hd)

    (dk, dv), dq_chunks = jax.lax.scan(
        chunk,
        (jnp.zeros_like(kf), jnp.zeros_like(vf)),
        (jnp.arange(n_chunks), qc_all, gc_all),
    )
    dq = dq_chunks.swapaxes(0, 1).reshape(B, S_pad, H, hd)[:, :S]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash_attention_vjp.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _flash_attention_fwd_impl(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool,
    block_q: int,
    block_k: int,
    interpret: bool | None,
) -> jax.Array:
    from . import is_tpu_backend  # noqa: PLC0415

    B, S, H, hd = q.shape
    K = k.shape[2]
    group = H // K
    if interpret is None:
        interpret = not is_tpu_backend()
    block_q = min(block_q, S)
    block_k = min(block_k, S)

    # Pad the kv sequence to a block_k multiple: a clamped pl.ds read on
    # a partial last block would re-read (and double-count) real keys
    # under wrong position labels. Padding keys are masked by kv_len.
    S_pad = -(-S // block_k) * block_k

    # [B, H|K, S, hd] layout so the grid walks (batch*head, q-block).
    qt = q.transpose(0, 2, 1, 3).reshape(B * H, S, hd)
    kt = k.transpose(0, 2, 1, 3).reshape(B * K, S, hd)
    vt = v.transpose(0, 2, 1, 3).reshape(B * K, S, hd)
    if S_pad != S:
        pad = ((0, 0), (0, S_pad - S), (0, 0))
        kt = jnp.pad(kt, pad)
        vt = jnp.pad(vt, pad)

    grid = (B * H, pl.cdiv(S, block_q))

    def q_index(bh, qi):
        return (bh, qi, 0)

    def kv_index(bh, qi):
        # GQA: q-head bh maps onto kv-head (bh % H) // group.
        b = bh // H
        h = bh % H
        return (b * K + h // group, 0, 0)

    out = pl.pallas_call(
        functools.partial(
            _flash_kernel,
            block_k=block_k,
            causal=causal,
            sm_scale=1.0 / (hd ** 0.5),
            kv_len=S,
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, S, hd), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, hd), q_index),
            pl.BlockSpec((1, S_pad, hd), kv_index),
            pl.BlockSpec((1, S_pad, hd), kv_index),
        ],
        out_specs=pl.BlockSpec((1, block_q, hd), q_index),
        interpret=interpret,
    )(qt, kt, vt)
    return out.reshape(B, H, S, hd).transpose(0, 2, 1, 3)
