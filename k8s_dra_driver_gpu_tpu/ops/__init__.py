"""TPU compute ops: attention, collectives, (pallas kernels as they land)."""
