"""TPU compute ops: attention, collectives, pallas kernels."""

TPU_BACKENDS = ("tpu", "axon")


def is_tpu_backend() -> bool:
    import jax  # noqa: PLC0415

    return jax.default_backend() in TPU_BACKENDS
