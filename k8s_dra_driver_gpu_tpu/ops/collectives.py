"""Collective microbenchmark ops over a device mesh.

The reference proves its prepared fabric with external nvbandwidth/NCCL
jobs asserting bandwidth output (tests/bats/test_cd_mnnvl_workload.bats);
this module is the in-tree JAX analog: an all-reduce (psum) benchmark over
the ComputeDomain's ICI mesh, reporting achieved GB/s.
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compat import shard_map as _shard_map


def allreduce_fn(mesh: Mesh, axis: str):
    """A jitted psum over ``axis`` of ``mesh`` for [N] fp32 buffers."""

    @partial(
        jax.jit,
        in_shardings=NamedSharding(mesh, P()),
        out_shardings=NamedSharding(mesh, P()),
    )
    def _psum(x):
        return _shard_map(
            lambda v: jax.lax.psum(v, axis),
            mesh=mesh,
            in_specs=P(),
            out_specs=P(),
        )(x)

    return _psum


def bench_allreduce(
    mesh: Mesh,
    axis: str,
    nbytes: int = 64 << 20,
    iters: int = 10,
) -> dict:
    """Time all-reduce of an nbytes fp32 buffer; returns achieved GB/s.

    Algorithmic bytes moved per device for a ring all-reduce of size S
    over n participants: 2*S*(n-1)/n.
    """
    n = mesh.shape[axis]
    x = jnp.ones((nbytes // 4,), jnp.float32)
    x = jax.device_put(x, NamedSharding(mesh, P()))
    fn = allreduce_fn(mesh, axis)
    fn(x).block_until_ready()  # compile + warm up
    start = time.perf_counter()
    for _ in range(iters):
        x = fn(x)
    x.block_until_ready()
    elapsed = time.perf_counter() - start
    algo_bytes = 2 * nbytes * (n - 1) / max(n, 1)
    return {
        "participants": n,
        "bytes": nbytes,
        "iters": iters,
        "seconds": elapsed,
        "gbps": (algo_bytes * iters / elapsed) / 1e9 if elapsed > 0 else 0.0,
    }
