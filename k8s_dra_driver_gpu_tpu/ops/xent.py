"""Chunked next-token cross-entropy: the logits never materialize.

The standard dense loss computes logits ``[B, S, V]`` in fp32 before
the softmax -- at flagship shapes that one buffer is the largest
allocation of the whole training step (B=32, S=1024, V=32k -> 4.3 GB)
and the reason a ~1B-param model cannot fit a 16 GB chip next to its
fp32 Adam state. TPU-first fix: scan the sequence in chunks, compute
each chunk's logits, reduce them to per-token losses immediately, and
``jax.checkpoint`` the chunk body so the backward pass RECOMPUTES the
chunk logits instead of saving them. Peak logits memory drops from
``B*S*V`` to ``B*chunk*V`` (128x smaller at chunk=8 on S=1024) for one
extra lm_head matmul per chunk in the backward -- the classic
flash-attention trade applied to the loss layer.

The gradient w.r.t. ``lm_head`` accumulates across chunks inside the
transposed scan; numerics match the dense loss to fp32 reduction
order.

No reference counterpart (the reference ships no training loss); this
is framework-native perf work, measured in docs/benchmarks.md.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def chunked_cross_entropy(
    hidden: jax.Array,
    lm_head: jax.Array,
    targets: jax.Array,
    *,
    chunk: int,
) -> jax.Array:
    """Mean cross-entropy of ``hidden @ lm_head`` against ``targets``.

    hidden:  [B, S, D] final (normed) hidden states, compute dtype.
    lm_head: [D, V] master weights (cast to hidden dtype for the
             matmul, logits accumulate in fp32 -- identical to the
             dense path's ``(x @ lm_head).astype(f32)``).
    targets: [B, S] int token ids.
    chunk:   sequence positions per scanned chunk; must divide S.
    """
    B, S, D = hidden.shape
    if S % chunk:
        raise ValueError(f"loss chunk {chunk} does not divide S={S}")
    n = S // chunk
    w = lm_head.astype(hidden.dtype)
    # [n, B, C, D] / [n, B, C] chunked views, scanned in order.
    hc = hidden.reshape(B, n, chunk, D).swapaxes(0, 1)
    tc = targets.reshape(B, n, chunk).swapaxes(0, 1)

    def body(acc, xt):
        xch, tch = xt
        logits = (xch @ w).astype(jnp.float32)  # [B, C, V]
        logz = jax.scipy.special.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(
            logits, tch[..., None], axis=-1)[..., 0]
        return acc + (logz - picked).sum(), None

    # checkpoint: the backward recomputes each chunk's logits; only the
    # scalar carry and the [n,B,C,D] inputs (already live) are kept.
    total, _ = jax.lax.scan(
        jax.checkpoint(body), jnp.zeros((), jnp.float32), (hc, tc))
    return total / (B * S)
