"""Llama-3 in TPU-first JAX: functional, scan-over-layers, bfloat16.

Design (not a torch port):
- Parameters are a plain pytree with per-leaf PartitionSpecs (fsdp/tp
  sharding per the scaling-book recipe); XLA inserts the collectives.
- Layers are STACKED and iterated with lax.scan: one traced layer body,
  O(1) compile time in depth, and jax.checkpoint (remat) on the body
  trades FLOPs for HBM.
- Matmuls stay large and bf16 so XLA tiles them onto the MXU; attention
  uses a fused softmax formulation with a causal mask computed inside the
  kernel-friendly einsum path (pallas flash-attention swaps in via
  ops.attention).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.attention import attention
from ..parallel.mesh import DATA_AXIS, FSDP_AXIS, TENSOR_AXIS


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14_336
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    dtype: jnp.dtype = jnp.bfloat16
    # "auto": pallas flash attention on TPU, einsum elsewhere.
    attn_impl: str = "auto"
    # Training-loss chunking: >0 computes the cross-entropy over
    # loss_chunk-position chunks of the sequence without materializing
    # the [B, S, V] logits (ops/xent.py) -- at flagship shapes that
    # buffer dominates HBM. 0 = dense loss. Must divide the train S.
    loss_chunk: int = 0
    # Rematerialization of the layer body in the backward pass:
    # "full" recomputes everything (long sequences / big models fit
    # HBM at ~+2 forward-FLOPs per 6 counted), "dots" saves matmul
    # outputs and recomputes the cheap elementwise rest, "none" saves
    # all activations (small models: highest true MFU). Trade per
    # jax.checkpoint docs; measured on v5e in docs/benchmarks.md.
    remat: str = "full"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def flagship() -> "LlamaConfig":
        """The flagship single-chip training config: the largest
        flagship-SHAPED model (head_dim 128, 2:1 GQA, SwiGLU ratio 3)
        that trains on one 16 GB v5e chip with a bf16 first moment
        (fp32 second moment and master params) --
        738M params, 12 layers, d_model 2048. Chunked loss (the
        [B,S,V] logits never materialize) is what makes it fit at the
        MFU-optimal batch; pair with
        ``make_optimizer(mu_dtype=jnp.bfloat16)``. Tuned point and
        sweep: docs/benchmarks.md flagship section."""
        return LlamaConfig(
            vocab_size=32_768,
            d_model=2048,
            n_layers=12,
            n_heads=16,
            n_kv_heads=8,
            d_ff=6144,
            loss_chunk=128,
        )

    @staticmethod
    def tiny() -> "LlamaConfig":
        """Test/dryrun config: same structure, toy sizes."""
        return LlamaConfig(
            vocab_size=256,
            d_model=64,
            n_layers=2,
            n_heads=4,
            n_kv_heads=2,
            d_ff=128,
        )


def pin_auto_attn_for_pjit(cfg: LlamaConfig, mesh) -> LlamaConfig:
    """attn_impl auto -> einsum when jitting over a MULTI-device mesh:
    a pallas_call inside jit with sharded operands does not partition
    (XLA gathers the full arrays per device), silently destroying the
    sharding at exactly the long-S shapes where auto picks the kernel.
    Sharded long-context belongs to the shard_map trainers (ring /
    Ulysses see local shapes). Single-device meshes keep auto -- there
    the kernel IS the long-context enabler (0.465 MFU at S=4096 where
    einsum cannot compile, docs/benchmarks.md) -- and an EXPLICIT
    attn_impl="flash" is always honored as the caller's choice."""
    if cfg.attn_impl == "auto" and mesh.size > 1:
        import dataclasses  # noqa: PLC0415

        return dataclasses.replace(cfg, attn_impl="einsum")
    return cfg


def param_specs(cfg: LlamaConfig) -> dict:
    """PartitionSpecs per parameter leaf (layer-stacked leaves lead with
    None for the scan dimension). fsdp shards the long matmul dim, tp the
    head/ff dim."""
    del cfg
    return {
        "embed": P(TENSOR_AXIS, FSDP_AXIS),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, FSDP_AXIS, TENSOR_AXIS),
            "wk": P(None, FSDP_AXIS, TENSOR_AXIS),
            "wv": P(None, FSDP_AXIS, TENSOR_AXIS),
            "wo": P(None, TENSOR_AXIS, FSDP_AXIS),
            "mlp_norm": P(None, None),
            "w_gate": P(None, FSDP_AXIS, TENSOR_AXIS),
            "w_up": P(None, FSDP_AXIS, TENSOR_AXIS),
            "w_down": P(None, TENSOR_AXIS, FSDP_AXIS),
        },
        "final_norm": P(None),
        "lm_head": P(FSDP_AXIS, TENSOR_AXIS),
    }


def batch_spec() -> P:
    return P((DATA_AXIS, FSDP_AXIS), None)


def init(key: jax.Array, cfg: LlamaConfig) -> dict:
    """Initialize parameters (fp32 master weights; cast at use)."""
    k = iter(jax.random.split(key, 16))
    d, h, kv, hd, f = (
        cfg.d_model, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.d_ff,
    )
    L = cfg.n_layers

    def dense(key, shape):
        fan_in = shape[-2] if len(shape) >= 2 else shape[-1]
        return jax.random.normal(key, shape, jnp.float32) / jnp.sqrt(fan_in)

    return {
        "embed": dense(next(k), (cfg.vocab_size, d)),
        "layers": {
            "attn_norm": jnp.ones((L, d), jnp.float32),
            "wq": dense(next(k), (L, d, h * hd)),
            "wk": dense(next(k), (L, d, kv * hd)),
            "wv": dense(next(k), (L, d, kv * hd)),
            "wo": dense(next(k), (L, h * hd, d)),
            "mlp_norm": jnp.ones((L, d), jnp.float32),
            "w_gate": dense(next(k), (L, d, f)),
            "w_up": dense(next(k), (L, d, f)),
            "w_down": dense(next(k), (L, f, d)),
        },
        "final_norm": jnp.ones((d,), jnp.float32),
        "lm_head": dense(next(k), (d, cfg.vocab_size)),
    }


def rms_norm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    # Normalize in fp32 for stability, cast back to the compute dtype.
    xf = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * rms).astype(x.dtype) * scale.astype(x.dtype)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embeddings over the last dim of [..., S, H, hd]."""
    hd = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, hd // 2, dtype=jnp.float32) / (hd // 2))
    angles = positions[..., :, None].astype(jnp.float32) * freqs  # [.., S, hd/2]
    cos = jnp.cos(angles)[..., :, None, :]  # broadcast over heads
    sin = jnp.sin(angles)[..., :, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)


def attention_block(cfg, x: jax.Array, p: dict, positions: jax.Array,
                    attn_fn=None) -> jax.Array:
    """rms-norm -> q/k/v -> rope -> attention -> wo residual. Shared by
    the dense and MoE model families (cfg only needs the attention
    fields: n_heads/n_kv_heads/head_dim/dtype/rope_theta/norm_eps/
    attn_impl).

    ``attn_fn(q, k, v)`` overrides the attention core -- the seam the
    sequence-parallel trainer uses to swap in ring/Ulysses attention
    (which communicate over the sp axis inside shard_map).
    """
    dt = cfg.dtype
    B, S, _ = x.shape
    h, kv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    a = rms_norm(x, p["attn_norm"], cfg.norm_eps)
    q = (a @ p["wq"].astype(dt)).reshape(B, S, h, hd)
    k = (a @ p["wk"].astype(dt)).reshape(B, S, kv, hd)
    v = (a @ p["wv"].astype(dt)).reshape(B, S, kv, hd)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    if attn_fn is not None:
        attn = attn_fn(q, k, v)
    else:
        attn = attention(q, k, v, causal=True, impl=cfg.attn_impl)
    return x + attn.reshape(B, S, h * hd) @ p["wo"].astype(dt)


def _layer(cfg: LlamaConfig, x: jax.Array, layer_params: dict,
           positions: jax.Array, attn_fn=None) -> jax.Array:
    """One transformer block: [B, S, D] -> [B, S, D]."""
    p = layer_params
    dt = cfg.dtype
    x = attention_block(cfg, x, p, positions, attn_fn)

    # SwiGLU MLP.
    m = rms_norm(x, p["mlp_norm"], cfg.norm_eps)
    gate = jax.nn.silu(m @ p["w_gate"].astype(dt))
    up = m @ p["w_up"].astype(dt)
    x = x + (gate * up) @ p["w_down"].astype(dt)
    return x


def apply_remat(body, remat: str):
    """Wrap a scan body per the cfg.remat policy (see LlamaConfig.remat)."""
    if remat == "full":
        return jax.checkpoint(body)
    if remat == "dots":
        return jax.checkpoint(body, policy=jax.checkpoint_policies.dots_saveable)
    if remat == "none":
        return body
    raise ValueError(f"unknown remat policy {remat!r}")


def forward_hidden(params: dict, tokens: jax.Array, cfg: LlamaConfig,
                   attn_fn=None,
                   positions: jax.Array | None = None) -> jax.Array:
    """Token ids [B, S] -> final-normed hidden states [B, S, D].

    The lm_head projection is split out so the training loss can run
    it CHUNKED (ops/xent.chunked_cross_entropy) without ever
    materializing [B, S, V] logits; ``forward`` composes the two for
    callers that want dense logits.

    ``positions`` overrides the rope positions ([1, S] or [B, S]) -- a
    sequence-parallel caller passes each shard's GLOBAL offsets so rope
    stays consistent across the sp ring.
    """
    # Sharding comes from the in_shardings on params/tokens; XLA propagates
    # (dp,fsdp)-batch x tp-heads layouts through the whole graph.
    x = params["embed"].astype(cfg.dtype)[tokens]
    if positions is None:
        positions = jnp.arange(tokens.shape[1])[None, :]

    # Scan over stacked layers; remat policy per cfg.remat (full: long
    # sequences fit HBM; none: small models keep max true MFU).
    body = lambda carry, lp: (  # noqa: E731
        _layer(cfg, carry, lp, positions, attn_fn), None)
    x, _ = jax.lax.scan(apply_remat(body, cfg.remat), x, params["layers"])

    return rms_norm(x, params["final_norm"], cfg.norm_eps)


def forward(params: dict, tokens: jax.Array, cfg: LlamaConfig,
            attn_fn=None, positions: jax.Array | None = None) -> jax.Array:
    """Token ids [B, S] -> logits [B, S, V] (fp32 logits)."""
    x = forward_hidden(params, tokens, cfg, attn_fn, positions)
    return (x @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)
