"""MoE-Llama: the Llama architecture with a mixture-of-experts FFN.

Second model family of the workload stack (dense Llama + this): the
attention/norm/rope stack is shared with models/llama.py; every layer's
SwiGLU FFN is replaced by the dense-dispatch MoE layer (models/moe.py)
with a replicated router and expert weights shardable over an "ep"
mesh axis. The training step is manual-SPMD over a (dp, ep) mesh, the
same shape as the sequence-parallel trainer (train/sp_train.py):

- tokens are dp-sharded, ep-replicated; each device computes the FULL
  model with its LOCAL expert shard and a psum over "ep" completes
  every layer's mixture;
- gradients: expert-shard leaves are pmean'd over dp only (each ep
  shard owns its experts); replicated leaves over (dp, ep) -- so the
  optimizer update is identical wherever the parameter is replicated.

TPU-first: routing/combine in fp32, expert matmuls in bf16 on the MXU,
dense one-hot dispatch (static shapes; XLA lowers it to matmuls), remat
over the layer scan.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compat import shard_map

from ..parallel.mesh import DATA_AXIS, EXPERT_AXIS
from ..train.train import TrainState, make_optimizer
from . import llama
from .moe import moe_ffn


@dataclass(frozen=True)
class LlamaMoEConfig:
    vocab_size: int = 32_768
    d_model: int = 1024
    n_layers: int = 8
    n_heads: int = 16
    n_kv_heads: int = 8
    d_ff: int = 2048  # per expert
    n_experts: int = 8
    top_k: int = 2
    aux_coef: float = 0.01  # load-balancing loss weight
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    dtype: jnp.dtype = jnp.bfloat16
    attn_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @staticmethod
    def tiny() -> "LlamaMoEConfig":
        return LlamaMoEConfig(
            vocab_size=256, d_model=64, n_layers=2, n_heads=4,
            n_kv_heads=2, d_ff=96, n_experts=4, top_k=2,
        )

    def as_llama(self) -> llama.LlamaConfig:
        """The dense view used by the shared attention stack."""
        return llama.LlamaConfig(
            vocab_size=self.vocab_size, d_model=self.d_model,
            n_layers=self.n_layers, n_heads=self.n_heads,
            n_kv_heads=self.n_kv_heads, d_ff=self.d_ff,
            rope_theta=self.rope_theta, norm_eps=self.norm_eps,
            dtype=self.dtype, attn_impl=self.attn_impl,
        )


def init(key: jax.Array, cfg: LlamaMoEConfig) -> dict:
    k = iter(jax.random.split(key, 16))
    d, h, kv, hd = cfg.d_model, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    f, E, L = cfg.d_ff, cfg.n_experts, cfg.n_layers

    def dense(key, shape):
        fan_in = shape[-2] if len(shape) >= 2 else shape[-1]
        return jax.random.normal(key, shape, jnp.float32) / jnp.sqrt(fan_in)

    return {
        "embed": dense(next(k), (cfg.vocab_size, d)),
        "layers": {
            "attn_norm": jnp.ones((L, d), jnp.float32),
            "wq": dense(next(k), (L, d, h * hd)),
            "wk": dense(next(k), (L, d, kv * hd)),
            "wv": dense(next(k), (L, d, kv * hd)),
            "wo": dense(next(k), (L, h * hd, d)),
            "mlp_norm": jnp.ones((L, d), jnp.float32),
            "router": dense(next(k), (L, d, E)),
            "w_in": dense(next(k), (L, E, d, f)),
            "w_out": dense(next(k), (L, E, f, d)),
        },
        "final_norm": jnp.ones((d,), jnp.float32),
        "lm_head": dense(next(k), (d, cfg.vocab_size)),
    }


def param_specs(cfg: LlamaMoEConfig, ep_axis: str = EXPERT_AXIS) -> dict:
    """Expert leaves shard their E dim over the ep axis; the rest are
    replicated (the dp x ep trainer's layout)."""
    return {
        "embed": P(),
        "layers": {
            "attn_norm": P(), "wq": P(), "wk": P(), "wv": P(), "wo": P(),
            "mlp_norm": P(),
            "router": P(),
            "w_in": P(None, ep_axis, None, None),
            "w_out": P(None, ep_axis, None, None),
        },
        "final_norm": P(),
        "lm_head": P(),
    }


def forward(
    params: dict,
    tokens: jax.Array,
    cfg: LlamaMoEConfig,
    expert_offset: jax.Array | int = 0,
    attn_fn=None,
    positions: jax.Array | None = None,
    ep_axis: str = EXPERT_AXIS,
) -> tuple[jax.Array, jax.Array]:
    """Token ids [B, S] -> (logits [B, S, V] fp32, aux scalar).

    With expert-sharded weights, ``expert_offset`` marks the local
    block; each layer's mixture is then PARTIAL and the caller psums it
    over the ep axis (combine_fn hook below handles it in-layer so the
    residual stream stays correct)."""
    lcfg = cfg.as_llama()
    x = params["embed"].astype(cfg.dtype)[tokens]
    if positions is None:
        positions = jnp.arange(tokens.shape[1])[None, :]

    inside_shard_map = not isinstance(expert_offset, int)

    def body(carry, lp):
        x, aux_sum = carry
        # Attention half is identical to dense Llama: the shared block.
        x = llama.attention_block(cfg, x, lp, positions, attn_fn)

        m = llama.rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        moe_params = {"router": lp["router"], "w_in": lp["w_in"],
                      "w_out": lp["w_out"]}
        out, aux = moe_ffn(moe_params, m, top_k=cfg.top_k,
                           dtype=cfg.dtype, expert_offset=expert_offset)
        if inside_shard_map:
            # Partial mixture over the local expert block -> complete it
            # before the residual add.
            out = jax.lax.psum(out, ep_axis)
        x = x + out
        return (x, aux_sum + aux), None

    (x, aux_sum), _ = jax.lax.scan(
        jax.checkpoint(body), (x, jnp.zeros((), jnp.float32)),
        params["layers"],
    )
    x = llama.rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)
    return logits, aux_sum / cfg.n_layers


def loss_fn(params, tokens, cfg: LlamaMoEConfig,
            expert_offset: jax.Array | int = 0,
            ep_axis: str = EXPERT_AXIS) -> jax.Array:
    logits, aux = forward(params, tokens[:, :-1], cfg,
                          expert_offset=expert_offset, ep_axis=ep_axis)
    losses = optax.softmax_cross_entropy_with_integer_labels(
        logits, tokens[:, 1:])
    return losses.mean() + cfg.aux_coef * aux


def make_moe_train(
    mesh: Mesh,
    cfg: LlamaMoEConfig,
    optimizer: optax.GradientTransformation | None = None,
    dp_axis: str = DATA_AXIS,
    ep_axis: str = EXPERT_AXIS,
):
    """Returns (init_fn, step_fn, batch_sharding, place_params) for a
    (dp, ep) mesh -- manual-SPMD like train/sp_train.py."""
    optimizer = optimizer or make_optimizer()
    specs = param_specs(cfg, ep_axis)
    token_spec = P(dp_axis, None)
    batch_shard = NamedSharding(mesh, token_spec)

    # Single source of truth for which leaves are expert-sharded: their
    # exact shapes from the config (optimizer moments mirror them). A
    # rank heuristic would silently misclassify any future rank-4
    # non-expert parameter.
    expert_shapes = {
        (cfg.n_layers, cfg.n_experts, cfg.d_model, cfg.d_ff),
        (cfg.n_layers, cfg.n_experts, cfg.d_ff, cfg.d_model),
    }

    def leaf_spec(x) -> P:
        """Spec for any GLOBAL state leaf (params AND optimizer
        moments, which mirror the param shapes)."""
        if tuple(getattr(x, "shape", ())) in expert_shapes:
            return P(None, ep_axis, None, None)
        return P()

    def local_step(state: TrainState, tokens):
        e_local = state.params["layers"]["w_in"].shape[1]
        # Inside shard_map leaves carry LOCAL shapes: the expert dim is
        # already split to e_local.
        local_expert_shapes = {
            (cfg.n_layers, e_local, cfg.d_model, cfg.d_ff),
            (cfg.n_layers, e_local, cfg.d_ff, cfg.d_model),
        }

        def is_expert(g) -> bool:
            return tuple(getattr(g, "shape", ())) in local_expert_shapes

        offset = jax.lax.axis_index(ep_axis) * e_local
        n_ep = jax.lax.psum(1, ep_axis)
        loss, grads = jax.value_and_grad(loss_fn)(
            state.params, tokens, cfg, offset, ep_axis)
        # Expert shards: every ep rank computes an IDENTICAL local loss
        # (the in-layer psum replicates the mixture), so AD through that
        # psum delivers each expert block the SUM of all n_ep identical
        # cotangents -- scale by 1/n_ep, then average over dp only (each
        # ep rank owns its experts). Replicated params pmean over both
        # axes so their update is device-invariant.
        grads = jax.tree_util.tree_map(
            lambda g: (jax.lax.pmean(g, (dp_axis,)) / n_ep
                       if is_expert(g)
                       else jax.lax.pmean(g, (dp_axis, ep_axis))),
            grads,
        )
        loss = jax.lax.pmean(loss, (dp_axis, ep_axis))
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    @jax.jit
    def init_fn(params):
        return TrainState(
            params=params,
            opt_state=optimizer.init(params),
            step=jnp.zeros((), jnp.int32),
        )

    compiled: dict = {}

    def step_fn(state, tokens):
        # The optimizer-state pytree structure is optax-internal; build
        # the spec tree from the live state by exact expert-tensor
        # shapes (cached per structure) instead of hard-coding optax
        # internals.
        key = jax.tree_util.tree_structure(state)
        if key not in compiled:
            state_specs = jax.tree_util.tree_map(leaf_spec, state)
            compiled[key] = jax.jit(
                lambda s, t: shard_map(
                    local_step,
                    mesh=mesh,
                    in_specs=(state_specs, token_spec),
                    out_specs=(state_specs, P()),
                    check_vma=False,
                )(s, t),
                donate_argnums=(0,),
            )
        return compiled[key](state, tokens)

    def place_params(params):
        return jax.device_put(
            params,
            jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), specs,
                is_leaf=lambda x: isinstance(x, P),
            ),
        )

    return init_fn, step_fn, batch_shard, place_params
