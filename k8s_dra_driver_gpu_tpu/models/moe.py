"""Mixture-of-Experts FFN with expert parallelism over a mesh axis.

Rounds out the parallelism surface (dp/fsdp/tp/sp + EP): experts are
sharded over an axis; tokens route top-k and travel to their experts via
the all-to-all-free "dense dispatch" formulation -- every device computes
its local experts over ALL tokens it holds, with a capacity-free
weighted combine. TPU-first choices:

- Router + combine run in fp32 (softmax stability); expert matmuls in
  the model dtype on the MXU.
- Dispatch is einsum-based (one_hot combine weights), which XLA turns
  into dense matmuls -- no gather/scatter with dynamic shapes, so the
  whole layer jits with static shapes. For very large expert counts an
  all_to_all dispatch (Ulysses-style) drops in behind the same
  signature.
- Under shard_map the expert dimension is sharded over ``axis_name``;
  psum over the axis completes the combine.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compat import shard_map


def init_moe(key, d_model: int, d_ff: int, n_experts: int, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    scale_in = 1.0 / (d_model ** 0.5)
    scale_out = 1.0 / (d_ff ** 0.5)
    return {
        "router": jax.random.normal(k1, (d_model, n_experts),
                                    jnp.float32) * scale_in,
        "w_in": jax.random.normal(k2, (n_experts, d_model, d_ff),
                                  jnp.float32) * scale_in,
        "w_out": jax.random.normal(k3, (n_experts, d_ff, d_model),
                                   jnp.float32) * scale_out,
    }


def moe_param_specs(axis_name: str = "ep") -> dict:
    return {
        "router": P(None, None),
        "w_in": P(axis_name, None, None),
        "w_out": P(axis_name, None, None),
    }


def moe_ffn(params: dict, x: jax.Array, top_k: int = 2,
            dtype=jnp.bfloat16,
            expert_offset: jax.Array | int = 0) -> tuple[jax.Array, jax.Array]:
    """Dense-dispatch MoE: x [B, S, D] -> (out, aux).

    Routing is over the GLOBAL expert count (the replicated router);
    ``params['w_in']/['w_out']`` may hold only a local expert shard, with
    ``expert_offset`` giving its position -- the combine weights are
    sliced to the local block, so summing shard outputs (psum over the
    ep axis) completes the full mixture.

    aux is the load-balancing loss (mean expert load * mean router prob,
    scaled by n_experts -- the standard switch-transformer auxiliary);
    it is computed from the replicated router, so it is identical on
    every shard (do NOT psum it).
    """
    E_total = params["router"].shape[1]
    E_local = params["w_in"].shape[0]
    logits = x.astype(jnp.float32) @ params["router"]  # [B,S,E_total]
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_idx = jax.lax.top_k(probs, top_k)  # [B,S,k]
    top_mask = jax.nn.one_hot(top_idx, E_total, dtype=jnp.float32)
    # Renormalized combine weights as a dense [B,S,E_total] mask.
    combine = jnp.sum(
        top_mask
        * (top_p / jnp.sum(top_p, -1, keepdims=True))[..., None],
        axis=2,
    )
    combine_local = jax.lax.dynamic_slice_in_dim(
        combine, expert_offset, E_local, axis=2
    )
    xd = x.astype(dtype)
    h = jnp.einsum("bsd,edf->besf", xd, params["w_in"].astype(dtype))
    h = jax.nn.silu(h)
    y = jnp.einsum("besf,efd->besd", h, params["w_out"].astype(dtype))
    out = jnp.einsum("besd,bse->bsd", y.astype(jnp.float32), combine_local)

    load = jnp.mean(
        jnp.sum(top_mask, axis=2), axis=(0, 1)
    )  # fraction of tokens per expert (x top_k)
    importance = jnp.mean(probs, axis=(0, 1))
    aux = E_total * jnp.sum(load * importance) / top_k
    return out.astype(x.dtype), aux


def make_sharded_moe(mesh: Mesh, axis_name: str, top_k: int = 2,
                     dtype=jnp.bfloat16):
    """Expert-parallel MoE: experts sharded over ``axis_name``; each
    device runs its expert shard over all tokens, psum combines."""

    def local_fn(params, x):
        e_local = params["w_in"].shape[0]
        offset = jax.lax.axis_index(axis_name) * e_local
        out, aux = moe_ffn(params, x, top_k=top_k, dtype=dtype,
                           expert_offset=offset)
        # Partial mixture over the local expert shard -> full combine.
        # aux is shard-invariant (replicated router), so no psum.
        return jax.lax.psum(out, axis_name), aux

    specs = moe_param_specs(axis_name)
    x_spec = P()  # tokens replicated over the ep axis

    @jax.jit
    def fn(params, x):
        return shard_map(
            local_fn,
            mesh=mesh,
            in_specs=(specs, x_spec),
            out_specs=(x_spec, P()),
        )(params, x)

    def place(params):
        return jax.device_put(
            params,
            jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), specs,
                is_leaf=lambda s: isinstance(s, P),
            ),
        )

    return fn, place
