"""Model families runnable on slices prepared by the DRA driver.

The reference exercises its prepared fabric with external NCCL/nvbandwidth
jobs (tests/bats/test_cd_mnnvl_workload.bats); the TPU build ships the JAX
workload in-tree. Flagship: Llama-3 (north star per BASELINE.json: a
32-chip ResourceClaim running Llama-3-8B training on a v5p slice).
"""
