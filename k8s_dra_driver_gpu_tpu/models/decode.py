"""Autoregressive decoding with a KV cache (the serving path).

TPU-first decisions:
- The cache is a pair of [L, B, max_len, K, hd] stacked tensors so the
  per-step layer loop is one lax.scan (same O(1)-compile trick as the
  training forward).
- The decode step is fully static-shaped: position is a traced scalar,
  cache updates are dynamic_update_slice, attention masks by position --
  no Python control flow under jit, so a whole generate() loop compiles
  once via lax.scan.
- Sampling: greedy or temperature, PRNG threaded through the scan.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .llama import LlamaConfig, rms_norm, rope


class KVCache(NamedTuple):
    k: jax.Array  # [L, B, max_len, K, hd] (cfg.dtype, or int8 quantized)
    v: jax.Array  # [L, B, max_len, K, hd]
    length: jax.Array  # [] int32: filled positions
    # int8 mode only: per-vector scales [L, B, max_len, K, 1] (bf16).
    # None = native-dtype cache; the choice is static at trace time.
    k_scale: jax.Array | None = None
    v_scale: jax.Array | None = None

    @classmethod
    def empty(cls, cfg: LlamaConfig, batch: int, max_len: int,
              quantized: bool = False) -> "KVCache":
        shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
        if quantized:
            sshape = shape[:-1] + (1,)
            return cls(
                k=jnp.zeros(shape, jnp.int8),
                v=jnp.zeros(shape, jnp.int8),
                length=jnp.zeros((), jnp.int32),
                k_scale=jnp.zeros(sshape, jnp.bfloat16),
                v_scale=jnp.zeros(sshape, jnp.bfloat16),
            )
        return cls(
            k=jnp.zeros(shape, cfg.dtype),
            v=jnp.zeros(shape, cfg.dtype),
            length=jnp.zeros((), jnp.int32),
        )


def _quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-vector symmetric int8: x [..., hd] -> (int8 codes, scale
    [..., 1] bf16). The KV cache is the HBM-bandwidth driver of batched
    decode (read in full every step); int8 halves that traffic for a
    ~0.4% per-vector quantization error (see tests/test_decode.py)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale.astype(jnp.bfloat16)


def _dequantize(q: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    # The convert+mul fuses into the attention matmul's operand load;
    # the bf16 tensor never materializes in HBM.
    return q.astype(dtype) * scale.astype(dtype)


def _project_qkv(cfg: LlamaConfig, x, lp, positions):
    B, S, _ = x.shape
    a = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = (a @ lp["wq"].astype(cfg.dtype)).reshape(
        B, S, cfg.n_heads, cfg.head_dim)
    k = (a @ lp["wk"].astype(cfg.dtype)).reshape(
        B, S, cfg.n_kv_heads, cfg.head_dim)
    v = (a @ lp["wv"].astype(cfg.dtype)).reshape(
        B, S, cfg.n_kv_heads, cfg.head_dim)
    return rope(q, positions, cfg.rope_theta), \
        rope(k, positions, cfg.rope_theta), v


def _mlp(cfg: LlamaConfig, x, lp):
    m = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    gate = jax.nn.silu(m @ lp["w_gate"].astype(cfg.dtype))
    up = m @ lp["w_up"].astype(cfg.dtype)
    return (gate * up) @ lp["w_down"].astype(cfg.dtype)


def _attend_cached(cfg: LlamaConfig, q, ck, cv, valid_len,
                   k_scale=None, v_scale=None):
    """q [B,S,H,hd] vs cache ck/cv [B,max_len,K,hd]; positions >=
    valid_len are masked. int8 caches pass their scales and are
    dequantized on the fly (fused into the matmul loads)."""
    if k_scale is not None:
        ck = _dequantize(ck, k_scale, q.dtype)
        cv = _dequantize(cv, v_scale, q.dtype)
    B, S, H, hd = q.shape
    K = ck.shape[2]
    group = H // K
    qg = q.reshape(B, S, K, group, hd)
    s = jnp.einsum("bqkgh,bskh->bkgqs", qg, ck) / jnp.sqrt(
        jnp.asarray(hd, jnp.float32)).astype(q.dtype)
    s = s.astype(jnp.float32)
    max_len = ck.shape[1]
    mask = jnp.arange(max_len)[None, :] < valid_len  # [1, max_len]
    s = jnp.where(mask[None, None, None], s, -jnp.inf)
    w = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgqs,bskh->bqkgh", w, cv)
    return out.reshape(B, S, H, hd)


def prefill(
    params: dict, tokens: jax.Array, cfg: LlamaConfig, max_len: int,
    quantized: bool = False,
) -> tuple[jax.Array, KVCache]:
    """Process the prompt; returns (logits for the LAST position [B, V],
    a cache filled up to tokens.shape[1])."""
    B, S = tokens.shape
    x = params["embed"].astype(cfg.dtype)[tokens]
    positions = jnp.arange(S)[None, :]

    def body(carry, lp):
        h = carry
        q, k, v = _project_qkv(cfg, h, lp, positions)
        if quantized:
            qk, sk = _quantize_kv(k)
            qv, sv = _quantize_kv(v)
            ck = jnp.zeros((B, max_len, cfg.n_kv_heads, cfg.head_dim),
                           jnp.int8)
            cv = jnp.zeros_like(ck)
            sks = jnp.zeros((B, max_len, cfg.n_kv_heads, 1), jnp.bfloat16)
            svs = jnp.zeros_like(sks)
            ck = jax.lax.dynamic_update_slice(ck, qk, (0, 0, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, qv, (0, 0, 0, 0))
            sks = jax.lax.dynamic_update_slice(sks, sk, (0, 0, 0, 0))
            svs = jax.lax.dynamic_update_slice(svs, sv, (0, 0, 0, 0))
            out = (ck, cv, sks, svs)
        else:
            ck = jnp.zeros((B, max_len, cfg.n_kv_heads, cfg.head_dim),
                           cfg.dtype)
            cv = jnp.zeros_like(ck)
            ck = jax.lax.dynamic_update_slice(ck, k, (0, 0, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, v, (0, 0, 0, 0))
            out = (ck, cv)
        # Causal attention within the prompt: same dispatcher as the
        # training forward (pallas flash on TPU when shapes allow).
        from ..ops.attention import attention  # noqa: PLC0415

        attn = attention(q, k, v, causal=True, impl=cfg.attn_impl).reshape(
            B, S, cfg.n_heads * cfg.head_dim)
        h = h + attn @ lp["wo"].astype(cfg.dtype)
        h = h + _mlp(cfg, h, lp)
        return h, out

    x, caches = jax.lax.scan(body, x, params["layers"])
    x = rms_norm(x[:, -1:], params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)
    length = jnp.asarray(S, jnp.int32)
    if quantized:
        cks, cvs, sks, svs = caches
        cache = KVCache(k=cks, v=cvs, length=length,
                        k_scale=sks, v_scale=svs)
    else:
        cks, cvs = caches
        cache = KVCache(k=cks, v=cvs, length=length)
    return logits[:, 0], cache


def decode_step(
    params: dict, cache: KVCache, token: jax.Array, cfg: LlamaConfig
) -> tuple[jax.Array, KVCache]:
    """One token [B] in -> next-token logits [B, V] + updated cache."""
    B = token.shape[0]
    pos = cache.length
    x = params["embed"].astype(cfg.dtype)[token][:, None, :]  # [B,1,D]
    positions = jnp.full((B, 1), pos, jnp.int32)

    quantized = cache.k_scale is not None

    def body(carry, layer_in):
        h = carry
        if quantized:
            lp, ck, cv, sk, sv = layer_in
        else:
            lp, ck, cv = layer_in
            sk = sv = None
        q, k, v = _project_qkv(cfg, h, lp, positions)
        if quantized:
            qk, ksc = _quantize_kv(k)
            qv, vsc = _quantize_kv(v)
            ck = jax.lax.dynamic_update_slice(ck, qk, (0, pos, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, qv, (0, pos, 0, 0))
            sk = jax.lax.dynamic_update_slice(sk, ksc, (0, pos, 0, 0))
            sv = jax.lax.dynamic_update_slice(sv, vsc, (0, pos, 0, 0))
        else:
            ck = jax.lax.dynamic_update_slice(ck, k, (0, pos, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, v, (0, pos, 0, 0))
        attn = _attend_cached(cfg, q, ck, cv, pos + 1,
                              k_scale=sk, v_scale=sv)
        attn = attn.reshape(B, 1, cfg.n_heads * cfg.head_dim)
        h = h + attn @ lp["wo"].astype(cfg.dtype)
        h = h + _mlp(cfg, h, lp)
        return h, ((ck, cv, sk, sv) if quantized else (ck, cv))

    if quantized:
        x, (cks, cvs, sks, svs) = jax.lax.scan(
            body, x,
            (params["layers"], cache.k, cache.v,
             cache.k_scale, cache.v_scale),
        )
        new_cache = KVCache(k=cks, v=cvs, length=pos + 1,
                            k_scale=sks, v_scale=svs)
    else:
        x, (cks, cvs) = jax.lax.scan(
            body, x, (params["layers"], cache.k, cache.v)
        )
        new_cache = KVCache(k=cks, v=cvs, length=pos + 1)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)
    return logits[:, 0], new_cache


def _check_budget(prompt_len: int, max_new_tokens: int, max_len: int):
    if prompt_len + max_new_tokens > max_len:
        # dynamic_update_slice clamps out-of-range writes -- overflow
        # would silently corrupt the cache instead of erroring.
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens "
            f"({max_new_tokens}) exceeds max_len ({max_len})"
        )


def _generate_impl(
    params: dict,
    prompt: jax.Array,  # [B, S] token ids
    key: jax.Array,
    cfg: LlamaConfig,
    max_new_tokens: int,
    max_len: int,
    temperature: float,
    kv_quant: bool = False,
) -> jax.Array:
    logits, cache = prefill(params, prompt, cfg, max_len,
                            quantized=kv_quant)

    def sample(logits, key):
        if temperature > 0:
            return jax.random.categorical(key, logits / temperature, axis=-1)
        return jnp.argmax(logits, axis=-1)

    def step(carry, _):
        logits, cache, key = carry
        key, sub = jax.random.split(key)
        token = sample(logits, sub).astype(jnp.int32)
        logits, cache = decode_step(params, cache, token, cfg)
        return (logits, cache, key), token

    (_, _, _), tokens = jax.lax.scan(
        step, (logits, cache, key), None, length=max_new_tokens
    )
    return tokens.swapaxes(0, 1)  # [B, max_new_tokens]


_generate_jit = jax.jit(
    _generate_impl,
    static_argnames=("cfg", "max_new_tokens", "max_len", "temperature",
                     "kv_quant"),
)


def generate(
    params: dict,
    prompt: jax.Array,  # [B, S] token ids
    cfg: LlamaConfig,
    max_new_tokens: int,
    max_len: int,
    temperature: float = 0.0,
    key: jax.Array | None = None,
    kv_quant: bool = False,
) -> jax.Array:
    """Greedy (temperature=0) or sampled generation; returns [B,
    max_new_tokens].

    ``kv_quant=True`` stores the KV cache int8 with per-vector scales:
    the cache is re-read in full every decode step, so halving it
    halves the dominant HBM traffic of large-batch serving (accuracy
    bound tested in tests/test_decode.py; throughput in
    docs/benchmarks.md)."""
    if key is None:
        key = jax.random.PRNGKey(0)
    _check_budget(prompt.shape[1], max_new_tokens, max_len)
    return _generate_jit(params, prompt, key, cfg, max_new_tokens,
                         max_len, temperature, kv_quant)


def make_sharded_generate(
    mesh,
    cfg: LlamaConfig,
    max_new_tokens: int,
    max_len: int,
    temperature: float = 0.0,
    kv_quant: bool = False,
):
    """Multi-chip serving: generate() jitted over a (dp, fsdp, tp) mesh.

    Returns (generate_fn(params, prompt, key=None) -> [B, new],
    prompt_sharding, place_params). Parameters shard with the training
    PartitionSpecs (fsdp over the long matmul dim, tp over heads/ff),
    the prompt batch over (dp, fsdp); XLA's sharding propagation then
    lays the KV cache out tp-sharded on the kv-head dim and dp-sharded
    on batch and inserts the tp all-reduces after wo/w_down -- the same
    single-program SPMD serving layout a hand-sharded engine would
    build, with no collective written by hand. Requires
    cfg.n_kv_heads % tp == 0 (GQA: each tp shard owns whole kv heads).

    Reference parity: the reference driver has no serving path in-tree
    (SURVEY.md §2.9 -- workloads bring their own); this is the
    workload-side analog, sized by the ResourceClaim's chip count.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS, FSDP_AXIS, TENSOR_AXIS

    from .llama import batch_spec, param_specs

    tp = mesh.shape.get(TENSOR_AXIS, 1)
    if cfg.n_kv_heads % tp:
        raise ValueError(
            f"n_kv_heads={cfg.n_kv_heads} not divisible by tp={tp}")
    from .llama import pin_auto_attn_for_pjit

    cfg = pin_auto_attn_for_pjit(cfg, mesh)
    param_shard = jax.tree.map(
        lambda s: NamedSharding(mesh, s), param_specs(cfg),
        is_leaf=lambda x: isinstance(x, P))
    prompt_shard = NamedSharding(mesh, batch_spec())
    repl = NamedSharding(mesh, P())

    jitted = jax.jit(
        partial(_generate_impl, cfg=cfg, max_new_tokens=max_new_tokens,
                max_len=max_len, temperature=temperature,
                kv_quant=kv_quant),
        in_shardings=(param_shard, prompt_shard, repl),
        out_shardings=prompt_shard,
    )

    def generate_fn(params, prompt, key=None):
        if key is None:
            key = jax.random.PRNGKey(0)
        _check_budget(prompt.shape[1], max_new_tokens, max_len)
        dp = mesh.shape.get(DATA_AXIS, 1)
        fsdp = mesh.shape.get(FSDP_AXIS, 1)
        if prompt.shape[0] % (dp * fsdp):
            raise ValueError(
                f"prompt batch {prompt.shape[0]} not divisible by "
                f"dp({dp}) * fsdp({fsdp}) = {dp * fsdp}")
        return jitted(params, prompt, key)

    def place_params(params):
        return jax.device_put(params, param_shard)

    return generate_fn, prompt_shard, place_params
