"""ComputeDomain stack: gang-prepared multi-host ICI slices.

Reference: the compute-domain.nvidia.com three-binary stack
(cmd/compute-domain-{controller,kubelet-plugin,daemon}/, SURVEY.md
§2.2-2.4, §3.3). A ComputeDomain CR names a contiguous multi-host ICI
slice; the controller materializes a per-CD DaemonSet + workload
ResourceClaimTemplate; node plugins gate workload Prepare on domain
readiness and inject slice-membership env; per-node daemons rendezvous
through ComputeDomainClique CRs and bootstrap the JAX coordination
service (coordinator = the stable DNS name of clique index 0) -- the
TPU-native replacement for IMEX daemon supervision.
"""

COMPUTE_DOMAIN_DRIVER_NAME = "compute-domain.tpu.dra.dev"
CHANNEL_DEVICE_CLASS = "compute-domain-default-channel.tpu.dra.dev"
DAEMON_DEVICE_CLASS = "compute-domain-daemon.tpu.dra.dev"
NODE_LABEL = "resource.tpu.dra/computeDomain"
# Controller-computed ICI-adjacent host window for the gang
# (comma-joined node names, best window of consecutive workerIds). The
# in-tree scheduler consults it when allocating this domain's channel
# claims (TopologyAwarePlacement gate, pkg/topology/hosts.py).
PREFERRED_NODES_ANNOTATION = "resource.tpu.dra/preferredNodes"
CLIQUE_POD_LABEL = "resource.tpu.dra/cliqueId"
FINALIZER = "resource.tpu.dra/computedomain-finalizer"
DOMAIN_DAEMON_PORT = 7077  # daemon rendezvous service (STATUS/MEMBERS)
# The JAX distributed-runtime coordinator. DISTINCT from the rendezvous
# port: the coordinator is BOUND BY WORKLOAD PROCESS 0 (jax.distributed
# semantics), while the rendezvous service is bound by the daemon. Both
# ride the same host network (TPU pods run hostNetwork, daemon and
# worker 0 share the node), so one address works for both -- but each
# needs its own port. 8476 is jax.distributed's conventional default.
JAX_COORDINATOR_PORT = 8476
# Cross-slice (multislice) DCN transport coordinator, MEGASCALE-style:
# libtpu's DCN layer reads MEGASCALE_* env; slice 0's worker 0 hosts
# the coordinator on this port (conventional default 8080).
MEGASCALE_PORT = 8080
API_GROUP = "resource.tpu.dra"
API_VERSION = "v1beta1"

# Stable daemon DNS name pattern, index-addressable (the reference uses
# compute-domain-daemon-%04d, dnsnames.go:36-37).
DAEMON_DNS_PATTERN = "compute-domain-daemon-{index:04d}"


def daemon_dns_name(index: int, cd_uid: str = "") -> str:
    base = DAEMON_DNS_PATTERN.format(index=index)
    return f"{base}.{cd_uid}" if cd_uid else base


def expected_slices(cd_spec: dict) -> int:
    """How many ICI slices a ComputeDomain spans (spec.numSlices,
    default 1). A multi-slice domain gangs numNodes hosts split evenly
    across numSlices ICI domains (one clique per slice); cross-slice
    traffic rides DCN with a MEGASCALE-style env contract
    (SURVEY §2.9: DCN is the cross-slice fallback)."""
    return max(1, int(cd_spec.get("numSlices", 1) or 1))


def per_slice_workers(cd_spec: dict) -> int:
    """Workers per slice (= per clique). THE divisibility authority:
    webhook admission, channel prepare, and daemon prepare all call
    this so they can never disagree on the split rule. Raises
    ValueError when numNodes does not split evenly over numSlices."""
    total = expected_workers(cd_spec)
    slices = expected_slices(cd_spec)
    if total % slices:
        raise ValueError(
            f"numNodes={total} does not split evenly over "
            f"numSlices={slices}")
    return total // slices


def expected_workers(cd_spec: dict) -> int:
    """How many hosts a ComputeDomain spans: explicit numNodes, else
    derived from the slice topology and chips-per-host (overridable via
    spec.chipsPerHost for 8-chip-host generations).

    Single source of truth for the controller's readiness threshold and
    the daemons' COMPUTE_DOMAIN_NUM_WORKERS -- these MUST agree or the
    domain can never go Ready.
    """
    import math  # noqa: PLC0415

    if cd_spec.get("numNodes"):
        return cd_spec["numNodes"]
    topology = cd_spec.get("topology", "")
    if topology:
        chips = math.prod(int(d) for d in topology.split("x"))
        per_host = cd_spec.get("chipsPerHost", 4)
        return max(1, math.ceil(chips / per_host))
    return 1
