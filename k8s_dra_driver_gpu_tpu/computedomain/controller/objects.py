"""Builders for the k8s objects the controller materializes per CD.

Reference: templates/compute-domain-daemon.tmpl.yaml rendered by
daemonset.go:190-254, and the two ResourceClaimTemplate flavors
(resourceclaimtemplate.go:304-398).
"""

from __future__ import annotations

import os

from .. import (
    API_GROUP,
    API_VERSION,
    CHANNEL_DEVICE_CLASS,
    CLIQUE_POD_LABEL,
    DAEMON_DEVICE_CLASS,
    DOMAIN_DAEMON_PORT,
    NODE_LABEL,
)

DAEMON_IMAGE = "ghcr.io/tpu-dra-driver/compute-domain-daemon:latest"


def daemonset_name(cd_uid: str) -> str:
    return f"computedomain-daemon-{cd_uid}"


def daemon_rct_name(cd_name: str) -> str:
    return f"{cd_name}-daemon-claim"


def build_daemon_daemonset(cd: dict, namespace: str) -> dict:
    """The per-CD DaemonSet. Its nodeSelector matches the CD node label
    that the kubelet plugin sets during a workload-channel Prepare --
    that label is the rendezvous that makes daemons appear exactly on
    nodes running this domain's workload (computedomain.go:312-364)."""
    uid = cd["metadata"]["uid"]
    return {
        "apiVersion": "apps/v1",
        "kind": "DaemonSet",
        "metadata": {
            "name": daemonset_name(uid),
            "namespace": namespace,
            "labels": {NODE_LABEL: uid},
            "ownerReferences": [_owner_ref(cd)],
        },
        "spec": {
            "selector": {"matchLabels": {NODE_LABEL: uid}},
            "template": {
                "metadata": {"labels": {NODE_LABEL: uid}},
                "spec": {
                    "nodeSelector": {NODE_LABEL: uid},
                    # Host network: the daemon registers the NODE's
                    # address, and the TPU_COORDINATOR_ADDRESS handed
                    # to workloads must be bindable by workload process
                    # 0 on that same node (TPU workload pods run
                    # hostNetwork; jax.distributed's coordinator is
                    # bound by process 0, not by this daemon). Without
                    # this the registered IP would be pod-netns-local
                    # and the gang could never rendezvous.
                    "hostNetwork": True,
                    "dnsPolicy": "ClusterFirstWithHostNet",
                    "containers": [
                        {
                            "name": "compute-domain-daemon",
                            "image": DAEMON_IMAGE,
                            "command": [
                                "python", "-m",
                                "k8s_dra_driver_gpu_tpu.computedomain.daemon.main",
                                "run",
                            ],
                            # Downward-API identity: the daemon registers
                            # its real pod IP/name in the Clique CR.
                            "env": [
                                {"name": "POD_IP", "valueFrom": {"fieldRef": {
                                    "fieldPath": "status.podIP"}}},
                                {"name": "POD_NAME", "valueFrom": {"fieldRef": {
                                    "fieldPath": "metadata.name"}}},
                                {"name": "NODE_NAME", "valueFrom": {"fieldRef": {
                                    "fieldPath": "spec.nodeName"}}},
                                {"name": "DRIVER_NAMESPACE", "valueFrom": {
                                    "fieldRef": {
                                        "fieldPath": "metadata.namespace"}}},
                                # Daemons inherit the controller's own
                                # verbosity (chart logVerbosity -> V).
                                {"name": "V",
                                 "value": os.environ.get("V", "4")},
                            ],
                            "ports": [
                                {"containerPort": DOMAIN_DAEMON_PORT,
                                 "name": "coordinator"}
                            ],
                            "startupProbe": _probe("startup"),
                            "readinessProbe": _probe("readiness"),
                            "livenessProbe": _probe("liveness"),
                            "resources": {
                                "claims": [{"name": "daemon-claim"}]
                            },
                        }
                    ],
                    "resourceClaims": [
                        {
                            "name": "daemon-claim",
                            "resourceClaimTemplateName": daemon_rct_name(
                                cd["metadata"]["name"]
                            ),
                        }
                    ],
                },
            },
        },
    }


def _probe(kind: str) -> dict:
    """Probe budgets mirror the reference daemon
    (compute-domain-daemon.tmpl.yaml:74-100: startup 1s x 1200,
    readiness every 10s, liveness 60s x 20)."""
    exec_check = {
        "exec": {
            "command": [
                "python", "-m",
                "k8s_dra_driver_gpu_tpu.computedomain.daemon.main",
                "check",
            ]
        }
    }
    if kind == "startup":
        return {**exec_check, "periodSeconds": 1, "failureThreshold": 1200}
    if kind == "readiness":
        return {**exec_check, "periodSeconds": 10, "failureThreshold": 1}
    return {**exec_check, "periodSeconds": 60, "failureThreshold": 20}


def build_daemon_rct(cd: dict, namespace: str) -> dict:
    """Daemon ResourceClaimTemplate (deviceClass daemon, opaque
    ComputeDomainDaemonConfig{domainID})."""
    uid = cd["metadata"]["uid"]
    return {
        "apiVersion": "resource.k8s.io/v1",
        "kind": "ResourceClaimTemplate",
        "metadata": {
            "name": daemon_rct_name(cd["metadata"]["name"]),
            "namespace": namespace,
            "labels": {NODE_LABEL: uid},
            "ownerReferences": [_owner_ref(cd)],
        },
        "spec": {
            "spec": {
                "devices": {
                    "requests": [
                        {
                            "name": "daemon",
                            # resource.k8s.io/v1 nests the request spec
                            # under "exactly" (the flat form died with
                            # v1beta1).
                            "exactly": {
                                "deviceClassName": DAEMON_DEVICE_CLASS,
                            },
                        }
                    ],
                    "config": [
                        {
                            "requests": ["daemon"],
                            "opaque": {
                                "driver": "compute-domain.tpu.dra.dev",
                                "parameters": {
                                    "apiVersion": f"{API_GROUP}/{API_VERSION}",
                                    "kind": "ComputeDomainDaemonConfig",
                                    "domainID": uid,
                                },
                            },
                        }
                    ],
                }
            }
        },
    }


def build_workload_rct(cd: dict) -> dict:
    """Workload-channel ResourceClaimTemplate, created in the USER'S
    namespace (resourceclaimtemplate.go:364-398)."""
    uid = cd["metadata"]["uid"]
    spec = cd.get("spec", {})
    channel = spec.get("channel") or {}
    rct_name = (channel.get("resourceClaimTemplate") or {}).get("name", "")
    return {
        "apiVersion": "resource.k8s.io/v1",
        "kind": "ResourceClaimTemplate",
        "metadata": {
            "name": rct_name,
            "namespace": cd["metadata"].get("namespace", "default"),
            "labels": {NODE_LABEL: uid},
            "ownerReferences": [_owner_ref(cd)],
        },
        "spec": {
            "spec": {
                "devices": {
                    "requests": [
                        {
                            "name": "channel",
                            "exactly": {
                                "deviceClassName": CHANNEL_DEVICE_CLASS,
                            },
                        }
                    ],
                    "config": [
                        {
                            "requests": ["channel"],
                            "opaque": {
                                "driver": "compute-domain.tpu.dra.dev",
                                "parameters": {
                                    "apiVersion": f"{API_GROUP}/{API_VERSION}",
                                    "kind": "ComputeDomainChannelConfig",
                                    "domainID": uid,
                                    "allocationMode": channel.get(
                                        "allocationMode", "Single"
                                    ),
                                },
                            },
                        }
                    ],
                }
            }
        },
    }


def _owner_ref(cd: dict) -> dict:
    return {
        "apiVersion": f"{API_GROUP}/{API_VERSION}",
        "kind": "ComputeDomain",
        "name": cd["metadata"]["name"],
        "uid": cd["metadata"]["uid"],
        "controller": True,
    }
