"""The ComputeDomain reconciler.

Reference: cmd/compute-domain-controller/computedomain.go -- on
add/update: add finalizer, create per-CD DaemonSet + workload RCT, update
global status (onAddOrUpdate :298-377); on delete: teardown cascade
RCT -> DaemonSet -> node labels -> finalizer; global status Ready iff
enough nodes and all Ready (calculateGlobalStatus :257). Status sync
groups cliques + daemon pods per CD (cdstatus.go:135-242). Orphan GC for
DaemonSets/RCTs whose CD is gone (cleanup.go, generics CleanupManager).
"""

from __future__ import annotations

import logging
import threading

from ...api.computedomain import ComputeDomainStatusValue
from ...pkg import flightrecorder, json_copy, tracing
from ...pkg.featuregates import (
    TOPOLOGY_AWARE_PLACEMENT,
    FeatureGateError,
    FeatureGates,
)
from ...pkg.kubeclient import ConflictError, NotFoundError
from ...pkg.topology import rank_adjacent_hosts
from ...pkg.workqueue import CONTROLLER_DEFAULT_LIMITER, WorkQueue
from .. import (
    API_GROUP,
    API_VERSION,
    FINALIZER,
    NODE_LABEL,
    PREFERRED_NODES_ANNOTATION,
    expected_workers,
)
from .objects import (
    build_daemon_daemonset,
    build_daemon_rct,
    build_workload_rct,
    daemon_rct_name,
    daemonset_name,
)

logger = logging.getLogger(__name__)

CD_RESOURCE = "computedomains"
CLIQUE_RESOURCE = "computedomaincliques"


class ComputeDomainController:
    def __init__(self, kube, driver_namespace: str = "tpu-dra-driver",
                 metrics=None, gates: FeatureGates | None = None):
        self.kube = kube
        self.ns = driver_namespace
        self.metrics = metrics  # ComputeDomainMetrics or None
        if gates is None:
            try:
                gates = FeatureGates.from_env()
            except FeatureGateError:
                logger.exception("FEATURE_GATES unparseable; using defaults")
                gates = FeatureGates()
        # ICI-adjacent host preference for multi-host gangs
        # (pkg/topology/hosts.py; consumed by the in-tree scheduler).
        self._topology = gates.is_enabled(TOPOLOGY_AWARE_PLACEMENT)
        # (expiry, node -> workerId): the map changes only when slices
        # (re)publish, but reconcile runs per CD per resync -- a short
        # TTL keeps W domains from costing W cluster-wide slice LISTs.
        self._host_workers_memo: tuple[float, dict[str, int]] | None = None
        self.queue = WorkQueue(
            limiter=CONTROLLER_DEFAULT_LIMITER, name="cd-controller"
        )
        self._stop = threading.Event()
        self._resync_thread = threading.Thread(
            target=self._resync_loop, name="cd-resync", daemon=True
        )
        # Event path: push watchers from the in-memory fake, or streamed
        # HTTP watches from a real client; periodic resync backstops both.
        if hasattr(kube, "add_watcher"):
            kube.add_watcher(self._on_event)
        elif hasattr(kube, "watch"):
            # Per-resource callbacks: the event must carry which resource
            # it came from (streamed objects may omit kind).
            import functools  # noqa: PLC0415

            for resource, kind in (
                (CD_RESOURCE, "ComputeDomain"),
                (CLIQUE_RESOURCE, "ComputeDomainClique"),
            ):
                kube.watch(
                    API_GROUP, API_VERSION, resource,
                    functools.partial(self._on_watch_event, kind),
                    stop=self._stop,
                )

    # -- lifecycle ------------------------------------------------------------

    def start(self, resync_interval: float = 30.0) -> None:
        self._resync_interval = resync_interval
        self.sync_all()
        self._resync_thread.start()

    def stop(self) -> None:
        self._stop.set()
        self.queue.shutdown()

    def _resync_loop(self) -> None:
        while not self._stop.wait(self._resync_interval):
            try:
                self.sync_all()
            except Exception:  # noqa: BLE001
                logger.exception("resync failed")

    _ALL_KEY = ("*", "*")  # sentinel: reconcile every domain

    def _on_watch_event(self, kind: str, event_type: str, obj: dict) -> None:
        """Streamed-watch events may omit kind; the watch registration
        tells us which resource they came from."""
        if not obj.get("kind"):
            obj = dict(obj)
            obj["kind"] = kind
        self._on_event(event_type, obj)

    def _on_event(self, event_type: str, obj: dict) -> None:
        kind = obj.get("kind", "")
        if kind == "ComputeDomain":
            key = (obj["metadata"].get("namespace", "default"),
                   obj["metadata"]["name"])
            self.queue.enqueue(key, self._reconcile_key)
        elif kind in ("ComputeDomainClique", "Pod"):
            # Status inputs changed: one deduplicated reconcile-all item
            # (a registration storm collapses into a single queue entry;
            # the list happens on a worker, never the watch thread).
            self.queue.enqueue(self._ALL_KEY, self._reconcile_key)

    def sync_all(self) -> None:
        for cd in self._list_cds():
            key = (cd["metadata"].get("namespace", "default"),
                   cd["metadata"]["name"])
            self.queue.enqueue(key, self._reconcile_key)
        self.cleanup_orphans()

    def _list_cds(self) -> list[dict]:
        try:
            return self.kube.list(API_GROUP, API_VERSION, CD_RESOURCE)
        except Exception:  # noqa: BLE001
            logger.exception("listing ComputeDomains failed")
            return []

    def _reconcile_key(self, key) -> None:
        if key == self._ALL_KEY:
            for cd in self._list_cds():
                self.reconcile(cd)
            return
        namespace, name = key
        try:
            cd = self.kube.get(API_GROUP, API_VERSION, CD_RESOURCE, name,
                               namespace=namespace)
        except NotFoundError:
            return
        self.reconcile(cd)

    # -- reconcile ------------------------------------------------------------

    def reconcile(self, cd: dict) -> None:
        # One root span + flight event per domain reconcile, keyed by
        # the domain UID (queryable at /debug/claims/<domain-uid> like
        # claim timelines) -- the controller's hop in the cross-binary
        # trace surface (pkg/tracing.py).
        meta = cd["metadata"]
        with tracing.span("cd.reconcile", attrs={
                "domain": (f"{meta.get('namespace', 'default')}/"
                           f"{meta.get('name', '?')}"),
                "claim_uid": meta.get("uid", "")}) as sp:
            flightrecorder.default().record(
                meta.get("uid", "") or meta.get("name", "?"),
                "cd_reconcile",
                alias=(f"{meta.get('namespace', 'default')}/"
                       f"{meta.get('name', '?')}"),
                trace_id=(sp.context.trace_id if sp.recording else ""),
                deleting=bool(meta.get("deletionTimestamp")))
            self._reconcile_inner(cd)

    def _reconcile_inner(self, cd: dict) -> None:
        meta = cd["metadata"]
        if meta.get("deletionTimestamp"):
            self._teardown(cd)
            return
        if FINALIZER not in meta.get("finalizers", []):
            # reconcile() receives shared objects (watch events, test
            # fixtures, one day an informer cache): never mutate them
            # in place -- deep-copy, mutate the copy, write that back
            # (lint TPUDRA006).
            cd = json_copy(cd)
            cd["metadata"].setdefault("finalizers", []).append(FINALIZER)
            cd = self.kube.update(
                API_GROUP, API_VERSION, CD_RESOURCE, meta["name"], cd,
                namespace=meta.get("namespace", "default"),
            )
            meta = cd["metadata"]
        self._ensure(build_daemon_rct(cd, self.ns), "resourceclaimtemplates",
                     "resource.k8s.io", "v1", self.ns)
        self._ensure(build_daemon_daemonset(cd, self.ns), "daemonsets",
                     "apps", "v1", self.ns)
        workload_rct = build_workload_rct(cd)
        if workload_rct["metadata"]["name"]:
            self._ensure(workload_rct, "resourceclaimtemplates",
                         "resource.k8s.io", "v1",
                         workload_rct["metadata"]["namespace"])
        if self._topology:
            self._sync_preferred_nodes(cd)
        self.update_global_status(cd)

    def _ensure(self, obj, resource, group, version, namespace) -> None:
        try:
            self.kube.create(group, version, resource, obj,
                             namespace=namespace)
        except ConflictError:
            pass  # already exists; spec is immutable per CD generation

    # -- ICI-adjacent node preference (topology-aware gangs) ------------------

    _HOST_WORKERS_TTL_S = 10.0

    def _host_workers(self) -> dict[str, int]:
        """node -> workerId, from the chip driver's published
        ResourceSlices (the ``workerId`` attribute every chip carries,
        deviceinfo.py), memoized for a few seconds. Nodes publishing no
        workerId -- CD channel pools, degraded slices -- simply don't
        participate."""
        import time  # noqa: PLC0415

        now = time.monotonic()
        if self._host_workers_memo and self._host_workers_memo[0] > now:
            return self._host_workers_memo[1]
        try:
            slices = self.kube.list("resource.k8s.io", "v1",
                                    "resourceslices")
        except Exception:  # noqa: BLE001 - preference is best-effort
            return {}
        from ...pkg.topology.grid import attr_int  # noqa: PLC0415

        workers: dict[str, int] = {}
        for s in slices:
            spec = s.get("spec", {})
            node = spec.get("nodeName")
            if not node or node in workers:
                continue
            for dev in spec.get("devices", []):
                wid = attr_int(dev.get("attributes") or {}, "workerId")
                if wid is not None:
                    workers[node] = wid
                    break
        # workerIds are slice-LOCAL and chip slices carry no slice
        # identity: a duplicated workerId means several independent ICI
        # fabrics are visible, and a worker-order window would
        # interleave them (hosts with "adjacent" ids on different
        # fabrics). No trustworthy signal -> no preference, which is
        # plain load-spread first-fit, never a wrong bias.
        if len(set(workers.values())) != len(workers):
            workers = {}
        self._host_workers_memo = (now + self._HOST_WORKERS_TTL_S,
                                   workers)
        return workers

    def _sync_preferred_nodes(self, cd: dict) -> None:
        """Stamp the ICI-adjacent host window (gang-size run of
        consecutive workerIds) on the CD; the scheduler biases this
        domain's channel-claim placement toward it. Best-effort and
        idempotent: no workerId data (or a single-host domain) clears
        the annotation rather than freezing a stale window."""
        meta = cd["metadata"]
        expected = self._expected_nodes(cd)
        workers = self._host_workers()
        window: list[str] = []
        if expected > 1 and len(workers) >= expected:
            window = rank_adjacent_hosts(workers, expected)[:expected]
        want = ",".join(window)
        have = (meta.get("annotations") or {}).get(
            PREFERRED_NODES_ANNOTATION, "")
        if want == have:
            return
        try:
            self.kube.patch(
                API_GROUP, API_VERSION, CD_RESOURCE, meta["name"],
                {"metadata": {"annotations": {
                    PREFERRED_NODES_ANNOTATION: want or None}}},
                namespace=meta.get("namespace", "default"),
            )
            logger.info("CD %s/%s preferred ICI-adjacent nodes: %s",
                        meta.get("namespace", "default"), meta["name"],
                        window or "(none)")
        except NotFoundError:
            pass

    # -- status ---------------------------------------------------------------

    def _expected_nodes(self, cd: dict) -> int:
        return expected_workers(cd.get("spec", {}))

    def update_global_status(self, cd: dict) -> None:
        """Aggregate clique daemons into CD.status (cdstatus.go:135-242 +
        calculateGlobalStatus computedomain.go:257)."""
        uid = cd["metadata"]["uid"]
        nodes: list[dict] = []
        any_clique = False
        for clique in self.kube.list(API_GROUP, API_VERSION, CLIQUE_RESOURCE):
            if clique.get("spec", {}).get("computeDomainUID") != uid:
                continue
            any_clique = True
            nodes.extend(clique.get("status", {}).get("daemons", []))
        # Legacy mode is recognized by the ABSENCE of clique CRs (the
        # daemons write status.nodes directly, cdstatus.go:223-293). A
        # clique that exists but drained to zero daemons must NOT fall
        # back, or a fully-deregistered domain would stay Ready on its
        # own stale node list.
        legacy = not any_clique
        if legacy:
            nodes = list(cd.get("status", {}).get("nodes", []))
        expected = self._expected_nodes(cd)
        ready = (
            len(nodes) >= expected
            and all(
                n.get("status") == ComputeDomainStatusValue.READY
                for n in nodes
            )
            and expected > 0
        )
        verdict = (
            ComputeDomainStatusValue.READY
            if ready
            else ComputeDomainStatusValue.NOT_READY
        )
        if legacy:
            # Daemons own status.nodes in legacy mode; rewriting the full
            # list from our read snapshot would race their registrations
            # (lost update). Patch only the verdict.
            status_patch: dict = {"status": verdict}
            changed = cd.get("status", {}).get("status") != verdict
        else:
            status_patch = {
                "status": verdict,
                "nodes": sorted(nodes, key=lambda n: n.get("index", -1)),
            }
            changed = cd.get("status") != status_patch
        if self.metrics is not None:
            ns = cd["metadata"].get("namespace", "default")
            name = cd["metadata"]["name"]
            self.metrics.status.labels(ns, name).set(1 if ready else 0)
            self.metrics.nodes.labels(ns, name).set(len(nodes))
        if not changed:
            return
        try:
            self.kube.patch(
                API_GROUP, API_VERSION, CD_RESOURCE,
                cd["metadata"]["name"], {"status": status_patch},
                namespace=cd["metadata"].get("namespace", "default"),
            )
        except NotFoundError:
            pass

    # -- teardown + orphan GC ---------------------------------------------------

    def _teardown(self, cd: dict) -> None:
        """Deletion cascade: workload RCT -> daemon RCT -> DaemonSet ->
        cliques -> finalizer (onAddOrUpdate delete path :298-361)."""
        meta = cd["metadata"]
        uid = meta["uid"]
        channel = (cd.get("spec", {}).get("channel") or {})
        rct = (channel.get("resourceClaimTemplate") or {}).get("name")
        if rct:
            self.kube.delete("resource.k8s.io", "v1",
                             "resourceclaimtemplates", rct,
                             namespace=meta.get("namespace", "default"))
        self.kube.delete("resource.k8s.io", "v1", "resourceclaimtemplates",
                         daemon_rct_name(meta["name"]), namespace=self.ns)
        self.kube.delete("apps", "v1", "daemonsets", daemonset_name(uid),
                         namespace=self.ns)
        for clique in self.kube.list(API_GROUP, API_VERSION, CLIQUE_RESOURCE):
            if clique.get("spec", {}).get("computeDomainUID") == uid:
                self.kube.delete(
                    API_GROUP, API_VERSION, CLIQUE_RESOURCE,
                    clique["metadata"]["name"],
                    namespace=clique["metadata"].get("namespace"),
                )
        self._remove_node_labels(uid)
        if self.metrics is not None:
            ns = meta.get("namespace", "default")
            for gauge in (self.metrics.status, self.metrics.nodes):
                try:
                    gauge.remove(ns, meta["name"])
                except KeyError:
                    pass  # never reported
        finalizers = [f for f in meta.get("finalizers", []) if f != FINALIZER]
        try:
            self.kube.patch(
                API_GROUP, API_VERSION, CD_RESOURCE, meta["name"],
                {"metadata": {"finalizers": finalizers or None}},
                namespace=meta.get("namespace", "default"),
            )
        except NotFoundError:
            pass

    def _remove_node_labels(self, cd_uid: str) -> None:
        """node.go RemoveComputeDomainLabels analog."""
        try:
            nodes = self.kube.list("", "v1", "nodes",
                                   label_selector=f"{NODE_LABEL}={cd_uid}")
        except Exception:  # noqa: BLE001
            return
        for node in nodes:
            self.kube.patch(
                "", "v1", "nodes", node["metadata"]["name"],
                {"metadata": {"labels": {NODE_LABEL: None}}},
            )

    def cleanup_orphans(self) -> None:
        """Periodic orphan GC: DaemonSets/RCTs labeled for a CD that no
        longer exists (cleanup.go CleanupManager[T])."""
        live_uids = {
            cd["metadata"]["uid"] for cd in self._list_cds()
        }
        for group, version, resource, ns in (
            ("apps", "v1", "daemonsets", self.ns),
            ("resource.k8s.io", "v1", "resourceclaimtemplates", None),
        ):
            try:
                objs = self.kube.list(group, version, resource, namespace=ns)
            except Exception:  # noqa: BLE001
                continue
            for obj in objs:
                uid = obj.get("metadata", {}).get("labels", {}).get(NODE_LABEL)
                if uid and uid not in live_uids:
                    logger.warning(
                        "GC orphan %s/%s (CD %s gone)",
                        resource, obj["metadata"]["name"], uid,
                    )
                    self.kube.delete(
                        group, version, resource, obj["metadata"]["name"],
                        namespace=obj["metadata"].get("namespace"),
                    )
