"""ComputeDomain controller (reference cmd/compute-domain-controller/)."""
