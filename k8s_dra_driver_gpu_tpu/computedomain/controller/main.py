"""compute-domain-controller entry with leader election.

Reference: cmd/compute-domain-controller/main.go -- flags including
max-nodes-per-domain (:56-59), Lease-based leader election with
release-on-cancel (runWithLeaderElection :277-377), metrics + pprof mux
(:379).
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import threading

from ... import __version__
from ...pkg import logsetup
from ...pkg.kubeclient import FakeKubeClient, KubeClient
from ...pkg.leaderelection import LeaderElector
from ...pkg.metrics import ComputeDomainMetrics, MetricsServer
from .controller import ComputeDomainController

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    env = os.environ.get
    p = argparse.ArgumentParser(prog="compute-domain-controller")
    p.add_argument("--namespace", default=env("DRIVER_NAMESPACE",
                                              "tpu-dra-driver"))
    p.add_argument("--max-nodes-per-domain", type=int,
                   default=int(env("MAX_NODES_PER_DOMAIN", "64")),
                   help="largest gang a single domain may span "
                        "(reference caps IMEX domains at 18)")
    p.add_argument("--metrics-port", type=int,
                   default=int(env("METRICS_PORT", "0")))
    p.add_argument("--leader-election", action="store_true",
                   default=env("LEADER_ELECTION", "") == "true")
    p.add_argument("--lease-name", default="tpu-dra-cd-controller")
    p.add_argument("--identity", default=env("POD_NAME", os.uname().nodename))
    p.add_argument("-v", "--verbosity", type=int,
                   default=int(env("V", "4")),
                   help="log verbosity (see pkg/logsetup.py) [V]")
    p.add_argument("--kube-api", default=env("KUBE_API", ""),
                   help="API server URL override [KUBE_API]")
    p.add_argument("--standalone", action="store_true")
    return p


def run(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    logsetup.setup(args.verbosity)
    logsetup.log_startup(__name__, "compute-domain-controller",
                         __version__, args)
    # Canonical verbosity channel for anything this process renders
    # (daemon DaemonSets inherit it, objects.py) -- a -v flag must win
    # over a stale inherited V.
    os.environ["V"] = str(args.verbosity)

    metrics = ComputeDomainMetrics()
    from ...pkg.metrics import (  # noqa: PLC0415
        ResilienceMetrics,
        register_build_info,
    )
    from ...pkg.retry import RetryingKubeClient  # noqa: PLC0415

    register_build_info(metrics.registry)
    resilience = ResilienceMetrics(registry=metrics.registry)
    kube = RetryingKubeClient(
        FakeKubeClient() if args.standalone else KubeClient(
            host=args.kube_api or None),
        metrics=resilience,
    )
    metrics_server = None
    if args.metrics_port > 0:
        metrics_server = MetricsServer(metrics.registry, host="0.0.0.0",
                                       port=args.metrics_port)
        metrics_server.start()

    controller = ComputeDomainController(kube, args.namespace,
                                         metrics=metrics)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())

    def lead():
        controller.start()
        stop.wait()
        controller.stop()

    if args.leader_election:
        elector = LeaderElector(
            kube, lease_name=args.lease_name, namespace=args.namespace,
            identity=args.identity,
        )
        elector.run(lead, stop)
    else:
        lead()
    if metrics_server:
        metrics_server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(run())
