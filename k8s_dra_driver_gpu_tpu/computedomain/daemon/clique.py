"""Clique registration: the daemon's rendezvous through the API server.

Reference: cmd/compute-domain-daemon/cdclique.go -- each daemon writes
its {nodeName, IP, cliqueID, index, status} into a ComputeDomainClique CR
named "<cdUID>.<cliqueID>"; the index is the first free slot (:350),
conflict-retried; readiness flips the entry's status (:429). On TPU a
clique is one ICI-connected slice: every host of the slice shares the
clique (cross-clique traffic is DCN).
"""

from __future__ import annotations

import logging
import time

from ...pkg.kubeclient import ConflictError, NotFoundError
from .. import API_GROUP, API_VERSION

logger = logging.getLogger(__name__)

CLIQUE_RESOURCE = "computedomaincliques"


def clique_name(cd_uid: str, clique_id: str) -> str:
    return f"{cd_uid}.{clique_id}"


class CliqueRegistrar:
    def __init__(
        self,
        kube,
        cd_uid: str,
        clique_id: str,
        node_name: str,
        ip_address: str,
        namespace: str = "tpu-dra-driver",
    ):
        self.kube = kube
        self.cd_uid = cd_uid
        self.clique_id = clique_id
        self.node_name = node_name
        self.ip_address = ip_address
        self.namespace = namespace
        self.index: int | None = None

    @property
    def name(self) -> str:
        return clique_name(self.cd_uid, self.clique_id)

    def _get_or_create(self) -> dict:
        try:
            return self.kube.get(API_GROUP, API_VERSION, CLIQUE_RESOURCE,
                                 self.name, namespace=self.namespace)
        except NotFoundError:
            obj = {
                "apiVersion": f"{API_GROUP}/{API_VERSION}",
                "kind": "ComputeDomainClique",
                "metadata": {"name": self.name, "namespace": self.namespace},
                "spec": {
                    "computeDomainUID": self.cd_uid,
                    "cliqueID": self.clique_id,
                },
                "status": {"daemons": []},
            }
            try:
                return self.kube.create(API_GROUP, API_VERSION,
                                        CLIQUE_RESOURCE, obj,
                                        namespace=self.namespace)
            except ConflictError:
                return self.kube.get(API_GROUP, API_VERSION, CLIQUE_RESOURCE,
                                     self.name, namespace=self.namespace)

    def register(self, status: str = "NotReady", retries: int = 10) -> int:
        """Write our entry; index = existing or first free slot
        (cdclique.go:350), retried on write conflicts."""
        for attempt in range(retries):
            obj = self._get_or_create()
            daemons = obj.setdefault("status", {}).setdefault("daemons", [])
            mine = next(
                (d for d in daemons if d.get("name") == self.node_name), None
            )
            if mine is None:
                used = {d.get("index") for d in daemons}
                index = next(i for i in range(len(daemons) + 1)
                             if i not in used)
                daemons.append({
                    "name": self.node_name,
                    "ipAddress": self.ip_address,
                    "cliqueID": self.clique_id,
                    "index": index,
                    "status": status,
                })
            else:
                mine["ipAddress"] = self.ip_address
                mine["status"] = status
                index = mine["index"]
            try:
                self.kube.update(API_GROUP, API_VERSION, CLIQUE_RESOURCE,
                                 self.name, obj, namespace=self.namespace)
                self.index = index
                return index
            except ConflictError:
                logger.info("clique write conflict (attempt %d)", attempt + 1)
                time.sleep(0.05 * (attempt + 1))
        raise RuntimeError(f"could not register in clique {self.name}")

    def set_status(self, status: str) -> None:
        self.register(status=status)

    def members(self) -> list[dict]:
        try:
            obj = self.kube.get(API_GROUP, API_VERSION, CLIQUE_RESOURCE,
                                self.name, namespace=self.namespace)
        except NotFoundError:
            return []
        return sorted(
            obj.get("status", {}).get("daemons", []),
            key=lambda d: d.get("index", -1),
        )

    def deregister(self) -> None:
        try:
            obj = self.kube.get(API_GROUP, API_VERSION, CLIQUE_RESOURCE,
                                self.name, namespace=self.namespace)
        except NotFoundError:
            return
        daemons = obj.get("status", {}).get("daemons", [])
        obj["status"]["daemons"] = [
            d for d in daemons if d.get("name") != self.node_name
        ]
        try:
            self.kube.update(API_GROUP, API_VERSION, CLIQUE_RESOURCE,
                             self.name, obj, namespace=self.namespace)
        except (ConflictError, NotFoundError):
            pass
