"""Clique registration: the daemon's rendezvous through the API server.

Reference: cmd/compute-domain-daemon/cdclique.go -- each daemon writes
its {nodeName, IP, cliqueID, index, status} into a ComputeDomainClique CR
named "<cdUID>.<cliqueID>"; the index is the first free slot (:350),
conflict-retried; readiness flips the entry's status (:429). On TPU a
clique is one ICI-connected slice: every host of the slice shares the
clique (cross-clique traffic is DCN).

Legacy mode (ComputeDomainCliques gate off) writes the same record shape
directly into ComputeDomain.status.nodes (cdstatus.go:223-293). Both
registrars share the slot-allocation/upsert algorithm; they differ only
in which object holds the entry list.
"""

from __future__ import annotations

import logging
import time

from ...pkg.kubeclient import ConflictError, NotFoundError
from .. import API_GROUP, API_VERSION

logger = logging.getLogger(__name__)

CLIQUE_RESOURCE = "computedomaincliques"


def clique_name(cd_uid: str, clique_id: str) -> str:
    return f"{cd_uid}.{clique_id}"


class _EntryRegistrar:
    """First-free-slot registration of {name, ip, cliqueID, index,
    status} records in some list owned by a k8s object. Subclasses
    provide fetch/persist and the list accessor."""

    clique_id: str
    node_name: str
    ip_address: str

    def __init__(self):
        self.index: int | None = None

    # -- subclass hooks ---------------------------------------------------------

    def _fetch(self) -> dict:
        raise NotImplementedError

    def _persist(self, obj: dict) -> None:
        raise NotImplementedError

    def _entries(self, obj: dict) -> list[dict]:
        raise NotImplementedError

    # -- shared algorithm -------------------------------------------------------

    def register(self, status: str = "NotReady", retries: int = 10) -> int:
        """Upsert our entry; index = existing or first free slot
        (cdclique.go:350), retried on write conflicts."""
        for attempt in range(retries):
            obj = self._fetch()
            entries = self._entries(obj)
            mine = next(
                (e for e in entries if e.get("name") == self.node_name), None
            )
            if mine is None:
                used = {e.get("index") for e in entries}
                index = next(i for i in range(len(entries) + 1)
                             if i not in used)
                entries.append({
                    "name": self.node_name,
                    "ipAddress": self.ip_address,
                    "cliqueID": self.clique_id,
                    "index": index,
                    "status": status,
                })
            else:
                mine["ipAddress"] = self.ip_address
                mine["status"] = status
                index = mine["index"]
            try:
                self._persist(obj)
                self.index = index
                return index
            except ConflictError:
                logger.info("registrar write conflict (attempt %d)",
                            attempt + 1)
                time.sleep(0.05 * (attempt + 1))
        raise RuntimeError(
            f"could not register {self.node_name} after {retries} attempts"
        )

    def set_status(self, status: str) -> None:
        self.register(status=status)

    def members(self) -> list[dict]:
        try:
            obj = self._fetch()
        except NotFoundError:
            return []
        return sorted(self._entries(obj), key=lambda e: e.get("index", -1))

    def deregister(self) -> None:
        try:
            obj = self._fetch()
        except NotFoundError:
            return
        entries = self._entries(obj)
        entries[:] = [
            e for e in entries if e.get("name") != self.node_name
        ]
        try:
            self._persist(obj)
        except (ConflictError, NotFoundError):
            pass


class CliqueRegistrar(_EntryRegistrar):
    """Entries live in ComputeDomainClique.status.daemons."""

    def __init__(
        self,
        kube,
        cd_uid: str,
        clique_id: str,
        node_name: str,
        ip_address: str,
        namespace: str = "tpu-dra-driver",
    ):
        super().__init__()
        self.kube = kube
        self.cd_uid = cd_uid
        self.clique_id = clique_id
        self.node_name = node_name
        self.ip_address = ip_address
        self.namespace = namespace

    @property
    def name(self) -> str:
        return clique_name(self.cd_uid, self.clique_id)

    def _fetch(self) -> dict:
        try:
            return self.kube.get(API_GROUP, API_VERSION, CLIQUE_RESOURCE,
                                 self.name, namespace=self.namespace)
        except NotFoundError:
            obj = {
                "apiVersion": f"{API_GROUP}/{API_VERSION}",
                "kind": "ComputeDomainClique",
                "metadata": {"name": self.name, "namespace": self.namespace},
                "spec": {
                    "computeDomainUID": self.cd_uid,
                    "cliqueID": self.clique_id,
                },
                "status": {"daemons": []},
            }
            try:
                return self.kube.create(API_GROUP, API_VERSION,
                                        CLIQUE_RESOURCE, obj,
                                        namespace=self.namespace)
            except ConflictError:
                return self.kube.get(API_GROUP, API_VERSION, CLIQUE_RESOURCE,
                                     self.name, namespace=self.namespace)

    def _persist(self, obj: dict) -> None:
        self.kube.update(API_GROUP, API_VERSION, CLIQUE_RESOURCE,
                         self.name, obj, namespace=self.namespace)

    def _entries(self, obj: dict) -> list[dict]:
        return obj.setdefault("status", {}).setdefault("daemons", [])


class LegacyStatusRegistrar(_EntryRegistrar):
    """Legacy mode: entries live in ComputeDomain.status.nodes."""

    def __init__(self, kube, cd_uid: str, cd_name: str, cd_namespace: str,
                 clique_id: str, node_name: str, ip_address: str):
        super().__init__()
        self.kube = kube
        self.cd_name = cd_name
        self.cd_namespace = cd_namespace
        self.clique_id = clique_id
        self.node_name = node_name
        self.ip_address = ip_address
        del cd_uid  # identity is (name, namespace) for direct status writes

    def _fetch(self) -> dict:
        return self.kube.get(API_GROUP, API_VERSION, "computedomains",
                             self.cd_name, namespace=self.cd_namespace)

    def _persist(self, obj: dict) -> None:
        self.kube.update(API_GROUP, API_VERSION, "computedomains",
                         self.cd_name, obj, namespace=self.cd_namespace)

    def _entries(self, obj: dict) -> list[dict]:
        return obj.setdefault("status", {}).setdefault("nodes", [])
