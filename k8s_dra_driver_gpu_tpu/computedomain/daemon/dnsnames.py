"""Stable index -> DNS name mapping + hosts-file rewriting.

Reference: cmd/compute-domain-daemon/dnsnames.go -- stable
compute-domain-daemon-%04d names per clique index; peer IP changes only
rewrite /etc/hosts and nudge the daemon (no restart), so a node
replacement never disrupts the rest of the gang (main.go:390-431).
"""

from __future__ import annotations

import os

from .. import daemon_dns_name

HOSTS_MARKER_BEGIN = "# BEGIN tpu-compute-domain\n"
HOSTS_MARKER_END = "# END tpu-compute-domain\n"


def dns_name_mappings(nodes: list[dict]) -> dict[str, str]:
    """index-stable DNS name -> IP for every known daemon."""
    out = {}
    for n in nodes:
        index = n.get("index", -1)
        ip = n.get("ipAddress", "")
        if index >= 0 and ip:
            out[daemon_dns_name(index)] = ip
    return out


def update_hosts_file(path: str, mappings: dict[str, str]) -> bool:
    """Idempotently rewrite the managed block; returns True on change."""
    try:
        with open(path, encoding="utf-8") as f:
            content = f.read()
    except FileNotFoundError:
        content = ""
    begin = content.find(HOSTS_MARKER_BEGIN)
    end = content.find(HOSTS_MARKER_END)
    if begin != -1 and end != -1:
        head = content[:begin]
        tail = content[end + len(HOSTS_MARKER_END):]
    else:
        head, tail = content, ""
        if head and not head.endswith("\n"):
            head += "\n"
    block = HOSTS_MARKER_BEGIN
    for name in sorted(mappings):
        block += f"{mappings[name]}\t{name}\n"
    block += HOSTS_MARKER_END
    new_content = head + block + tail
    if new_content == content:
        return False
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(new_content)
    os.replace(tmp, path)
    return True
