"""The coordination service the daemon supervises.

This is the nvidia-imex analog for TPU: ICI itself needs no userland
memory-export daemon, but multi-host JAX needs (a) a rendezvous that
hands every worker the coordinator address + its worker id, and (b) peer
liveness the gang can gate on. This small TCP service provides both:

  STATUS\n  -> READY\n | NOT_READY\n   (quorum state; probes use this,
               the analog of `nvidia-imex-ctl -q` == READY)
  MEMBERS\n -> one-line JSON of the current membership (workers, ips,
               coordinator address, worker count)

Membership lives in a JSON file the daemon rewrites on peer changes;
SIGUSR1 reloads it without dropping connections (the reference's
DNS-names mode uses SIGUSR1 on nvidia-imex for non-disruptive updates,
main.go:390-431). Quorum: READY once all expected workers appear
(IMEX_WAIT_FOR_QUORUM analog).

Run as a child process:
    python -m ...daemon.rendezvous --members-file F --port N
"""

from __future__ import annotations

import argparse
import json
import logging
import signal
import socketserver
import sys
import threading

logger = logging.getLogger(__name__)


class MembershipState:
    def __init__(self, members_file: str):
        self._file = members_file
        self._lock = threading.Lock()
        self._doc: dict = {}
        self.reload()

    def reload(self) -> None:
        try:
            with open(self._file, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            doc = {}
        with self._lock:
            self._doc = doc
        logger.info(
            "membership reloaded: %d/%s workers",
            len(doc.get("workers", [])), doc.get("numWorkers", "?"),
        )

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._doc)

    def ready(self) -> bool:
        doc = self.snapshot()
        expected = doc.get("numWorkers", 0)
        workers = doc.get("workers", [])
        return (
            expected > 0
            and len(workers) >= expected
            and all(w.get("status") == "Ready" for w in workers)
        )


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        state: MembershipState = self.server.state  # type: ignore[attr-defined]
        line = self.rfile.readline().decode(errors="replace").strip().upper()
        if line == "STATUS":
            self.wfile.write(b"READY\n" if state.ready() else b"NOT_READY\n")
        elif line == "MEMBERS":
            self.wfile.write(
                (json.dumps(state.snapshot()) + "\n").encode()
            )
        else:
            self.wfile.write(b"ERR unknown command\n")


class CoordinationService(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, host: str, port: int, state: MembershipState):
        super().__init__((host, port), _Handler)
        self.state = state


def query(host: str, port: int, command: str, timeout: float = 3.0) -> str:
    """Client helper (used by `check` probes and tests)."""
    import socket

    with socket.create_connection((host, port), timeout=timeout) as s:
        s.sendall(command.encode() + b"\n")
        data = s.makefile().readline()
    return data.strip()


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="coordination-service")
    p.add_argument("--members-file", required=True)
    p.add_argument("--port", type=int, default=7077)
    p.add_argument("--host", default="0.0.0.0")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    state = MembershipState(args.members_file)
    signal.signal(signal.SIGUSR1, lambda *a: state.reload())
    server = CoordinationService(args.host, args.port, state)
    # shutdown() must not run on the serving (main) thread -- it blocks
    # until serve_forever exits, which would deadlock inside the handler.
    signal.signal(
        signal.SIGTERM,
        lambda *a: threading.Thread(target=server.shutdown).start(),
    )
    logger.info("coordination service on %s:%d", args.host, args.port)
    server.serve_forever(poll_interval=0.2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
