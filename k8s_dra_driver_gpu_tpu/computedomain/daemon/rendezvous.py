"""The coordination service the daemon supervises.

This is the nvidia-imex analog for TPU: ICI itself needs no userland
memory-export daemon, but multi-host JAX needs (a) a rendezvous that
hands every worker the coordinator address + its worker id, and (b) peer
liveness the gang can gate on. This small TCP service provides both:

  STATUS\n  -> READY\n | NOT_READY\n   (quorum state; probes use this,
               the analog of `nvidia-imex-ctl -q` == READY)
  MEMBERS\n -> one-line JSON of the current membership (workers, ips,
               coordinator address, worker count)
  WAIT <s>\n -> READY\n | TIMEOUT\n    (rendezvous BARRIER with a
               deadline: blocks until quorum or <s> seconds elapse --
               gang members gate on this instead of spinning STATUS,
               and a straggler node past the deadline yields TIMEOUT,
               never a hung connection)

Membership lives in a JSON file the daemon rewrites on peer changes;
SIGUSR1 reloads it without dropping connections (the reference's
DNS-names mode uses SIGUSR1 on nvidia-imex for non-disruptive updates,
main.go:390-431). Quorum: READY once all expected workers appear
(IMEX_WAIT_FOR_QUORUM analog).

Run as a child process:
    python -m ...daemon.rendezvous --members-file F --port N
"""

from __future__ import annotations

import argparse
import json
import logging
import signal
import socketserver
import sys
import threading
import time

from ...pkg import faults

logger = logging.getLogger(__name__)

# Upper bound a WAIT client may request; a typo'd huge deadline must
# not pin a handler thread for hours.
MAX_WAIT_S = 600.0


class MembershipState:
    def __init__(self, members_file: str):
        self._file = members_file
        self._lock = threading.Lock()
        self._doc: dict = {}
        # Pulsed on every reload so WAIT barriers wake immediately on
        # membership changes instead of polling.
        self._changed = threading.Condition(self._lock)
        self.reload()

    def reload(self) -> None:
        try:
            with open(self._file, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            doc = {}
        with self._lock:
            self._doc = doc
            self._changed.notify_all()
        logger.info(
            "membership reloaded: %d/%s workers",
            len(doc.get("workers", [])), doc.get("numWorkers", "?"),
        )

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._doc)

    @staticmethod
    def _doc_ready(doc: dict) -> bool:
        expected = doc.get("numWorkers", 0)
        workers = doc.get("workers", [])
        return (
            expected > 0
            and len(workers) >= expected
            and all(w.get("status") == "Ready" for w in workers)
        )

    def ready(self) -> bool:
        return self._doc_ready(self.snapshot())

    def wait_ready(self, timeout: float) -> bool:
        """Rendezvous barrier: block until quorum or the deadline.
        Returns the final ready state -- a False IS the straggler
        signal, never an exception or a hang."""
        deadline = time.monotonic() + min(max(timeout, 0.0), MAX_WAIT_S)
        with self._changed:
            while not self._doc_ready(self._doc):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                # Wake on the next reload pulse (short tick as the
                # safety net against a missed notify).
                self._changed.wait(min(remaining, 0.5))
            return True


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        state: MembershipState = self.server.state  # type: ignore[attr-defined]
        line = self.rfile.readline().decode(errors="replace").strip().upper()
        # Fault seam: error mode drops the connection mid-command (the
        # probe/barrier client sees a reset, exactly like a dying
        # daemon); latency mode delays the answer past probe timeouts.
        faults.fault_point("rendezvous.handle",
                          error=lambda m: ConnectionResetError(m))
        if line == "STATUS":
            self.wfile.write(b"READY\n" if state.ready() else b"NOT_READY\n")
        elif line == "MEMBERS":
            self.wfile.write(
                (json.dumps(state.snapshot()) + "\n").encode()
            )
        elif line.startswith("WAIT"):
            try:
                timeout = float(line.split(None, 1)[1])
            except (IndexError, ValueError):
                self.wfile.write(b"ERR bad WAIT timeout\n")
                return
            ok = state.wait_ready(timeout)
            self.wfile.write(b"READY\n" if ok else b"TIMEOUT\n")
        else:
            self.wfile.write(b"ERR unknown command\n")


class CoordinationService(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, host: str, port: int, state: MembershipState):
        super().__init__((host, port), _Handler)
        self.state = state


def query(host: str, port: int, command: str, timeout: float = 3.0) -> str:
    """Client helper (used by `check` probes and tests)."""
    import socket

    with socket.create_connection((host, port), timeout=timeout) as s:
        s.sendall(command.encode() + b"\n")
        data = s.makefile().readline()
    return data.strip()


def wait_for_quorum(host: str, port: int, deadline_s: float) -> bool:
    """Client-side rendezvous barrier: True once the gang is READY,
    False when ``deadline_s`` elapses first (straggler). Connection
    errors count against the deadline and are retried -- the daemon may
    still be starting."""
    deadline = time.monotonic() + deadline_s
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return False
        try:
            answer = query(host, port, f"WAIT {remaining:.3f}",
                           timeout=remaining + 2.0)
        except OSError:
            if time.monotonic() >= deadline:
                return False
            time.sleep(min(0.2, deadline_s / 10))
            continue
        if answer == "READY":
            return True
        if answer == "TIMEOUT":
            return False
        # ERR / garbage: an old daemon without WAIT -- fall back to a
        # STATUS poll for the rest of the budget.
        try:
            if query(host, port, "STATUS") == "READY":
                return True
        except OSError:
            pass
        time.sleep(min(0.2, deadline_s / 10))


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="coordination-service")
    p.add_argument("--members-file", required=True)
    p.add_argument("--port", type=int, default=7077)
    p.add_argument("--host", default="0.0.0.0")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    state = MembershipState(args.members_file)
    signal.signal(signal.SIGUSR1, lambda *a: state.reload())
    server = CoordinationService(args.host, args.port, state)
    # shutdown() must not run on the serving (main) thread -- it blocks
    # until serve_forever exits, which would deadlock inside the handler.
    signal.signal(
        signal.SIGTERM,
        lambda *a: threading.Thread(target=server.shutdown).start(),
    )
    logger.info("coordination service on %s:%d", args.host, args.port)
    server.serve_forever(poll_interval=0.2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
