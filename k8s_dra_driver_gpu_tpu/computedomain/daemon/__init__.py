"""Per-node ComputeDomain daemon (reference cmd/compute-domain-daemon/)."""
