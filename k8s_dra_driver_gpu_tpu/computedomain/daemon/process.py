"""Child-process supervisor for the coordination service.

Reference: cmd/compute-domain-daemon/process.go -- ProcessManager with
Restart/EnsureStarted/Signal/stop (SIGTERM -> 5s -> SIGKILL) and a
Watchdog goroutine auto-restarting on unexpected exit with 1s backoff
(:169-203). The supervised child there is nvidia-imex; here it is the
TPU coordination-service stub (rendezvous.py).
"""

from __future__ import annotations

import ctypes
import logging
import os
import signal
import subprocess
import threading
import time

logger = logging.getLogger(__name__)

TERM_GRACE_S = 5.0
RESTART_BACKOFF_S = 1.0

_PR_SET_PDEATHSIG = 1  # linux/prctl.h

# Resolved at import: preexec_fn runs between fork and exec in a
# multithreaded process, where dlopen/malloc can deadlock on locks some
# other thread held at fork time -- only the pre-resolved call is safe
# there.
try:
    _LIBC = ctypes.CDLL(None, use_errno=True)
    _LIBC.prctl  # resolve the symbol now too
except (OSError, AttributeError):  # non-linux dev hosts
    _LIBC = None


def _child_preexec() -> None:
    """Runs in the child between fork and exec: own session (the child
    must not ride the supervisor's process group / controlling tty) plus
    parent-death signal, so a SIGKILLed supervisor can never leak its
    children -- the kernel SIGTERMs them the moment the parent thread
    dies. Respawned supervisors additionally kill stale pids recorded in
    the pidfile (the PDEATHSIG belt's braces)."""
    os.setsid()
    if _LIBC is not None:
        _LIBC.prctl(_PR_SET_PDEATHSIG, signal.SIGTERM, 0, 0, 0)


class ProcessManager:
    def __init__(self, argv: list[str], env: dict | None = None,
                 pidfile: str | None = None):
        self._argv = argv
        self._env = env
        self._pidfile = pidfile
        self._proc: subprocess.Popen | None = None
        self._lock = threading.Lock()
        self._expected_exit = False
        self._watchdog_stop = threading.Event()
        self._watchdog_thread: threading.Thread | None = None

    # -- lifecycle ------------------------------------------------------------

    def ensure_started(self) -> None:
        with self._lock:
            if self._proc is not None and self._proc.poll() is None:
                return
            self._start_locked()

    def restart(self) -> None:
        with self._lock:
            self._stop_locked()
            self._start_locked()

    def signal(self, sig: int = signal.SIGUSR1) -> None:
        with self._lock:
            if self._proc is not None and self._proc.poll() is None:
                self._proc.send_signal(sig)

    def stop(self) -> None:
        self._watchdog_stop.set()
        with self._lock:
            self._expected_exit = True
            self._stop_locked()
        if self._watchdog_thread:
            self._watchdog_thread.join(timeout=RESTART_BACKOFF_S + 1)

    def alive(self) -> bool:
        with self._lock:
            return self._proc is not None and self._proc.poll() is None

    @property
    def pid(self) -> int | None:
        with self._lock:
            return self._proc.pid if self._proc else None

    # -- internals ------------------------------------------------------------

    def _start_locked(self) -> None:
        self._expected_exit = False
        self._kill_stale_locked()
        self._proc = subprocess.Popen(
            self._argv, env=self._env, preexec_fn=_child_preexec)
        if self._pidfile:
            tmp = self._pidfile + ".tmp"
            try:
                with open(tmp, "w", encoding="utf-8") as f:
                    f.write(str(self._proc.pid))
                os.replace(tmp, self._pidfile)
            except OSError:
                logger.warning("could not write pidfile %s", self._pidfile)
        logger.info("started %s (pid %d)", self._argv[0], self._proc.pid)

    def _kill_stale_locked(self) -> None:
        """A previous supervisor instance's child may survive a missed
        PDEATHSIG (e.g. the pidfile outlived a host that lost the signal
        race); kill it before binding its resources again."""
        if not self._pidfile:
            return
        try:
            with open(self._pidfile, encoding="utf-8") as f:
                stale = int(f.read().strip())
        except (OSError, ValueError):
            return
        if self._proc is not None and self._proc.pid == stale:
            return
        # Guard against pid recycling: only kill a process that is
        # recognizably ours (argv prefix match via /proc cmdline).
        try:
            with open(f"/proc/{stale}/cmdline", "rb") as f:
                cmdline = f.read().split(b"\0")
        except OSError:
            return
        want = [a.encode() for a in self._argv]
        if cmdline[: len(want)] != want:
            return
        # The stale child must actually be GONE before the replacement
        # starts (it may still own a socket/dir); escalate to SIGKILL
        # if it ignores SIGTERM through the grace period.
        def gone() -> bool:
            # A pid can linger as a zombie (e.g. this very process
            # spawned it earlier and never reaped); a zombie holds no
            # sockets or files, so Z counts as gone.
            try:
                with open(f"/proc/{stale}/stat", encoding="ascii",
                          errors="replace") as f:
                    return f.read().rsplit(")", 1)[1].split()[0] == "Z"
            except (OSError, IndexError):
                return True

        try:
            os.kill(stale, signal.SIGTERM)
            logger.warning("terminating stale child pid %d from %s",
                           stale, self._pidfile)
        except OSError:
            return
        deadline = time.monotonic() + TERM_GRACE_S
        while time.monotonic() < deadline:
            if gone():
                return
            time.sleep(0.05)
        try:
            os.kill(stale, signal.SIGKILL)
        except OSError:
            return
        logger.warning("stale child %d ignored SIGTERM; killed", stale)
        deadline = time.monotonic() + 1.0
        while time.monotonic() < deadline and not gone():
            time.sleep(0.05)

    def _stop_locked(self) -> None:
        proc = self._proc
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=TERM_GRACE_S)
        except subprocess.TimeoutExpired:
            logger.warning("child %d ignored SIGTERM; killing", proc.pid)
            proc.kill()
            proc.wait()

    # -- watchdog ---------------------------------------------------------------

    def start_watchdog(self) -> None:
        self._watchdog_thread = threading.Thread(
            target=self._watchdog, name="process-watchdog", daemon=True
        )
        self._watchdog_thread.start()

    def _watchdog(self) -> None:
        while not self._watchdog_stop.wait(RESTART_BACKOFF_S):
            with self._lock:
                dead = (
                    self._proc is not None
                    and self._proc.poll() is not None
                    and not self._expected_exit
                )
            if dead:
                logger.warning(
                    "coordination service exited unexpectedly; restarting"
                )
                time.sleep(RESTART_BACKOFF_S)
                with self._lock:
                    if not self._expected_exit:
                        self._start_locked()
