"""Child-process supervisor for the coordination service.

Reference: cmd/compute-domain-daemon/process.go -- ProcessManager with
Restart/EnsureStarted/Signal/stop (SIGTERM -> 5s -> SIGKILL) and a
Watchdog goroutine auto-restarting on unexpected exit with 1s backoff
(:169-203). The supervised child there is nvidia-imex; here it is the
TPU coordination-service stub (rendezvous.py).
"""

from __future__ import annotations

import logging
import signal
import subprocess
import threading
import time

logger = logging.getLogger(__name__)

TERM_GRACE_S = 5.0
RESTART_BACKOFF_S = 1.0


class ProcessManager:
    def __init__(self, argv: list[str], env: dict | None = None):
        self._argv = argv
        self._env = env
        self._proc: subprocess.Popen | None = None
        self._lock = threading.Lock()
        self._expected_exit = False
        self._watchdog_stop = threading.Event()
        self._watchdog_thread: threading.Thread | None = None

    # -- lifecycle ------------------------------------------------------------

    def ensure_started(self) -> None:
        with self._lock:
            if self._proc is not None and self._proc.poll() is None:
                return
            self._start_locked()

    def restart(self) -> None:
        with self._lock:
            self._stop_locked()
            self._start_locked()

    def signal(self, sig: int = signal.SIGUSR1) -> None:
        with self._lock:
            if self._proc is not None and self._proc.poll() is None:
                self._proc.send_signal(sig)

    def stop(self) -> None:
        self._watchdog_stop.set()
        with self._lock:
            self._expected_exit = True
            self._stop_locked()
        if self._watchdog_thread:
            self._watchdog_thread.join(timeout=RESTART_BACKOFF_S + 1)

    def alive(self) -> bool:
        with self._lock:
            return self._proc is not None and self._proc.poll() is None

    @property
    def pid(self) -> int | None:
        with self._lock:
            return self._proc.pid if self._proc else None

    # -- internals ------------------------------------------------------------

    def _start_locked(self) -> None:
        self._expected_exit = False
        self._proc = subprocess.Popen(self._argv, env=self._env)
        logger.info("started %s (pid %d)", self._argv[0], self._proc.pid)

    def _stop_locked(self) -> None:
        proc = self._proc
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=TERM_GRACE_S)
        except subprocess.TimeoutExpired:
            logger.warning("child %d ignored SIGTERM; killing", proc.pid)
            proc.kill()
            proc.wait()

    # -- watchdog ---------------------------------------------------------------

    def start_watchdog(self) -> None:
        self._watchdog_thread = threading.Thread(
            target=self._watchdog, name="process-watchdog", daemon=True
        )
        self._watchdog_thread.start()

    def _watchdog(self) -> None:
        while not self._watchdog_stop.wait(RESTART_BACKOFF_S):
            with self._lock:
                dead = (
                    self._proc is not None
                    and self._proc.poll() is not None
                    and not self._expected_exit
                )
            if dead:
                logger.warning(
                    "coordination service exited unexpectedly; restarting"
                )
                time.sleep(RESTART_BACKOFF_S)
                with self._lock:
                    if not self._expected_exit:
                        self._start_locked()
