"""compute-domain-daemon entry: `run` and `check` subcommands.

Reference: cmd/compute-domain-daemon/main.go -- identity via CDI-injected
env (:44-51), pod clique label (:536), config render (:461), three
concurrent loops: controller (clique registration), update loop (peer
changes -> hosts rewrite + SIGUSR1, DNS-names mode :390-431), process
watchdog (:333). `check` = probe shelling to `nvidia-imex-ctl -q`
expecting READY (:435-459); here it queries the coordination service.

The daemon's workload-facing output is the BOOTSTRAP FILE
(<state>/bootstrap.json): coordinator address (index-0 stable DNS name),
this host's worker id, and worker hostnames -- exactly what
jax.distributed.initialize needs on every pod of the gang.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import sys
import threading
import time

from ...pkg.kubeclient import FakeKubeClient, KubeClient
from .. import API_GROUP, API_VERSION, DOMAIN_DAEMON_PORT, daemon_dns_name
from .clique import CliqueRegistrar
from .dnsnames import dns_name_mappings, update_hosts_file
from .process import ProcessManager
from .rendezvous import query

logger = logging.getLogger(__name__)

# Peer updates arrive via the registrar object's watch (informer); the
# resync interval is only the fallback cadence covering watch gaps
# (reference: informer-driven, cdclique.go, + periodic resync). The
# liveness interval bounds how fast a dead coordination child flips the
# daemon NotReady -- child death produces no watch event.
RESYNC_INTERVAL_S = 15.0
LIVENESS_INTERVAL_S = 2.0


class DaemonConfig:
    """Identity + paths, from the env the CD plugin injected."""

    def __init__(self, env=os.environ):
        self.cd_uid = env.get("COMPUTE_DOMAIN_UUID", "")
        self.cd_name = env.get("COMPUTE_DOMAIN_NAME", "")
        self.cd_namespace = env.get("COMPUTE_DOMAIN_NAMESPACE", "default")
        self.clique_id = env.get("CLIQUE_ID", "0")
        self.node_name = env.get("NODE_NAME", os.uname().nodename)
        self.pod_ip = env.get("POD_IP", "127.0.0.1")
        self.pod_name = env.get("POD_NAME", "")
        self.num_workers = int(env.get("COMPUTE_DOMAIN_NUM_WORKERS", "1"))
        self.state_dir = env.get("DOMAIN_STATE_DIR", "/var/run/tpu-domain")
        self.hosts_file = env.get("HOSTS_FILE", "/etc/hosts")
        self.port = int(env.get("COORDINATION_PORT", str(DOMAIN_DAEMON_PORT)))
        # The JAX coordinator port advertised in bootstrap.json; bound
        # by workload process 0, not by this daemon (see
        # computedomain.JAX_COORDINATOR_PORT).
        from .. import JAX_COORDINATOR_PORT  # noqa: PLC0415

        self.jax_port = int(
            env.get("JAX_COORDINATOR_PORT", str(JAX_COORDINATOR_PORT)))
        # Bind/probe address for the coordination service. Default: bind
        # all interfaces, probe loopback (one daemon per host). Set to
        # the pod IP when several daemons share one network namespace
        # (the fake-cluster gang e2e runs every "node" on one machine).
        self.coordination_host = env.get("COORDINATION_HOST", "")
        self.driver_namespace = env.get("DRIVER_NAMESPACE", "tpu-dra-driver")
        self.standalone = env.get("CD_DAEMON_STANDALONE", "") == "1"
        # Both mode switches ride the k8s-style FEATURE_GATES mechanism
        # (pkg/featuregates): ComputeDomainCliques picks the registrar,
        # DomainDaemonsWithDNSNames picks hosts-rewrite+SIGUSR1 vs the
        # legacy restart-on-peer-change loop (reference main.go:347-431).
        from ...pkg.featuregates import (  # noqa: PLC0415
            COMPUTE_DOMAIN_CLIQUES,
            DOMAIN_DAEMONS_WITH_DNS_NAMES,
            FeatureGates,
        )

        gates = FeatureGates.parse(env.get("FEATURE_GATES", ""))
        self.use_cliques = gates.is_enabled(COMPUTE_DOMAIN_CLIQUES)
        self.dns_names = gates.is_enabled(DOMAIN_DAEMONS_WITH_DNS_NAMES)


class Daemon:
    def __init__(self, config: DaemonConfig, kube=None):
        self.cfg = config
        if kube is None:
            from ...pkg.retry import RetryingKubeClient  # noqa: PLC0415

            kube = RetryingKubeClient(
                FakeKubeClient() if config.standalone else KubeClient())
        self.kube = kube
        os.makedirs(config.state_dir, exist_ok=True)
        self.members_file = os.path.join(config.state_dir, "members.json")
        self.bootstrap_file = os.path.join(config.state_dir, "bootstrap.json")
        if config.use_cliques:
            self.registrar = CliqueRegistrar(
                self.kube,
                cd_uid=config.cd_uid,
                clique_id=config.clique_id,
                node_name=config.node_name,
                ip_address=config.pod_ip,
                namespace=config.driver_namespace,
            )
        else:
            # Legacy direct-status mode (ComputeDomainCliques gate off).
            from .clique import LegacyStatusRegistrar  # noqa: PLC0415

            self.registrar = LegacyStatusRegistrar(
                self.kube,
                cd_uid=config.cd_uid,
                cd_name=config.cd_name,
                cd_namespace=config.cd_namespace,
                clique_id=config.clique_id,
                node_name=config.node_name,
                ip_address=config.pod_ip,
            )
        self._write_members([])  # exists before the child starts
        # The child must resolve this package regardless of how the
        # daemon itself was launched.
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        child_env = dict(os.environ)
        child_env["PYTHONPATH"] = (
            pkg_root + os.pathsep + child_env.get("PYTHONPATH", "")
        ).rstrip(os.pathsep)
        self.process = ProcessManager([
            sys.executable, "-m",
            "k8s_dra_driver_gpu_tpu.computedomain.daemon.rendezvous",
            "--members-file", self.members_file,
            "--port", str(config.port),
            "--host", config.coordination_host or "0.0.0.0",
        ], env=child_env)
        self._stop = threading.Event()
        self._kick = threading.Event()
        self._last_members: list[dict] | None = None
        # Watch-driven peer propagation: an informer over the registrar's
        # backing resource kicks the sync loop the moment a peer
        # registers/flips status, instead of a fixed-cadence poll.
        from ...pkg.informer import Informer  # noqa: PLC0415

        if config.use_cliques:
            self._informer = Informer(
                self.kube, API_GROUP, API_VERSION, "computedomaincliques",
                kind="ComputeDomainClique",
                namespace=config.driver_namespace,
                resync_period=RESYNC_INTERVAL_S,
            )
        else:
            self._informer = Informer(
                self.kube, API_GROUP, API_VERSION, "computedomains",
                kind="ComputeDomain",
                namespace=config.cd_namespace,
                resync_period=RESYNC_INTERVAL_S,
            )
        self._informer.add_change_hook(self._kick.set)

    # -- membership/bootstrap files --------------------------------------------

    def _write_members(self, members: list[dict]) -> None:
        doc = {
            "computeDomain": self.cfg.cd_uid,
            "cliqueID": self.cfg.clique_id,
            "numWorkers": self.cfg.num_workers,
            "workers": members,
        }
        tmp = self.members_file + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, self.members_file)

    def _write_bootstrap(self, my_index: int) -> None:
        """The JAX bootstrap contract consumed by workload pods.

        workerHostnames is POSITIONAL BY PROCESS ID and always
        num_workers long -- like the CDI env contract
        (plugin/device_state.py:_prepare_channel), it derives from the
        declared gang size, never from whichever subset of peers
        happens to be registered right now: a transient 3-of-4
        membership must not produce a 3-entry list that consumers
        rightly reject against numProcesses=4.

        SCOPE: this file is CLIQUE-LOCAL (this daemon's slice only --
        num_workers is already numNodes/numSlices on multi-slice
        domains, injected by the CD plugin). On a multi-slice domain
        the authoritative GLOBAL contract is the CDI-injected channel
        env (slice-major ids + MEGASCALE set); the ``scope`` and
        ``cliqueID`` fields let consumers tell the two apart instead
        of mistaking a slice-local gang for the whole domain."""
        coordinator = f"{daemon_dns_name(0)}:{self.cfg.jax_port}"
        doc = {
            "coordinatorAddress": coordinator,
            "numProcesses": self.cfg.num_workers,
            "processId": my_index,
            "workerHostnames": [
                daemon_dns_name(i) for i in range(self.cfg.num_workers)
            ],
            "scope": "clique",
            "cliqueID": self.cfg.clique_id,
        }
        tmp = self.bootstrap_file + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, self.bootstrap_file)

    # -- pod label ---------------------------------------------------------------

    def _label_own_pod(self) -> None:
        if not self.cfg.pod_name:
            return
        from .. import CLIQUE_POD_LABEL  # noqa: PLC0415

        try:
            self.kube.patch(
                "", "v1", "pods", self.cfg.pod_name,
                {"metadata": {"labels": {
                    CLIQUE_POD_LABEL: self.cfg.clique_id}}},
                namespace=self.cfg.driver_namespace,
            )
        except Exception:  # noqa: BLE001 - label is advisory
            logger.exception("labeling own pod failed")

    # -- main loops ---------------------------------------------------------------

    def sync_once(self) -> None:
        """One pass of the update loop: clique members -> members file +
        hosts + bootstrap; SIGUSR1 the child on change (DNS-names mode:
        no restart, no workload disruption)."""
        members = self.registrar.members()
        if members == self._last_members:
            return
        self._last_members = members
        self._write_members(members)
        if self.registrar.index is not None:
            self._write_bootstrap(self.registrar.index)
        try:
            update_hosts_file(self.cfg.hosts_file, dns_name_mappings(members))
        except OSError:
            logger.exception("hosts file update failed")
        if not self.cfg.dns_names and self.process.alive():
            # Legacy IP mode: membership changes restart the service
            # (disruptive, like the reference's nodes.cfg rewrite +
            # IMEX restart; DNS mode below avoids it).
            self.process.restart()
            return
        self.process.ensure_started()
        # Nudge a RUNNING service only: a SIGUSR1 during interpreter
        # startup (before the handler is registered) would kill the
        # child. A freshly started child reads the members file itself.
        try:
            query(self.cfg.coordination_host or "127.0.0.1",
                  self.cfg.port, "STATUS", timeout=1.0)
        except OSError:
            logger.info("coordination service not answering yet; no nudge")
        else:
            self.process.signal(signal.SIGUSR1)
        logger.info("membership: %d/%d worker(s)",
                    len(members), self.cfg.num_workers)

    def run(self) -> int:
        logger.info(
            "compute-domain-daemon starting: cd=%s clique=%s node=%s",
            self.cfg.cd_uid, self.cfg.clique_id, self.cfg.node_name,
        )
        self._label_own_pod()
        index = self.registrar.register(status="NotReady")
        logger.info("registered as worker index %d", index)

        self.process.ensure_started()
        self.process.start_watchdog()
        self._informer.start()

        def terminate(*_):
            self._stop.set()
            self._kick.set()  # unblock the wait immediately

        signal.signal(signal.SIGTERM, terminate)
        signal.signal(signal.SIGINT, terminate)

        ready_reported = False
        last_sync = 0.0
        while not self._stop.is_set():
            # Wake on watch events. The short timeout only drives the
            # child-liveness Ready/NotReady flips (no informer event
            # fires when the local child dies); membership syncs happen
            # on kicks plus a RESYNC_INTERVAL_S fallback relist.
            kicked = self._kick.wait(LIVENESS_INTERVAL_S)
            self._kick.clear()
            if self._stop.is_set():
                break
            now = time.monotonic()
            try:
                if kicked or now - last_sync >= RESYNC_INTERVAL_S:
                    last_sync = now
                    self.sync_once()
                if self.process.alive() and not ready_reported:
                    self.registrar.set_status("Ready")
                    ready_reported = True
                    self._last_members = None  # re-sync with own Ready
                elif not self.process.alive() and ready_reported:
                    self.registrar.set_status("NotReady")
                    ready_reported = False
            except Exception:  # noqa: BLE001 - daemon must survive
                logger.exception("sync failed")
                last_sync = 0.0  # retry the sync on the next liveness tick
        self._informer.stop()
        self.registrar.deregister()
        self.process.stop()
        return 0


def check(config: DaemonConfig) -> int:
    """Probe: the coordination service must answer READY
    (reference `compute-domain-daemon check`, main.go:435-459)."""
    try:
        answer = query(config.coordination_host or "127.0.0.1",
                       config.port, "STATUS")
    except OSError as e:
        print(f"NOT_READY ({e})")
        return 1
    print(answer)
    return 0 if answer == "READY" else 1


def main(argv: list[str] | None = None) -> int:
    from ...pkg import logsetup  # noqa: PLC0415

    p = argparse.ArgumentParser(prog="compute-domain-daemon")
    p.add_argument("command", choices=["run", "check"])
    p.add_argument("-v", "--verbosity", type=int,
                   default=int(os.environ.get("V", "4")),
                   help="log verbosity (see pkg/logsetup.py) [V]")
    args = p.parse_args(argv)
    logsetup.setup(args.verbosity)
    config = DaemonConfig()
    if args.command == "check":
        return check(config)
    from ... import __version__  # noqa: PLC0415

    logsetup.log_startup(__name__, "compute-domain-daemon",
                         __version__, args)
    return Daemon(config).run()


if __name__ == "__main__":
    sys.exit(main())
