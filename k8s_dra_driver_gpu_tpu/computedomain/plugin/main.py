"""compute-domain-kubelet-plugin entry point.

Reference: cmd/compute-domain-kubelet-plugin/main.go (same flag pattern
as the chip plugin; driver name compute-domain.tpu.dra.dev).
"""

from __future__ import annotations

import argparse
import logging
import os

import sys

from ... import __version__
from ...pkg import logsetup
from ...pkg.debug import start_debug_signal_handlers, wait_for_termination
from ...pkg.dra.service import PluginServer
from ...pkg.healthcheck import HealthcheckServer
from ...pkg.kubeclient import FakeKubeClient, KubeClient
from ...pkg.metrics import DRARequestMetrics, MetricsServer
from .. import COMPUTE_DOMAIN_DRIVER_NAME
from .device_state import CDDeviceState
from .driver import CDDriver

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    env = os.environ.get
    p = argparse.ArgumentParser(prog="compute-domain-kubelet-plugin")
    p.add_argument("--node-name", default=env("NODE_NAME", ""))
    p.add_argument("--state-root",
                   default=env("STATE_ROOT", "/var/lib/tpu-dra/cd"))
    p.add_argument("--cdi-root", default=env("CDI_ROOT", "/var/run/cdi"))
    p.add_argument("--plugin-dir",
                   default=env("PLUGIN_DIR",
                               "/var/lib/kubelet/plugins/"
                               "compute-domain.tpu.dra.dev"))
    p.add_argument("--registry-dir",
                   default=env("REGISTRY_DIR",
                               "/var/lib/kubelet/plugins_registry"))
    p.add_argument("--clique-id", default=env("TPU_SLICE_ID", "0"),
                   help="identity of the ICI slice this host belongs to")
    p.add_argument("--driver-namespace",
                   default=env("DRIVER_NAMESPACE", "tpu-dra-driver"))
    p.add_argument("--metrics-port", type=int,
                   default=int(env("METRICS_PORT", "0")))
    p.add_argument("--healthcheck-port", type=int,
                   default=int(env("HEALTHCHECK_PORT", "0")))
    p.add_argument("-v", "--verbosity", type=int,
                   default=int(env("V", "4")),
                   help="log verbosity (see pkg/logsetup.py) [V]")
    p.add_argument("--kube-api", default=env("KUBE_API", ""),
                   help="API server URL override [KUBE_API]")
    p.add_argument("--standalone", action="store_true")
    p.add_argument("--version", action="version", version=__version__)
    return p


def run(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    logsetup.setup(args.verbosity)
    start_debug_signal_handlers()
    logsetup.log_startup(__name__, "compute-domain-kubelet-plugin",
                         __version__, args)

    node_name = args.node_name or os.uname().nodename
    metrics = DRARequestMetrics()
    from ...pkg.metrics import (  # noqa: PLC0415
        ResilienceMetrics,
        register_build_info,
    )
    from ...pkg.retry import RetryingKubeClient  # noqa: PLC0415

    register_build_info(metrics.registry)
    resilience = ResilienceMetrics(registry=metrics.registry)
    kube = RetryingKubeClient(
        FakeKubeClient() if args.standalone else KubeClient(
            host=args.kube_api or None),
        metrics=resilience,
    )
    state = CDDeviceState(
        root=args.state_root,
        kube=kube,
        node_name=node_name,
        clique_id=args.clique_id,
        cdi_root=args.cdi_root,
        driver_namespace=args.driver_namespace,
    )
    driver = CDDriver(state, kube, node_name, metrics=metrics,
                      resilience=resilience)
    driver.publish_resources()
    driver.start_background()

    server = PluginServer(
        COMPUTE_DOMAIN_DRIVER_NAME,
        plugin_dir=args.plugin_dir,
        registry_dir=args.registry_dir,
        prepare_fn=driver.prepare_resource_claims,
        unprepare_fn=driver.unprepare_resource_claims,
    )
    server.start()

    extras = []
    if args.metrics_port > 0:
        m = MetricsServer(metrics.registry, host="0.0.0.0",
                          port=args.metrics_port)
        m.start()
        extras.append(m)
    if args.healthcheck_port > 0:
        h = HealthcheckServer(server.plugin_socket, server.registry_socket,
                              host="0.0.0.0", port=args.healthcheck_port)
        h.start()
        extras.append(h)

    logger.info("serving CD DRA on %s", server.plugin_socket)
    try:
        wait_for_termination()
    finally:
        server.stop()
        driver.stop_background()
        for e in extras:
            e.stop()
    return 0


if __name__ == "__main__":
    sys.exit(run())
