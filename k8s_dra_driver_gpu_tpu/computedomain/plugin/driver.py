"""CD plugin driver: the codependent-Prepare retry engine.

Reference: cmd/compute-domain-kubelet-plugin/driver.go:40-233 -- unlike
the TPU/GPU plugin, every claim runs through a retry loop bounded by
ErrorRetryMaxTimeout=45s with exponential backoff, because Prepare is
*codependent*: a workload-channel Prepare can only succeed after the CD
daemon on this node is Ready, which itself requires another (daemon)
Prepare that is triggered BY the first Prepare's node-label side effect.
permanentError short-circuits (:56-60); work is not serialized (:89-96).
"""

from __future__ import annotations

import logging
import time

from ...kubeletplugin.claim import ResourceClaim
from ...pkg import flightrecorder, tracing
from ...pkg.events import emit_warning_event
from ...pkg.kubeclient import KubeError, NotFoundError
from ...pkg.retry import RETRIABLE_STATUSES
from ...pkg.metrics import DRARequestMetrics
from ...pkg.sliceutil import publish_resource_slices
from ...pkg.workqueue import PermanentError, RateLimiter
from .. import COMPUTE_DOMAIN_DRIVER_NAME
from .device_state import CDDeviceState, RetryableError

logger = logging.getLogger(__name__)

ERROR_RETRY_MAX_TIMEOUT_S = 45.0
RETRY_LIMITER = RateLimiter(base_delay=0.25, max_delay=3.0, jitter=0.2)


STALE_DIR_GC_INTERVAL_S = 600.0


class CDDriver:
    def __init__(
        self,
        state: CDDeviceState,
        kube,
        node_name: str,
        metrics: DRARequestMetrics | None = None,
        retry_timeout: float = ERROR_RETRY_MAX_TIMEOUT_S,
        resilience=None,  # pkg.metrics.ResilienceMetrics | None
        recovery_metrics=None,  # pkg.metrics.RecoveryMetrics | None
    ):
        self.state = state
        self.kube = kube
        self.node_name = node_name
        self.metrics = metrics or DRARequestMetrics()
        self.retry_timeout = retry_timeout
        self.resilience = resilience
        self.gang_aborts = 0  # lifetime rendezvous-deadline aborts
        self._gc_stop = None
        # Cross-layer reconcile sweep (kubeletplugin/reconcile.py):
        # stale CD claim records unprepare (dropping the daemon node
        # label with the last channel), orphaned CD CDI specs unwind.
        from ...kubeletplugin.reconcile import (  # noqa: PLC0415
            CDStateReconciler,
        )

        self.reconciler = CDStateReconciler(
            state, kube, metrics=recovery_metrics)

    def start_background(self) -> None:
        """Periodic stale-domain-dir GC (computedomain.go:384) + the
        cross-layer CD reconcile sweep."""
        import threading  # noqa: PLC0415

        self._gc_stop = threading.Event()

        def loop():
            while not self._gc_stop.wait(STALE_DIR_GC_INTERVAL_S):
                try:
                    self.state.cleanup_stale_domain_dirs()
                except Exception:  # noqa: BLE001
                    logger.exception("stale domain dir GC failed")
                try:
                    self.reconciler.reconcile_once()
                except Exception:  # noqa: BLE001
                    logger.exception("CD recovery sweep failed")

        threading.Thread(target=loop, name="cd-domain-gc",
                         daemon=True).start()

    def stop_background(self) -> None:
        if self._gc_stop is not None:
            self._gc_stop.set()
        self.state.stop()

    def _fetch_claim(self, ref) -> ResourceClaim:
        uid = getattr(ref, "uid", None) or ref.get("uid")
        namespace = getattr(ref, "namespace", None) or ref.get("namespace")
        name = getattr(ref, "name", None) or ref.get("name")
        obj = self.kube.get(
            "resource.k8s.io", "v1", "resourceclaims", name,
            namespace=namespace,
        )
        if obj.get("metadata", {}).get("uid") != uid:
            raise PermanentError(f"claim {namespace}/{name} UID mismatch")
        return ResourceClaim.from_dict(obj, driver=COMPUTE_DOMAIN_DRIVER_NAME)

    def prepare_resource_claims(self, claim_refs: list) -> dict:
        out = {}
        for ref in claim_refs:
            uid = getattr(ref, "uid", None) or ref.get("uid")
            try:
                with self.metrics.observe("NodePrepareResources"):
                    out[uid] = (self._prepare_with_retry(ref), "")
            except Exception as e:  # noqa: BLE001 - wire boundary
                logger.warning("prepare failed for %s: %s", uid, e)
                out[uid] = ([], str(e))
        return out

    def _prepare_with_retry(self, ref) -> list[dict]:
        """Bounded retry loop (the reference's per-call retry engine with
        ErrorRetryMaxTimeout; driver.go:165-233).

        The retry budget IS the gang-prepare deadline: a channel
        Prepare blocks on the CD rendezvous (every node of the gang
        registered + Ready), so a straggler node parks every punctual
        one in this loop. When the budget blows on a RETRIABLE
        condition, the node unwinds its own prepared state (CDI spec,
        checkpoint record, daemon node label -- see
        CDDeviceState.unwind_failed_prepare) and reports a retriable
        NodePrepareResources failure, instead of hanging the gang with
        a half-labeled fleet. Kubelet retries the whole Prepare later;
        an intact gang then goes clean end to end."""
        uid = getattr(ref, "uid", None) or ref.get("uid")
        deadline = time.monotonic() + self.retry_timeout
        t0 = time.monotonic()
        failures = 0
        while True:
            try:
                claim = self._fetch_claim(ref)
                cdi_ids = self.state.prepare(claim)
                trace_id = tracing.trace_id_of(claim.annotations)
                self.metrics.slo.observe(
                    "prepare", time.monotonic() - t0, trace_id)
                flightrecorder.default().record(
                    uid, "cd_prepare_done",
                    alias=f"{claim.namespace}/{claim.name}",
                    trace_id=trace_id, retries=failures,
                    ms=round((time.monotonic() - t0) * 1e3, 2))
                return [
                    {
                        "request_names": [r.request],
                        "pool_name": self.node_name,
                        "device_name": r.device,
                        "cdi_device_ids": cdi_ids,
                    }
                    for r in claim.results
                ]
            except PermanentError:
                raise
            except (RetryableError, KubeError, OSError,
                    TimeoutError) as e:
                # Retriable here: the gang gate (RetryableError), a
                # claim not visible yet (404), connection trouble, and
                # 429/5xx incl. CircuitOpenError from the retrying
                # client -- an apiserver outage mid-gang is bounded by
                # the same deadline instead of surfacing a raw wire
                # error. A PERMANENT 4xx (403 RBAC, 400/422) must NOT
                # burn the 45s budget reporting itself 'retriable'.
                if isinstance(e, KubeError) and \
                        not isinstance(e, NotFoundError) and \
                        e.status not in RETRIABLE_STATUSES:
                    raise
                failures += 1
                delay = RETRY_LIMITER.delay_for(failures)
                if time.monotonic() + delay >= deadline:
                    self._abort_gang_prepare(uid, e, ref=ref)
                    raise TimeoutError(
                        f"gang prepare deadline ({self.retry_timeout}s) "
                        f"exceeded; node state unwound, retriable: {e}"
                    ) from e
                logger.info("prepare retry %d in %.2fs: %s",
                            failures, delay, e)
                time.sleep(delay)

    def _abort_gang_prepare(self, uid: str, cause: Exception,
                            ref=None) -> None:
        """Deadline blown: unwind this node's own half-prepared state so
        a kubelet retry starts clean (and a dissolved gang leaves no
        daemon pods pinned by a stale node label). The operator gets
        the claim's whole flight-recorder timeline in the log plus a
        create-once Warning Event on the claim -- no archaeology across
        four binaries' log streams."""
        self.gang_aborts += 1
        if self.resilience is not None:
            self.resilience.gang_aborts.inc()
        flight = flightrecorder.default()
        flight.record(uid, "gang_abort", error=str(cause)[:200],
                      deadline_s=self.retry_timeout)
        logger.warning(
            "gang prepare abort for claim %s after %.0fs: %s "
            "(unwinding node-local state); flight record:\n%s",
            uid, self.retry_timeout, cause, flight.dump(uid),
        )
        self._gang_abort_event(uid, ref, cause)
        # Incident bundle (pkg/doctor, TPU_DRA_DOCTOR_DIR-gated,
        # rate-limited): a gang abort is exactly the moment the
        # bounded rings hold the evidence -- snapshot them before the
        # retry churn ages them out. Never blocks or fails the unwind.
        from ...pkg import doctor  # noqa: PLC0415

        doctor.auto_bundle("gang-abort", claim=uid)
        try:
            self.state.unwind_failed_prepare(uid)
        except Exception:  # noqa: BLE001 - best-effort unwind
            logger.exception("gang-abort unwind failed for %s", uid)

    def _gang_abort_event(self, uid: str, ref, cause: Exception) -> None:
        """Deduped Warning Event on the claim (deterministic name =
        create-once: repeat aborts for the same claim hit 409 instead
        of spamming). Best-effort -- the unwind must proceed even when
        the apiserver is the thing that is down."""
        name = getattr(ref, "name", None) or (
            ref.get("name") if isinstance(ref, dict) else "")
        namespace = getattr(ref, "namespace", None) or (
            ref.get("namespace") if isinstance(ref, dict) else "") or \
            "default"
        if not name:
            return
        emit_warning_event(
            self.kube, event_name=f"{name}.gang-abort",
            namespace=namespace, reason="GangPrepareAborted",
            message=(
                f"gang prepare deadline ({self.retry_timeout:.0f}s) "
                f"exceeded on node {self.node_name}: {str(cause)[:300]}; "
                "node-local state unwound, kubelet will retry "
                "(timeline at /debug/claims/<uid> on the node plugin)"),
            involved_kind="ResourceClaim", involved_name=name,
            involved_uid=uid, component="tpu-dra-cd-plugin")

    def unprepare_resource_claims(self, claim_refs: list) -> dict:
        out = {}
        for ref in claim_refs:
            uid = getattr(ref, "uid", None) or ref.get("uid")
            try:
                with self.metrics.observe("NodeUnprepareResources"):
                    self.state.unprepare(uid)
                out[uid] = ""
            except Exception as e:  # noqa: BLE001 - wire boundary
                logger.exception("unprepare failed for %s", uid)
                out[uid] = str(e)
        return out

    # -- ResourceSlice publication ------------------------------------------------

    def generate_resource_slices(self) -> list[dict]:
        return [{
            "apiVersion": "resource.k8s.io/v1",
            "kind": "ResourceSlice",
            "metadata": {
                "name": f"{self.node_name}-{COMPUTE_DOMAIN_DRIVER_NAME}",
            },
            "spec": {
                "driver": COMPUTE_DOMAIN_DRIVER_NAME,
                "nodeName": self.node_name,
                "pool": {
                    "name": self.node_name,
                    "resourceSliceCount": 1,
                    "generation": 1,
                },
                "devices": self.state.allocatable_devices(),
            },
        }]

    def publish_resources(self) -> None:
        publish_resource_slices(self.kube, self.generate_resource_slices())
