"""CD plugin driver: the codependent-Prepare retry engine.

Reference: cmd/compute-domain-kubelet-plugin/driver.go:40-233 -- unlike
the TPU/GPU plugin, every claim runs through a retry loop bounded by
ErrorRetryMaxTimeout=45s with exponential backoff, because Prepare is
*codependent*: a workload-channel Prepare can only succeed after the CD
daemon on this node is Ready, which itself requires another (daemon)
Prepare that is triggered BY the first Prepare's node-label side effect.
permanentError short-circuits (:56-60); work is not serialized (:89-96).
"""

from __future__ import annotations

import logging
import time

from ...kubeletplugin.claim import ResourceClaim
from ...pkg.kubeclient import NotFoundError
from ...pkg.metrics import DRARequestMetrics
from ...pkg.sliceutil import publish_resource_slices
from ...pkg.workqueue import PermanentError, RateLimiter
from .. import COMPUTE_DOMAIN_DRIVER_NAME
from .device_state import CDDeviceState, RetryableError

logger = logging.getLogger(__name__)

ERROR_RETRY_MAX_TIMEOUT_S = 45.0
RETRY_LIMITER = RateLimiter(base_delay=0.25, max_delay=3.0, jitter=0.2)


STALE_DIR_GC_INTERVAL_S = 600.0


class CDDriver:
    def __init__(
        self,
        state: CDDeviceState,
        kube,
        node_name: str,
        metrics: DRARequestMetrics | None = None,
        retry_timeout: float = ERROR_RETRY_MAX_TIMEOUT_S,
    ):
        self.state = state
        self.kube = kube
        self.node_name = node_name
        self.metrics = metrics or DRARequestMetrics()
        self.retry_timeout = retry_timeout
        self._gc_stop = None

    def start_background(self) -> None:
        """Periodic stale-domain-dir GC (computedomain.go:384)."""
        import threading  # noqa: PLC0415

        self._gc_stop = threading.Event()

        def loop():
            while not self._gc_stop.wait(STALE_DIR_GC_INTERVAL_S):
                try:
                    self.state.cleanup_stale_domain_dirs()
                except Exception:  # noqa: BLE001
                    logger.exception("stale domain dir GC failed")

        threading.Thread(target=loop, name="cd-domain-gc",
                         daemon=True).start()

    def stop_background(self) -> None:
        if self._gc_stop is not None:
            self._gc_stop.set()
        self.state.stop()

    def _fetch_claim(self, ref) -> ResourceClaim:
        uid = getattr(ref, "uid", None) or ref.get("uid")
        namespace = getattr(ref, "namespace", None) or ref.get("namespace")
        name = getattr(ref, "name", None) or ref.get("name")
        obj = self.kube.get(
            "resource.k8s.io", "v1", "resourceclaims", name,
            namespace=namespace,
        )
        if obj.get("metadata", {}).get("uid") != uid:
            raise PermanentError(f"claim {namespace}/{name} UID mismatch")
        return ResourceClaim.from_dict(obj, driver=COMPUTE_DOMAIN_DRIVER_NAME)

    def prepare_resource_claims(self, claim_refs: list) -> dict:
        out = {}
        for ref in claim_refs:
            uid = getattr(ref, "uid", None) or ref.get("uid")
            try:
                with self.metrics.observe("NodePrepareResources"):
                    out[uid] = (self._prepare_with_retry(ref), "")
            except Exception as e:  # noqa: BLE001 - wire boundary
                logger.warning("prepare failed for %s: %s", uid, e)
                out[uid] = ([], str(e))
        return out

    def _prepare_with_retry(self, ref) -> list[dict]:
        """Bounded retry loop (the reference's per-call retry engine with
        ErrorRetryMaxTimeout; driver.go:165-233)."""
        deadline = time.monotonic() + self.retry_timeout
        failures = 0
        while True:
            try:
                claim = self._fetch_claim(ref)
                cdi_ids = self.state.prepare(claim)
                return [
                    {
                        "request_names": [r.request],
                        "pool_name": self.node_name,
                        "device_name": r.device,
                        "cdi_device_ids": cdi_ids,
                    }
                    for r in claim.results
                ]
            except PermanentError:
                raise
            except (RetryableError, NotFoundError, OSError) as e:
                failures += 1
                delay = RETRY_LIMITER.delay_for(failures)
                if time.monotonic() + delay >= deadline:
                    raise TimeoutError(
                        f"prepare retry budget ({self.retry_timeout}s) "
                        f"exhausted: {e}"
                    ) from e
                logger.info("prepare retry %d in %.2fs: %s",
                            failures, delay, e)
                time.sleep(delay)

    def unprepare_resource_claims(self, claim_refs: list) -> dict:
        out = {}
        for ref in claim_refs:
            uid = getattr(ref, "uid", None) or ref.get("uid")
            try:
                with self.metrics.observe("NodeUnprepareResources"):
                    self.state.unprepare(uid)
                out[uid] = ""
            except Exception as e:  # noqa: BLE001 - wire boundary
                logger.exception("unprepare failed for %s", uid)
                out[uid] = str(e)
        return out

    # -- ResourceSlice publication ------------------------------------------------

    def generate_resource_slices(self) -> list[dict]:
        return [{
            "apiVersion": "resource.k8s.io/v1",
            "kind": "ResourceSlice",
            "metadata": {
                "name": f"{self.node_name}-{COMPUTE_DOMAIN_DRIVER_NAME}",
            },
            "spec": {
                "driver": COMPUTE_DOMAIN_DRIVER_NAME,
                "nodeName": self.node_name,
                "pool": {
                    "name": self.node_name,
                    "resourceSliceCount": 1,
                    "generation": 1,
                },
                "devices": self.state.allocatable_devices(),
            },
        }]

    def publish_resources(self) -> None:
        publish_resource_slices(self.kube, self.generate_resource_slices())
