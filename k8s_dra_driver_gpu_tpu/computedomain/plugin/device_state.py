"""CD plugin DeviceState: channel + daemon claim preparation.

Reference: cmd/compute-domain-kubelet-plugin/device_state.go --
allocatables are IMEX channels + one daemon device (nvlib.go:167-194);
applyComputeDomainChannelConfig (:544): double-alloc guard, namespace
spoof guard (PermanentError, :577 + computedomain.go:296), node label
add (the DaemonSet trigger), BLOCK until CD Ready, then CDI-inject the
channel; applyComputeDomainDaemonConfig (:594): per-domain config dir +
daemon identity injection.

TPU translation: a "channel" is slice-membership -- the workload gets
the JAX bootstrap contract (coordinator address, process id, worker
hostnames via the daemon's bootstrap file) instead of an
/dev/nvidia-caps-imex-channels device node. The daemon device carries
the domain identity env the compute-domain-daemon needs.
"""

from __future__ import annotations

import logging
import os
import threading

from ...api.configs import (
    ComputeDomainChannelConfig,
    ComputeDomainDaemonConfig,
)
from ...api.decode import strict_decode
from ...kubeletplugin.cdi import CDIHandler, ContainerEdits
from ...kubeletplugin.checkpoint import (
    CheckpointedClaim,
    CheckpointedDevice,
    CheckpointManager,
    ClaimState,
)
from ...kubeletplugin.claim import ResourceClaim
from ...pkg import tracing
from ...pkg.analysis.statemachine import SINGLE_PHASE_POLICY
from ...pkg.kubeclient import KubeError, NotFoundError
from ...pkg.timing import SegmentTimer
from ...pkg.workqueue import PermanentError
from .. import (
    API_GROUP,
    API_VERSION,
    NODE_LABEL,
    daemon_dns_name,
    expected_workers,
)

logger = logging.getLogger(__name__)

MAX_CHANNELS = 128
DAEMON_DEVICE = "daemon"
DOMAIN_STATE_ROOT = "/var/run/tpu-domain"


class RetryableError(RuntimeError):
    """Prepare must be retried (e.g. CD not Ready yet)."""


class CDDeviceState:
    def __init__(
        self,
        root: str,
        kube,
        node_name: str,
        clique_id: str = "0",
        cdi_root: str | None = None,
        driver_namespace: str = "tpu-dra-driver",
        boot_id: str | None = None,
        use_informer: bool = True,
    ):
        os.makedirs(root, exist_ok=True)
        self.root = root
        self.kube = kube
        self.node_name = node_name
        self.clique_id = clique_id
        self.ns = driver_namespace
        self._lock = threading.Lock()
        # CD prepares mutate no device state, so the lifecycle is
        # single-phase: absent -> PrepareCompleted -> absent. The
        # runtime validator makes a PrepareStarted in a CD checkpoint
        # (someone porting two-phase code here) fail loudly.
        self._checkpoint = CheckpointManager(
            root, boot_id=boot_id,
            transition_policy=SINGLE_PHASE_POLICY)
        self._cdi = CDIHandler(cdi_root=cdi_root or os.path.join(root, "cdi"))
        # ComputeDomains are read through an informer cache: Prepare sits
        # in a retry loop for up to 45s, and a full list() per attempt
        # hammers the API server at scale (reference uses informers,
        # computedomain.go:118-127). The cache is uid-indexed, O(1) per
        # lookup; a periodic relist reconciles watch gaps.
        self._cd_informer = None
        if use_informer:
            from ...pkg.informer import Informer  # noqa: PLC0415

            self._cd_informer = Informer(
                kube, API_GROUP, API_VERSION, "computedomains",
                kind="ComputeDomain",
            ).start()

    def stop(self) -> None:
        """Stop background machinery (the CD informer's watch/resync)."""
        if self._cd_informer is not None:
            self._cd_informer.stop()

    # -- allocatable devices ----------------------------------------------------

    def allocatable_devices(self) -> list[dict]:
        """channel-0..N + the daemon device (nvlib.go:167-194).

        Every device carries the node's slice identity (``cliqueId``,
        from --clique-id/TPU_SLICE_ID): a CEL selector or
        ``matchAttribute`` on it pins channel claims to one ICI slice,
        and cross-slice tooling can see which slice each published
        channel belongs to (SURVEY §2.9 DCN attribute annotation)."""
        devices = [
            {
                "name": DAEMON_DEVICE,
                "attributes": {
                    "type": {"string": "daemon"},
                    "cliqueId": {"string": self.clique_id},
                },
                "capacity": {},
            }
        ]
        for i in range(MAX_CHANNELS):
            devices.append(
                {
                    "name": f"channel-{i}",
                    "attributes": {
                        "type": {"string": "channel"},
                        "channel": {"int": i},
                        "cliqueId": {"string": self.clique_id},
                    },
                    "capacity": {},
                }
            )
        return devices

    # -- prepare ------------------------------------------------------------------

    def prepare(self, claim: ResourceClaim) -> list[str]:
        # Per-segment timings (the reference CD plugin logs the same
        # t_prep_* breakdown); the segments double as the fault-
        # injection seams the robustness suite uses. The claim's
        # traceparent annotation (stamped by the scheduler's commit)
        # parents these segments into the cross-binary trace.
        timer = SegmentTimer("cd_prepare", claim.uid,
                             parent=tracing.extract(claim.annotations))
        try:
            return self._prepare_locked(claim, timer)
        finally:
            # Like the chip plugin's prepare: the error / idempotent
            # paths finish the operation span too (a raised segment
            # would otherwise export children whose cd_prepare parent
            # never appears in /debug/traces).
            timer.done()

    def _prepare_locked(self, claim: ResourceClaim,
                        timer: SegmentTimer) -> list[str]:
        with self._lock:
            with timer.segment("cd_get_checkpoint"):
                cp = self._checkpoint.get()
            existing = cp.claims.get(claim.uid)
            if existing and existing.state == ClaimState.PREPARE_COMPLETED.value:
                return [i for d in existing.devices for i in d.cdi_device_ids]

            cfg = self._decode_config(claim)
            if isinstance(cfg, ComputeDomainChannelConfig):
                with timer.segment("cd_prepare_channel"):
                    edits, devices = self._prepare_channel(claim, cfg)
            elif isinstance(cfg, ComputeDomainDaemonConfig):
                with timer.segment("cd_prepare_daemon"):
                    edits, devices = self._prepare_daemon(claim, cfg)
            else:
                raise PermanentError(
                    f"config kind {type(cfg).__name__} not valid for "
                    "compute-domain claims"
                )

            device_edits = {d: ContainerEdits() for d in devices}
            with timer.segment("cd_write_cdi_spec"):
                cdi_ids = self._cdi.create_claim_spec_file(
                    claim.uid, device_edits, edits
                )

            def complete(c):
                c.claims[claim.uid] = CheckpointedClaim(
                    uid=claim.uid,
                    namespace=claim.namespace,
                    name=claim.name,
                    state=ClaimState.PREPARE_COMPLETED.value,
                    devices=[
                        CheckpointedDevice(
                            canonical_name=name, kind="cd",
                            cdi_device_ids=[cid],
                        )
                        for name, cid in zip(sorted(devices), cdi_ids)
                    ],
                )

            with timer.segment("cd_checkpoint_write"):
                self._checkpoint.update(complete)
            from ...pkg import flightrecorder  # noqa: PLC0415

            flightrecorder.default().record(
                claim.uid, "cd_prepare_segments",
                trace_id=timer.trace_id,
                **{f"{name}_ms": round(dt * 1e3, 2)
                   for name, dt in sorted(timer.segments.items())})
            return cdi_ids

    def _decode_config(self, claim: ResourceClaim):
        for oc in claim.configs:
            try:
                cfg = strict_decode(oc.parameters)
            except Exception as e:
                raise PermanentError(e) from e
            cfg.normalize()
            cfg.validate()
            return cfg
        raise PermanentError("compute-domain claim carries no opaque config")

    def _get_cd(self, domain_id: str) -> dict:
        if self._cd_informer is not None:
            cd = self._cd_informer.get_by_uid(domain_id)
            if cd is not None:
                return cd
        else:
            for cd in self.kube.list(API_GROUP, API_VERSION,
                                     "computedomains"):
                if cd["metadata"].get("uid") == domain_id:
                    return cd
        raise RetryableError(f"ComputeDomain {domain_id} not found (yet)")

    def _prepare_channel(
        self, claim: ResourceClaim, cfg: ComputeDomainChannelConfig
    ):
        cd = self._get_cd(cfg.domain_id)
        # Cross-namespace spoof guard: a claim may only join a CD living
        # in its own namespace (device_state.go:577, PermanentError).
        if cd["metadata"].get("namespace", "default") != claim.namespace:
            raise PermanentError(
                f"ComputeDomain {cd['metadata']['name']} namespace "
                f"{cd['metadata'].get('namespace')!r} does not match claim "
                f"namespace {claim.namespace!r}"
            )
        self._assert_channel_not_allocated(claim)
        self._add_node_label(cfg.domain_id)
        node = self._assert_cd_ready(cd)  # raises RetryableError until ready

        channels = [r.device for r in claim.results]
        # The JAX coordinator port -- NOT the daemon's rendezvous port:
        # workload process 0 binds this itself (jax.distributed starts
        # the coordination service on process 0), so it must be free on
        # the node. The daemon's STATUS/MEMBERS service keeps its own
        # port (COORDINATION_PORT).
        from .. import JAX_COORDINATOR_PORT  # noqa: PLC0415

        port = int(os.environ.get("JAX_COORDINATOR_PORT",
                                  str(JAX_COORDINATOR_PORT)))
        layout = self._slice_layout(cd, node)
        # Coordinator by IP: workload pods have no resolver entry for the
        # daemon DNS names (those live in the daemons' own /etc/hosts), so
        # hand out global worker 0's registered pod IP directly; the
        # full name<->IP map rides the mounted members.json for consumers
        # that want stable names.
        coordinator_host = layout["hostnames"][0]
        hostnames = ",".join(layout["hostnames"])
        env = [
            f"COMPUTE_DOMAIN_UUID={cfg.domain_id}",
            f"TPU_COORDINATOR_ADDRESS={coordinator_host}:{port}",
            f"TPU_PROCESS_ID={layout['process_id']}",
            f"TPU_NUM_PROCESSES={layout['num_processes']}",
            f"TPU_WORKER_HOSTNAMES={hostnames}",
            "TPU_DOMAIN_CHANNELS="
            + ("all" if cfg.allocation_mode == "All"
               else ",".join(sorted(channels))),
        ]
        if layout["num_slices"] > 1:
            # Cross-slice (multislice) DCN contract, MEGASCALE-style:
            # one jax.distributed world spans every slice (global
            # process ids above); libtpu's DCN transport layer reads
            # the MEGASCALE_* set. Slice order = sorted clique ids;
            # the DCN coordinator is global worker 0's host.
            from .. import MEGASCALE_PORT  # noqa: PLC0415

            ms_port = int(os.environ.get("MEGASCALE_PORT_OVERRIDE",
                                         str(MEGASCALE_PORT)))
            env += [
                f"TPU_NUM_SLICES={layout['num_slices']}",
                f"TPU_SLICE_ID={layout['slice_id']}",
                f"MEGASCALE_NUM_SLICES={layout['num_slices']}",
                f"MEGASCALE_SLICE_ID={layout['slice_id']}",
                f"MEGASCALE_COORDINATOR_ADDRESS={coordinator_host}"
                f":{ms_port}",
                f"MEGASCALE_PORT={ms_port}",
            ]
        edits = ContainerEdits(
            env=env,
            # The daemon's bootstrap/membership files for this domain,
            # read-only. Host source must match what _prepare_daemon
            # mounts INTO the daemon (same per-domain dir).
            mounts=[(
                os.path.join(self.root, "domains", cfg.domain_id),
                DOMAIN_STATE_ROOT, True,
            )],
        )
        return edits, channels

    def _slice_layout(self, cd: dict, node: dict) -> dict:
        """Global (slice-major) worker layout of a possibly multi-slice
        domain.

        Worker addresses are POSITIONAL BY GLOBAL PROCESS ID (libtpu's
        multi-host contract): entry i must be worker i's address and
        the list length must equal TPU_NUM_PROCESSES, so both derive
        from the gang size the SPEC declares -- never from whichever
        subset of nodes happens to be registered. Slices are ordered by
        sorted clique id; global id = slice_index * per_slice +
        clique-local index. Registered pod IPs are emitted (workloads
        can't resolve daemon DNS names); an unregistered slot falls
        back to its stable per-clique DNS name.

        Raises PermanentError when numNodes does not split evenly over
        numSlices, RetryableError while the registered cliques don't
        yet match the declared slice count (the Ready gate usually
        guarantees they do).
        """
        from .. import expected_slices, per_slice_workers  # noqa: PLC0415

        expected = self._expected_workers(cd)
        num_slices = expected_slices(cd.get("spec", {}))
        try:
            per_slice = per_slice_workers(cd.get("spec", {}))
        except ValueError as e:
            raise PermanentError(
                f"ComputeDomain {cd['metadata']['name']}: {e}") from e
        nodes = cd.get("status", {}).get("nodes", [])
        cliques = sorted({n.get("cliqueID", "") or "0" for n in nodes})
        if num_slices > 1 and len(cliques) != num_slices:
            raise RetryableError(
                f"ComputeDomain {cd['metadata']['name']}: {len(cliques)}"
                f" clique(s) registered, want numSlices={num_slices}")
        if num_slices == 1:
            # Single slice: exactly one clique id may be registered --
            # collapsing several onto slice 0 would collide their
            # clique-local indices in by_gid and hand duplicate
            # TPU_PROCESS_ID values to different pods.
            if len(cliques) > 1:
                raise RetryableError(
                    f"ComputeDomain {cd['metadata']['name']}: numSlices=1"
                    f" but {len(cliques)} cliques registered ({cliques});"
                    " refusing to assign colliding process ids")
            cliques = cliques or ["0"]
            slice_of = dict.fromkeys(cliques, 0)
        else:
            slice_of = {c: i for i, c in enumerate(cliques)}
        by_gid: dict[int, dict] = {}
        for n in nodes:
            idx = n.get("index", -1)
            si = slice_of.get(n.get("cliqueID", "") or "0")
            if idx is None or idx < 0 or idx >= per_slice or si is None:
                continue
            by_gid[si * per_slice + idx] = n
        hostnames = []
        for gid in range(expected):
            entry = by_gid.get(gid)
            if entry and entry.get("ipAddress"):
                hostnames.append(entry["ipAddress"])
            else:
                si, idx = divmod(gid, per_slice)
                clique = (cliques[si] if si < len(cliques) else str(si))
                hostnames.append(
                    daemon_dns_name(idx) if num_slices == 1
                    else f"{daemon_dns_name(idx)}.{clique}")
        my_slice = slice_of.get(node.get("cliqueID", "") or "0", 0)
        return {
            "num_processes": expected,
            "num_slices": num_slices,
            "per_slice": per_slice,
            "slice_id": my_slice,
            "process_id": my_slice * per_slice + node.get("index", 0),
            "hostnames": hostnames,
        }

    def _ready_nodes(self, cd: dict) -> list[dict]:
        return [
            n for n in cd.get("status", {}).get("nodes", [])
            if n.get("status") == "Ready"
        ]

    def _assert_cd_ready(self, cd: dict) -> dict:
        """Our node must be registered and the domain Ready
        (AssertComputeDomainReady, computedomain.go:238-295)."""
        status = cd.get("status", {})
        node = next(
            (n for n in status.get("nodes", [])
             if n.get("name") == self.node_name),
            None,
        )
        if status.get("status") != "Ready" or node is None:
            raise RetryableError(
                f"ComputeDomain {cd['metadata']['name']} not ready on "
                f"{self.node_name} (status={status.get('status')})"
            )
        return node

    def _assert_channel_not_allocated(self, claim: ResourceClaim) -> None:
        """Checkpoint-backed double-alloc guard (device_state.go:729)."""
        cp = self._checkpoint.get()
        wanted = {r.device for r in claim.results}
        for other in cp.claims.values():
            if other.uid == claim.uid:
                continue
            held = {d.canonical_name for d in other.devices}
            both = wanted & held
            if both:
                raise PermanentError(
                    f"channel(s) {sorted(both)} already allocated to "
                    f"claim {other.uid}"
                )

    def _add_node_label(self, cd_uid: str) -> None:
        """Label this node so the per-CD DaemonSet schedules here
        (computedomain.go:312-364) -- THE rendezvous step."""
        try:
            self.kube.patch(
                "", "v1", "nodes", self.node_name,
                {"metadata": {"labels": {NODE_LABEL: cd_uid}}},
            )
        except NotFoundError:
            # Node objects may not exist in bare test environments.
            logger.warning("node %s not found for labeling", self.node_name)

    def _prepare_daemon(
        self, claim: ResourceClaim, cfg: ComputeDomainDaemonConfig
    ):
        cd = self._get_cd(cfg.domain_id)
        domain_dir = os.path.join(self.root, "domains", cfg.domain_id)
        os.makedirs(domain_dir, exist_ok=True)
        # The daemon's quorum is CLIQUE-LOCAL: its rendezvous service
        # flips READY when its own slice's workers are all registered;
        # cross-slice readiness is the controller's aggregation. So a
        # multi-slice domain hands each daemon numNodes/numSlices.
        from .. import per_slice_workers  # noqa: PLC0415

        try:
            expected = per_slice_workers(cd.get("spec", {}))
        except ValueError as e:
            raise PermanentError(
                f"ComputeDomain {cd['metadata']['name']}: {e}") from e
        edits = ContainerEdits(
            env=[
                f"COMPUTE_DOMAIN_UUID={cfg.domain_id}",
                f"COMPUTE_DOMAIN_NAME={cd['metadata']['name']}",
                f"COMPUTE_DOMAIN_NAMESPACE={cd['metadata'].get('namespace', 'default')}",
                f"CLIQUE_ID={self.clique_id}",
                f"NODE_NAME={self.node_name}",
                f"COMPUTE_DOMAIN_NUM_WORKERS={expected}",
                f"DOMAIN_STATE_DIR={DOMAIN_STATE_ROOT}",
            ],
            mounts=[(domain_dir, DOMAIN_STATE_ROOT, False)],
        )
        return edits, [DAEMON_DEVICE]

    def _expected_workers(self, cd: dict) -> int:
        return expected_workers(cd.get("spec", {}))

    # -- unprepare ------------------------------------------------------------------

    def unprepare(self, claim_uid: str) -> None:
        with self._lock:
            cp = self._checkpoint.get()
            if claim_uid not in cp.claims:
                # Single-phase prepare: a crash between the CDI write
                # and the (only) checkpoint write leaves a spec file
                # with no claim record -- delete it here so claim
                # deletion cleans the orphan (idempotent).
                self._cdi.delete_claim_spec_file(claim_uid)
                return
            self._cdi.delete_claim_spec_file(claim_uid)
            self._checkpoint.update(
                lambda c: c.claims.pop(claim_uid, None)
            )
            # Last CHANNEL claim gone: drop the node label so the daemon
            # pod drains (computedomain.go:312-364 removal path). The
            # daemon's own claim must not keep the label alive -- the
            # daemon only exists because of the label.
            self._drop_node_label_if_unused()

    def unwind_failed_prepare(self, claim_uid: str) -> None:
        """Gang-abort unwind: tear down whatever a FAILED (never
        completed) prepare left on this node -- the CDI spec, any
        checkpoint record, and (conditionally) the daemon node label.

        The label needs care in both directions. While the
        ComputeDomain still EXISTS, the label must SURVIVE the abort:
        it is the DaemonSet trigger, i.e. the very bootstrap that lets
        the kubelet's next retry find a Ready gang -- dropping it on
        every blown deadline would kill each node's daemon out of
        phase and livelock a slow gang. But once the CD is GONE (the
        user deleted a domain that never formed), the label is a
        permanent leak that pins a daemon pod to a dead gang -- THAT
        is what a blown deadline must clean up, because no unprepare
        ever comes for a claim that never prepared.
        Idempotent; safe to call for claims that never started.

        A COMPLETED record is never unwound: an aborted prepare by
        definition never committed one, so a completed record here
        means a prepare WON a race against this unwind (e.g. the
        reconcile sweep snapshotting the spec-written-but-uncommitted
        window of the single-phase prepare) -- destroying its spec and
        record would hand the kubelet dead CDI ids. Teardown of
        completed claims belongs to unprepare() alone."""
        with self._lock:
            existing = self._checkpoint.get().claims.get(claim_uid)
            if existing is not None and \
                    existing.state == ClaimState.PREPARE_COMPLETED.value:
                logger.warning(
                    "unwind requested for COMPLETED claim %s; refusing "
                    "(a live prepare owns this state)", claim_uid)
                return
            self._cdi.delete_claim_spec_file(claim_uid)
            if existing is not None:
                self._checkpoint.update(
                    lambda c: c.claims.pop(claim_uid, None)
                )
        # The EVIDENCE gathering (node read + CD list) runs OUTSIDE
        # self._lock: it is kube I/O, up to the retry deadline during
        # the very degradation that caused the abort, and must not park
        # every other claim operation on this node. The final
        # check-and-drop re-takes the lock so it cannot race a
        # concurrent channel prepare for a NEW domain that just set the
        # label: under the lock, that prepare's completed checkpoint
        # record is visible and vetoes the drop.
        try:
            node = self.kube.get("", "v1", "nodes", self.node_name)
            labeled_cd = node.get("metadata", {}).get(
                "labels", {}).get(NODE_LABEL)
        except (KubeError, OSError):
            return  # can't even read the node: change nothing
        if labeled_cd and self._cd_definitely_gone(labeled_cd):
            with self._lock:
                node = None
                try:
                    node = self.kube.get("", "v1", "nodes",
                                         self.node_name)
                except (KubeError, OSError):
                    return
                # Re-check under the lock: a concurrent prepare may
                # have re-pointed the label at a LIVE domain.
                if node.get("metadata", {}).get(
                        "labels", {}).get(NODE_LABEL) == labeled_cd:
                    self._drop_node_label_if_unused()

    def _cd_definitely_gone(self, cd_uid: str) -> bool:
        """POSITIVE evidence that a ComputeDomain no longer exists: a
        SUCCESSFUL apiserver list that does not contain the uid. An
        informer cache miss is NOT evidence -- the cache is legitimately
        empty right after a restart during an apiserver blip (informer
        start tolerates a failed initial relist), and dropping the node
        label on that signal would dissolve a living gang. Any API
        error reads as 'unknown' -> keep the label (safe default; a
        truly dead domain is reclaimed on a later abort)."""
        try:
            return not any(
                cd["metadata"].get("uid") == cd_uid
                for cd in self.kube.list(API_GROUP, API_VERSION,
                                         "computedomains")
            )
        except (KubeError, OSError):
            return False

    def _drop_node_label_if_unused(self) -> None:
        """Remove the daemon-scheduling node label when no completed
        claim holds a channel device (call under self._lock, so the
        checkpoint read and the patch can't interleave with a
        concurrent prepare's label-set + completion)."""
        remaining = self._checkpoint.get().claims.values()
        any_channels = any(
            d.canonical_name.startswith("channel-")
            for c in remaining
            for d in c.devices
        )
        if not any_channels:
            try:
                self.kube.patch(
                    "", "v1", "nodes", self.node_name,
                    {"metadata": {"labels": {NODE_LABEL: None}}},
                )
            except NotFoundError:
                pass

    def prepared_claims(self):
        return self._checkpoint.get().claims

    # -- stale per-domain dir GC -------------------------------------------------

    def cleanup_stale_domain_dirs(self) -> list[str]:
        """Remove domains/<uid> state dirs whose ComputeDomain no longer
        exists (reference computedomain.go:384 periodic cleanup)."""
        import shutil  # noqa: PLC0415

        domains_root = os.path.join(self.root, "domains")
        if not os.path.isdir(domains_root):
            return []
        # Order matters (TOCTOU): snapshot the dirs FIRST, then the live
        # set. A dir can only be created for an already-existing CD, so
        # any dir observed here either has its CD in the (later) live
        # snapshot or is genuinely stale. The reverse order could delete
        # the state dir of a domain created between the two reads.
        dirs = os.listdir(domains_root)
        live = {
            cd["metadata"].get("uid")
            for cd in self.kube.list(API_GROUP, API_VERSION, "computedomains")
        }
        removed = []
        for uid in dirs:
            if uid in live:
                continue
            path = os.path.join(domains_root, uid)
            try:
                shutil.rmtree(path)
            except OSError:
                logger.exception("removing stale domain dir %s failed", path)
                continue
            removed.append(uid)
        if removed:
            logger.warning("removed stale domain dir(s): %s", removed)
        return removed
