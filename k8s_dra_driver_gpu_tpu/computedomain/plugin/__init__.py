"""ComputeDomain kubelet plugin (reference cmd/compute-domain-kubelet-plugin/)."""
