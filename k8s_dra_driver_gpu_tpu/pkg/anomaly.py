"""Telemetry anomaly detection: EWMA/z-score detectors over the
per-chip telemetry stream.

The node collector (kubeletplugin/health.py) feeds every health-poll
telemetry sample through one :class:`AnomalyDetector`; detections are
surfaced four ways by the driver wiring:

- a deduped Warning Event on the Node (create-once per episode),
- ``tpu_dra_anomaly_total{kind}`` on the plugin registry,
- a flight-recorder entry keyed by the device name, and
- a NON-FATAL device taint (``tpu.dra.dev/<kind>``, empty effect)
  merged into the poll's taint list -- which is exactly what the PR 4
  QuarantineTracker counts, so a chip whose anomaly FLAPS (drifts hot,
  recovers, drifts again) escalates to NoSchedule quarantine through
  the existing machinery, while a steady condition stays observe-only
  (ROADMAP item 5's thermal-flapping -> quarantine semantics).

Detection is deliberately boring and cheap -- one EWMA mean/variance
pair per (chip, signal) plus plain thresholds:

``thermal_drift``
    temperature z-score above ``TPU_DRA_ANOMALY_Z`` vs the chip's OWN
    EWMA baseline (one-sided: only drift UP), after a minimum-sample
    warmup -- a chip that always ran hot is baseline, a chip that is
    GETTING hot is an anomaly.
``power_cap_throttle``
    power pinned at/above ``TPU_DRA_ANOMALY_POWER_CAP_W`` while the
    duty cycle is high: the chip is being clock-throttled by its power
    cap (2501.17752's scheduler-visible power signal). 0 disables.
``duty_cycle_straggler``
    this chip's duty cycle far below its same-poll peers' mean while
    the peers are busy -- the straggler profile that silently drags a
    whole gang's step time.
``ici_link_error_burst``
    the CUMULATIVE link-error counter jumped by more than
    ``TPU_DRA_ANOMALY_ICI_BURST`` within one poll interval.

Episode semantics: :meth:`AnomalyDetector.observe` returns NEW
detections (rising edges) for event/metric/flight emission, while
:meth:`taints` reflects the CURRENT level for the quarantine feed --
an anomaly that persists is one episode (one Warning Event, one
counter increment) but taints every poll until it clears.

State mutations live in this module + pkg/fleetstate.py + health.py
only (lint rule TPUDRA013).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from . import positive_float_env

#: z-score threshold for the EWMA drift detectors.
ANOMALY_Z = positive_float_env("TPU_DRA_ANOMALY_Z", default=3.0,
                               floor=0.5)
#: EWMA smoothing factor (weight of the newest sample).
ANOMALY_ALPHA = positive_float_env("TPU_DRA_ANOMALY_ALPHA", default=0.2,
                                   floor=0.01)
#: Samples a chip's baseline must see before drift can fire.
ANOMALY_MIN_SAMPLES = int(positive_float_env(
    "TPU_DRA_ANOMALY_MIN_SAMPLES", default=8, floor=2))
#: ICI link-error delta per poll that counts as a burst.
ANOMALY_ICI_BURST = int(positive_float_env(
    "TPU_DRA_ANOMALY_ICI_BURST", default=5, floor=1))
#: Straggler: peers' mean duty must exceed this...
ANOMALY_STRAGGLER_PEERS_DUTY = positive_float_env(
    "TPU_DRA_ANOMALY_STRAGGLER_PEERS_DUTY", default=0.7, floor=0.05)
#: ...while this chip trails the mean by at least this much.
ANOMALY_STRAGGLER_GAP = positive_float_env(
    "TPU_DRA_ANOMALY_STRAGGLER_GAP", default=0.4, floor=0.05)

KIND_THERMAL = "thermal_drift"
KIND_POWER = "power_cap_throttle"
KIND_STRAGGLER = "duty_cycle_straggler"
KIND_ICI = "ici_link_error_burst"
KINDS = (KIND_THERMAL, KIND_POWER, KIND_STRAGGLER, KIND_ICI)


def _power_cap_env() -> float:
    """``TPU_DRA_ANOMALY_POWER_CAP_W``: the platform's per-chip power
    cap in watts for throttle detection; 0 (the default) disables --
    the cap is platform-specific and must be configured, never
    guessed."""
    import os  # noqa: PLC0415

    try:
        return max(float(os.environ.get(
            "TPU_DRA_ANOMALY_POWER_CAP_W", "0")), 0.0)
    except ValueError:
        return 0.0


@dataclass(frozen=True)
class Anomaly:
    """One detection episode's rising edge."""

    device: str  # canonical device name (chip-N)
    kind: str
    detail: dict = field(default_factory=dict)


class Ewma:
    """Exponentially-weighted mean/variance pair (one per chip+signal);
    ``update`` returns the z-score of the sample against the PRIOR
    baseline, then folds it in."""

    __slots__ = ("alpha", "mean", "var", "n")

    def __init__(self, alpha: float = 0.0):
        self.alpha = alpha or ANOMALY_ALPHA
        self.mean = 0.0
        self.var = 0.0
        self.n = 0

    def zscore(self, x: float) -> float:
        """z of ``x`` against the current baseline (no fold)."""
        if self.n == 0:
            return 0.0
        sd = self.var ** 0.5
        if sd <= 1e-9:
            # A flat baseline: any real move is "infinite" sigma; use
            # a minimum scale of 1% of the mean (or 1.0) so the first
            # wiggle of a perfectly-flat series doesn't page.
            sd = max(abs(self.mean) * 0.01, 1.0)
        return (float(x) - self.mean) / sd

    def update(self, x: float) -> float:
        """Fold ``x`` into the baseline; returns its prior z-score.
        Callers detecting drift fold only NON-anomalous samples
        (baseline freeze), so an excursion cannot normalize itself
        into the baseline and mute every following episode."""
        z = self.zscore(x)
        if self.n == 0:
            self.mean, self.var, self.n = float(x), 0.0, 1
            return 0.0
        delta = float(x) - self.mean
        self.mean = self.mean + self.alpha * delta
        # West-style EWM variance: stable, no sample window to keep.
        self.var = (1 - self.alpha) * (self.var
                                       + self.alpha * delta * delta)
        self.n += 1
        return z


class AnomalyDetector:
    """Per-node detector over the health-poll telemetry stream."""

    def __init__(self, z_threshold: float = 0.0,
                 min_samples: int = 0, power_cap_w: float | None = None,
                 ici_burst: int = 0, straggler_peers_duty: float = 0.0,
                 straggler_gap: float = 0.0, alpha: float = 0.0,
                 chip_name=None):
        self.z = z_threshold or ANOMALY_Z
        self.min_samples = min_samples or ANOMALY_MIN_SAMPLES
        self.power_cap_w = (_power_cap_env() if power_cap_w is None
                            else power_cap_w)
        self.ici_burst = ici_burst or ANOMALY_ICI_BURST
        self.straggler_peers_duty = (straggler_peers_duty
                                     or ANOMALY_STRAGGLER_PEERS_DUTY)
        self.straggler_gap = straggler_gap or ANOMALY_STRAGGLER_GAP
        self._alpha = alpha or ANOMALY_ALPHA
        # Canonical device naming (kubeletplugin.subslice.chip_name);
        # injectable so pkg/ has no import edge into kubeletplugin/.
        self._chip_name = chip_name or (lambda i: f"chip-{i}")
        self._lock = threading.Lock()
        self._temp: dict[int, Ewma] = {}
        self._ici_last: dict[int, int] = {}
        # (device, kind) currently active -- the level the taint feed
        # reflects; observe() returns only rising edges.
        self._active: set[tuple[str, str]] = set()
        self.detections_total = 0

    def observe(self, samples) -> list[Anomaly]:
        """Fold one poll's ChipTelemetry samples; returns the NEW
        detections (episode rising edges)."""
        samples = list(samples or ())
        with self._lock:
            return self._fold_samples(samples)

    def _fold_samples(self, samples) -> list[Anomaly]:
        new: list[Anomaly] = []
        now_active: set[tuple[str, str]] = set()
        duties = [float(getattr(s, "duty_cycle", 0.0)) for s in samples]
        for i, s in enumerate(samples):
            device = self._chip_name(int(s.chip))
            # thermal drift (one-sided EWMA z-score). Anomalous
            # samples are NOT folded into the baseline: a drifting
            # chip must not normalize its own excursion and mute the
            # next episode (the flapping the quarantine feed counts).
            ewma = self._temp.get(s.chip)
            if ewma is None:
                ewma = self._temp[s.chip] = Ewma(self._alpha)
            warmed = ewma.n >= self.min_samples
            zscore = ewma.zscore(float(s.temp_celsius))
            if warmed and zscore >= self.z:
                now_active.add((device, KIND_THERMAL))
                self._edge(new, device, KIND_THERMAL,
                           temp_c=float(s.temp_celsius),
                           z=round(zscore, 2))
            else:
                ewma.update(float(s.temp_celsius))
            # power-cap throttling
            if self.power_cap_w > 0 and \
                    float(s.power_watts) >= self.power_cap_w * 0.98 \
                    and float(s.duty_cycle) >= 0.5:
                now_active.add((device, KIND_POWER))
                self._edge(new, device, KIND_POWER,
                           power_w=float(s.power_watts),
                           cap_w=self.power_cap_w)
            # ICI link-error burst (cumulative counter delta per poll)
            last = self._ici_last.get(s.chip)
            self._ici_last[s.chip] = int(s.ici_link_errors)
            if last is not None:
                delta = int(s.ici_link_errors) - last
                if delta >= self.ici_burst:
                    now_active.add((device, KIND_ICI))
                    self._edge(new, device, KIND_ICI, delta=delta)
            # duty-cycle straggler vs same-poll peers (the gang's other
            # members on this host run the same program; one chip idling
            # while its peers are pegged is the straggler profile)
            if len(samples) >= 2:
                peers = duties[:i] + duties[i + 1:]
                peers_mean = sum(peers) / len(peers)
                if peers_mean >= self.straggler_peers_duty and \
                        duties[i] <= peers_mean - self.straggler_gap:
                    now_active.add((device, KIND_STRAGGLER))
                    self._edge(new, device, KIND_STRAGGLER,
                               duty=duties[i],
                               peers_mean=round(peers_mean, 3))
        # Episodes end when the condition clears: drop inactive pairs
        # so the next occurrence is a fresh edge (and the taint feed
        # reflects the current level).
        self._active = now_active
        return new

    def _edge(self, out: list[Anomaly], device: str, kind: str,
              **detail) -> None:
        if (device, kind) not in self._active:
            self.detections_total += 1
            out.append(Anomaly(device=device, kind=kind, detail=detail))

    def active(self) -> frozenset[tuple[str, str]]:
        """(device, kind) pairs currently in an anomaly episode."""
        with self._lock:
            return frozenset(self._active)

    def taints(self, taint_cls, key_prefix: str):
        """The CURRENT anomaly level as non-fatal device taints (empty
        effect = observe-only) -- the QuarantineTracker feed. The
        taint class + prefix are injected so pkg/ has no import edge
        into kubeletplugin/health.py."""
        with self._lock:
            active = sorted(self._active)
        return [
            taint_cls(device=device, key=f"{key_prefix}/{kind}",
                      value="true", effect="")
            for device, kind in active
        ]
