"""Debug signal handlers: thread-stack dumps on SIGUSR1/SIGUSR2.

Reference: internal/common/util.go:29-34 -- goroutine-stack dumps to
/tmp/goroutine-stacks.dump on SIGUSR1/2, used to diagnose wedged
prepare/unprepare flows in the field.
"""

from __future__ import annotations

import faulthandler
import os
import signal
import sys
import threading
import traceback

DUMP_PATH = "/tmp/thread-stacks.dump"


def format_thread_stacks() -> str:
    frames = sys._current_frames()
    out = []
    for thread in threading.enumerate():
        out.append(f"--- {thread.name} (ident {thread.ident}, "
                   f"daemon={thread.daemon}) ---\n")
        frame = frames.get(thread.ident)
        if frame is not None:
            out.append("".join(traceback.format_stack(frame)))
        out.append("\n")
    return "".join(out)


def dump_thread_stacks(path: str = DUMP_PATH) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write(format_thread_stacks())


def debug_stacks_endpoint() -> tuple[int, str, bytes]:
    """Live thread stacks as text (the reference mounts net/http/pprof
    on its diagnostics mux, compute-domain-controller main.go:383-390;
    this is the in-process analog, also reachable via SIGUSR1)."""
    return 200, "text/plain", format_thread_stacks().encode()


def start_debug_signal_handlers(path: str | None = None) -> None:
    """Install SIGUSR1/SIGUSR2 stack dumpers + SIGABRT faulthandler.
    ``TPU_DRA_STACK_DUMP`` overrides the dump path (per-pod hostPath in
    the field; per-test isolation in the system suite)."""
    if path is None:
        path = os.environ.get("TPU_DRA_STACK_DUMP", DUMP_PATH)
    signal.signal(signal.SIGUSR1, lambda *a: dump_thread_stacks(path))
    signal.signal(signal.SIGUSR2, lambda *a: dump_thread_stacks(path))
    faulthandler.enable()


def wait_for_termination() -> None:
    """Block until SIGTERM/SIGINT, race-free.

    signal.pause() in a check-then-pause loop loses a signal delivered
    between the check and the pause; an Event set from the handler is
    immune (the kubelet's SIGKILL-after-grace would otherwise hit us).
    """
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
