"""Fleet telemetry state: the per-chip telemetry ring (node side) and
the fleet aggregator (scheduler side).

The telemetry plane has three stations; this module owns the state at
both ends:

- **TelemetryRing** (every node plugin): a compact rolling in-memory
  ring of per-chip power/thermal/HBM/duty-cycle samples fed by the
  health-poll loop (kubeletplugin/health.py sampling the
  ``tpulib.chip_telemetry`` seam) and served at ``/debug/telemetry``
  on the plugin's metrics listener. Bounded (``TPU_DRA_TELEMETRY_RING``
  samples per chip), no external store to deploy.
- **FleetAggregator** (the scheduler): folds per-node telemetry --
  published as quantized ResourceSlice device attributes riding the
  existing content-hash-diffed publish path, so a converged republish
  stays ZERO kube writes -- together with the scheduler's own
  ``AllocationState`` and ``pkg/topology`` into fleet time-series:
  per-pool utilization, ``fragmentation_score`` /
  ``largest_free_shape`` history, and pending-claim demand vs. free
  capacity. Exported as ``tpu_dra_fleet_*`` gauges and served as a
  JSON snapshot at ``/debug/fleet``.

Mutation discipline (lint rule TPUDRA013): ring / aggregator state
mutations (``record_sample``, ``fold_*``) happen ONLY inside this
module, pkg/anomaly.py, and kubeletplugin/health.py -- every other
caller goes through the read surface (``latest``/``series``/
``snapshot``) or the public fold entry (``observe_pass``), so the
time-series can never be corrupted from a random call site.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from . import positive_float_env
from .partition.spec import parse_partition_device_name
from .schedcache import ATTR_POWER_CAP, power_cap_env
from .topology import TorusGrid
from .topology.score import (
    attr_int,
    frag_from_largest,
    largest_free_shape,
)

#: Samples kept per chip in the node ring (at the default 5s health
#: poll cadence, 360 samples = 30 minutes of history).
DEFAULT_RING_SAMPLES = int(positive_float_env(
    "TPU_DRA_TELEMETRY_RING", default=360, floor=16))
#: Fleet time-series points kept per pool by the scheduler aggregator.
DEFAULT_FLEET_HISTORY = int(positive_float_env(
    "TPU_DRA_FLEET_HISTORY", default=512, floor=16))
#: How long a chip's last known power reading is carried when a fold
#: sees no (or a zero) power attribute for it -- the last-known-demand
#: fallback of the tenant store, applied to power: a single dropped
#: poll must not fake instant power headroom and let the scorer pile
#: claims onto a hot host. Past the TTL the chip reads as no data.
POWER_SAMPLE_TTL_S = positive_float_env(
    "TPU_DRA_POWER_SAMPLE_TTL_S", default=60.0, floor=1.0)

#: ResourceSlice attribute names the node plugin publishes (quantized;
#: see kubeletplugin/driver.py) and the aggregator folds.
ATTR_POWER = "telemetryPowerWatts"
ATTR_TEMP = "telemetryTempCelsius"
ATTR_DUTY = "telemetryDutyPct"
ATTR_HBM = "telemetryHbmUsedPct"
ATTR_ICI_ERR = "telemetryIciErrors"
TELEMETRY_ATTRS = (ATTR_POWER, ATTR_TEMP, ATTR_DUTY, ATTR_HBM,
                   ATTR_ICI_ERR)


class TelemetryRing:
    """Bounded per-chip ring of telemetry samples (the
    ``/debug/telemetry`` source on every node plugin)."""

    def __init__(self, samples_per_chip: int = 0):
        self._lock = threading.Lock()
        self._maxlen = max(16, int(samples_per_chip
                                   or DEFAULT_RING_SAMPLES))
        self._series: dict[int, deque] = {}
        self.recorded_total = 0

    def record_sample(self, sample) -> None:
        """Append one ChipTelemetry sample (mutation fenced to the
        telemetry layer by lint rule TPUDRA013)."""
        doc = sample.to_dict() if hasattr(sample, "to_dict") else dict(
            sample)
        doc["ts"] = time.time()
        chip = int(doc.get("chip", -1))
        with self._lock:
            ring = self._series.get(chip)
            if ring is None:
                ring = self._series[chip] = deque(maxlen=self._maxlen)
            ring.append(doc)
            self.recorded_total += 1

    def latest(self) -> dict[int, dict]:
        """Most recent sample per chip."""
        with self._lock:
            return {chip: ring[-1] for chip, ring in
                    self._series.items() if ring}

    def series(self, chip: int) -> list[dict]:
        """Full retained history for one chip, oldest first."""
        with self._lock:
            ring = self._series.get(int(chip))
            return list(ring) if ring else []

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "samples_per_chip": self._maxlen,
                "recorded_total": self.recorded_total,
                "chips": {str(chip): list(ring)
                          for chip, ring in self._series.items()},
            }

    def clear(self) -> None:
        with self._lock:
            self._series.clear()

    # -- /debug/telemetry endpoint (pkg/httpserver handler signature) ---------

    def telemetry_endpoint(self) -> tuple[int, str, bytes]:
        body = json.dumps(self.snapshot(), sort_keys=True).encode()
        return 200, "application/json", body


class FleetAggregator:
    """Scheduler-side fleet state: per-pool utilization / fragmentation
    time-series plus per-node telemetry folded from published slice
    attributes.

    ``observe_pass`` is the one public entry (called from the
    scheduler's full sync pass); everything it learns lands in bounded
    history rings and the optional duck-typed ``metrics`` sink
    (pkg.metrics.FleetMetrics). Reads never block a sync: the JSON
    snapshot is rebuilt from the rings under a short lock.
    """

    def __init__(self, metrics=None, history: int = 0):
        self._lock = threading.Lock()
        self._history = max(16, int(history or DEFAULT_FLEET_HISTORY))
        self.metrics = metrics
        # (driver, pool) -> deque of per-pass points
        self._pools: dict[tuple[str, str], deque] = {}
        # node -> latest folded telemetry aggregate
        self._nodes: dict[str, dict] = {}
        self._pending = 0
        self._last_pass_ts = 0.0
        self.passes_total = 0
        # Labels currently exported through the metrics sink (pruned
        # when a pool/node leaves the snapshot; the power-headroom set
        # additionally prunes when a still-present pool's caps vanish
        # -- a gauge must never freeze at a stale headroom for a pool
        # whose power model turned off).
        self._metric_pools: set[str] = set()
        self._metric_nodes: set[str] = set()
        self._metric_power_pools: set[str] = set()
        # Defrag trigger hysteresis (pkg/defrag): pool key -> wall
        # clock its fragmentation first crossed the trigger threshold.
        # Armed pools stay armed until frag falls to the RELEASE
        # threshold, so a pool oscillating just under the trigger
        # cannot flap the controller on and off.
        self._frag_armed: dict[tuple[str, str], float] = {}
        # Pools present in the LAST fold: the trigger signal only
        # considers these -- a vanished pool's ring keeps its history
        # for /debug/fleet, but a frozen last reading must neither
        # keep firing the controller nor hold a stale armed clock
        # that would skip the sustain window on return.
        self._live_pools: set[tuple[str, str]] = set()
        # Pending-demand ring: (ts, pending claims) per pass. The
        # autoscaler's starvation signal and the /debug/fleet history
        # next to the per-pool frag/utilization rings.
        self._pending_ring: deque = deque(maxlen=self._history)
        # Optional TenantProfileStore (pkg/partition/profiles): when
        # attached, /debug/fleet surfaces the per-tenant demand
        # percentiles the autoscale planner sizes against -- operators
        # see what the controller sees.
        self._profile_store = None
        # Last known per-device power reading, (ts, watts) keyed by
        # candidate key: the carry source when a fold sees a device
        # with a missing/zero power attribute (POWER_SAMPLE_TTL_S).
        self._last_dev_power: dict[tuple, tuple[float, int]] = {}

    def attach_profile_store(self, store) -> None:
        """Surface a TenantProfileStore's windowed percentiles in the
        fleet snapshot (read-only: the aggregator never mutates the
        store)."""
        self._profile_store = store

    # -- the fold (mutations; TPUDRA013 fences callers) -----------------------

    def observe_pass(self, snapshot, alloc, pending_claims: int,
                     grid_fn=None) -> dict:
        """Fold one scheduler pass: ``snapshot`` is the
        InventorySnapshot, ``alloc`` the AllocationState, and
        ``pending_claims`` the claims still waiting for capacity.
        ``grid_fn(candidates) -> TorusGrid`` injects the scheduler's
        grid builder (defaults to TorusGrid.from_devices). Returns the
        per-pool points folded (tests / the debug endpoint)."""
        t0 = time.monotonic()
        now = time.time()
        by_pool: dict[tuple[str, str], list] = {}
        for cand in snapshot.candidates:
            by_pool.setdefault((cand.driver, cand.pool), []).append(cand)
        allocated = alloc.allocated if alloc is not None else frozenset()
        holder_counts = (alloc.slot_counts()
                         if alloc is not None
                         and hasattr(alloc, "slot_counts") else {})
        points = {}
        nodes: dict[str, dict] = {}
        env_cap = power_cap_env()
        for key, cands in by_pool.items():
            total = len(cands)
            used = sum(1 for c in cands if c.key in allocated)
            free = [c for c in cands if c.key not in allocated]
            frag, largest = self._fold_frag(cands, free, grid_fn)
            # Partition-slot occupancy (the autoscaler's input next to
            # frag/utilization): pt- devices' tenant slots vs holders.
            pt = [c for c in cands
                  if parse_partition_device_name(c.name) is not None]
            slots_total = sum(c.slots for c in pt)
            slots_used = sum(min(holder_counts.get(c.key, 0), c.slots)
                             for c in pt)
            pool_power, pool_caps = self._fold_node_telemetry(
                cands, nodes, now)
            cap_total = sum(
                (cap if cap > 0 else env_cap)
                for cap in pool_caps.values()) if pool_caps else 0
            points[key] = {
                "ts": round(now, 3),
                "total_devices": total,
                "allocated_devices": used,
                "free_devices": total - used,
                "utilization": round(used / total, 4) if total else 0.0,
                "fragmentation_score": frag,
                "largest_free_shape": largest,
                "partition_slots_total": slots_total,
                "partition_slots_used": slots_used,
                "partition_slot_occupancy": (
                    round(slots_used / slots_total, 4)
                    if slots_total else None),
                # Power envelope (2501.17752 scheduling input): summed
                # device draw vs the summed node caps of this pool.
                # None when no cap is known (model off).
                "power_watts": pool_power,
                "power_cap_watts": cap_total or None,
                "power_headroom_watts": (
                    max(cap_total - pool_power, 0)
                    if cap_total else None),
            }
        self._finalize_nodes(nodes)
        # Age the carry map: a device gone past the TTL reads as no
        # data everywhere instead of a frozen plausible wattage.
        for dkey in [k for k, (ts, _w) in self._last_dev_power.items()
                     if now - ts > POWER_SAMPLE_TTL_S]:
            del self._last_dev_power[dkey]
        with self._lock:
            for key, point in points.items():
                ring = self._pools.get(key)
                if ring is None:
                    ring = self._pools[key] = deque(maxlen=self._history)
                ring.append(point)
            # Pools that vanished from the snapshot keep their history
            # (the ring is the record of what happened); nodes reflect
            # the CURRENT inventory only.
            self._nodes = nodes
            self._pending = int(pending_claims)
            self._pending_ring.append(
                {"ts": round(now, 3), "pending": int(pending_claims)})
            self._last_pass_ts = now
            self.passes_total += 1
            self._live_pools = set(points)
            for key in [k for k in self._frag_armed
                        if k not in self._live_pools]:
                del self._frag_armed[key]
        if self.metrics is not None:
            try:
                # The fold-cost histogram the score-memo satellite is
                # judged against: largest_free_shape memoization
                # (pkg/topology/score.py) is what keeps this flat as
                # pools multiply. getattr: the sink is duck-typed and
                # older test doubles may not carry the histogram.
                fold_hist = getattr(self.metrics, "fold_seconds", None)
                if fold_hist is not None:
                    fold_hist.observe(time.monotonic() - t0)
                self.metrics.set_pending(int(pending_claims))
                pool_labels = {f"{driver}/{pool}"
                               for driver, pool in points}
                # getattr: the sink is duck-typed and older test
                # doubles may not carry the power gauge.
                pool_power_fn = getattr(self.metrics, "set_pool_power",
                                        None)
                power_pools: set[str] = set()
                for (driver, pool), point in points.items():
                    self.metrics.set_pool(
                        f"{driver}/{pool}", point["utilization"],
                        point["free_devices"])
                    if pool_power_fn is not None and \
                            point.get("power_headroom_watts") \
                            is not None:
                        pool_power_fn(f"{driver}/{pool}",
                                      point["power_headroom_watts"])
                        power_pools.add(f"{driver}/{pool}")
                # A pool whose caps vanished this pass (model turned
                # off) drops its headroom gauge instead of freezing.
                power_prune_fn = getattr(self.metrics,
                                         "remove_pool_power", None)
                if power_prune_fn is not None:
                    for label in self._metric_power_pools - power_pools:
                        power_prune_fn(label)
                self._metric_power_pools = power_pools
                for node, agg in nodes.items():
                    self.metrics.set_node(
                        node, agg.get("power_watts", 0.0),
                        agg.get("temp_celsius", 0.0))
                # Pools/nodes gone from THIS pass stop exporting: a
                # retired pool or dead node must not freeze its last
                # reading into fleet sums.
                for label in self._metric_pools - pool_labels:
                    self.metrics.remove_pool(label)
                for node in self._metric_nodes - set(nodes):
                    self.metrics.remove_node(node)
                self._metric_pools = pool_labels
                self._metric_nodes = set(nodes)
            except Exception:  # noqa: BLE001 - metrics sink best-effort
                pass
        return points

    @staticmethod
    def _fold_frag(cands, free, grid_fn) -> tuple[float | None,
                                                  int | None]:
        """Fragmentation of a pool's free chips via pkg/topology; None
        when the pool publishes no usable ICI coordinates."""
        try:
            grid = (grid_fn or
                    (lambda cs: TorusGrid.from_devices(
                        [c.device for c in cs])))(cands)
            free_cells = {grid.coords[c.name] for c in free
                          if c.name in grid.coords}
            if not grid.coords:
                return None, None
            _, chips = largest_free_shape(grid, free_cells)
            return (round(frag_from_largest(chips, len(free_cells)), 4),
                    chips)
        except Exception:  # noqa: BLE001 - uncoordinated pools
            return None, None

    def _fold_node_telemetry(self, cands, nodes: dict[str, dict],
                             now: float) -> tuple[int, dict[str, int]]:
        """Aggregate the quantized per-device telemetry attributes the
        node plugins publish into one per-node view (sum of power,
        max temp, mean duty, max HBM-used fraction, sum of ICI error
        counters). Returns ``(pool power watts, {node: published power
        cap})`` for this candidate group's pool point.

        A device with a MISSING or ZERO power attribute carries its
        last windowed reading (``POWER_SAMPLE_TTL_S``) instead of
        folding as 0 W -- one dropped poll must not fake instant power
        headroom under a pile of claims; past the TTL it genuinely
        reads as no data (the replace-semantics contract)."""
        pool_power = 0
        pool_caps: dict[str, int] = {}
        for cand in cands:
            attrs = cand.device.get("attributes") or {}
            vals = {}
            for name in TELEMETRY_ATTRS:
                entry = attrs.get(name)
                if isinstance(entry, dict) and "int" in entry:
                    try:
                        vals[name] = int(entry["int"])
                    except (TypeError, ValueError):
                        pass
            cap = max(attr_int(attrs, ATTR_POWER_CAP), 0)
            if cap > 0 or vals:
                pool_caps[cand.node] = max(
                    pool_caps.get(cand.node, 0), cap)
            power = vals.get(ATTR_POWER, 0)
            if power > 0:
                self._last_dev_power[cand.key] = (now, power)
            else:
                carried = self._last_dev_power.get(cand.key)
                if carried is not None and \
                        now - carried[0] <= POWER_SAMPLE_TTL_S:
                    power = carried[1]
                    vals[ATTR_POWER] = power
            if not vals:
                continue
            pool_power += power
            agg = nodes.setdefault(cand.node, {
                "chips": 0, "power_watts": 0, "temp_celsius": 0,
                "duty_pct_sum": 0, "hbm_used_pct": 0,
                "ici_link_errors": 0,
            })
            agg["chips"] += 1
            agg["power_watts"] += vals.get(ATTR_POWER, 0)
            agg["temp_celsius"] = max(agg["temp_celsius"],
                                      vals.get(ATTR_TEMP, 0))
            agg["duty_pct_sum"] += vals.get(ATTR_DUTY, 0)
            agg["hbm_used_pct"] = max(agg["hbm_used_pct"],
                                      vals.get(ATTR_HBM, 0))
            agg["ici_link_errors"] += vals.get(ATTR_ICI_ERR, 0)
        return pool_power, pool_caps

    @staticmethod
    def _finalize_nodes(nodes: dict[str, dict]) -> None:
        """One-shot finalize AFTER every pool folded: a node's devices
        may span several (driver, pool) groups, so the running sum
        must survive across _fold_node_telemetry calls."""
        for agg in nodes.values():
            if agg["chips"]:
                agg["duty_pct_mean"] = round(
                    agg.pop("duty_pct_sum") / agg["chips"], 1)

    # -- defrag trigger signal (pkg/defrag.DefragController) ------------------

    def frag_signal(self, trigger: float, release: float,
                    sustain_s: float,
                    demand: set | None = None,
                    now: float | None = None) -> dict:
        """Per-pool defrag trigger evaluation over the fragmentation
        rings, with hysteresis.

        A pool ARMS when its latest ``fragmentation_score`` crosses
        ``trigger`` and stays armed until the score falls back to
        ``release`` (values between the two keep the armed state --
        the anti-flap band). An armed pool FIRES when ``demand``
        contains its key (a pending large-shape claim is starving
        NOW) or when it has stayed armed for ``sustain_s`` seconds.

        Returns ``{(driver, pool): {"fragmentation_score",
        "largest_free_shape", "armed_since", "fire"}}`` for every
        armed pool. Read-only apart from the hysteresis bookkeeping;
        the controller owns everything downstream (planning, budgets,
        cooldown)."""
        now = time.time() if now is None else now
        demand = demand or set()
        out: dict[tuple[str, str], dict] = {}
        with self._lock:
            for key in sorted(self._live_pools):
                ring = self._pools.get(key)
                point = ring[-1] if ring else None
                frag = (point or {}).get("fragmentation_score")
                if frag is None or frag <= release:
                    # Healed (or uncoordinated): disarm.
                    self._frag_armed.pop(key, None)
                    continue
                if frag < trigger and key not in self._frag_armed:
                    continue  # in the hysteresis band, never armed
                armed_since = self._frag_armed.setdefault(key, now)
                out[key] = {
                    "fragmentation_score": frag,
                    "largest_free_shape": point.get(
                        "largest_free_shape"),
                    "armed_since": armed_since,
                    "fire": (key in demand
                             or now - armed_since >= sustain_s),
                }
        return out

    # -- read surface ---------------------------------------------------------

    def pending_recent(self, points: int = 5) -> int:
        """Max pending-claim count over the last ``points`` passes:
        the autoscaler's sustained-starvation signal (one noisy pass
        neither fires nor masks it)."""
        with self._lock:
            tail = list(self._pending_ring)[-max(points, 1):]
            return max((p["pending"] for p in tail), default=0)

    def snapshot(self) -> dict:
        tenants = (self._profile_store.percentiles()
                   if self._profile_store is not None else None)
        with self._lock:
            out = {
                "ts": self._last_pass_ts,
                "passes_total": self.passes_total,
                "pending_claims": self._pending,
                "pending_history": list(self._pending_ring),
                "pools": {
                    f"{driver}/{pool}": {
                        "current": ring[-1] if ring else None,
                        "history": list(ring),
                    }
                    for (driver, pool), ring in self._pools.items()
                },
                "nodes": dict(self._nodes),
            }
            if tenants is not None:
                # What the autoscale planner sees: windowed per-tenant
                # demand percentiles (pkg/autoscale reads the same
                # store).
                out["tenant_demand"] = tenants
            return out

    # -- /debug/fleet endpoint (pkg/httpserver handler signature) -------------

    def fleet_endpoint(self) -> tuple[int, str, bytes]:
        body = json.dumps(self.snapshot(), sort_keys=True).encode()
        return 200, "application/json", body


# -- process-wide defaults (what the MetricsServer debug routes serve) --------

_default_ring: TelemetryRing | None = None
_default_fleet: FleetAggregator | None = None
_default_lock = threading.Lock()


def default_ring() -> TelemetryRing:
    """The process-wide telemetry ring (served at /debug/telemetry)."""
    global _default_ring
    if _default_ring is None:
        with _default_lock:
            if _default_ring is None:
                _default_ring = TelemetryRing()
    return _default_ring


def set_default_ring(ring: TelemetryRing) -> TelemetryRing:
    """Swap the process ring (tests / bench isolation)."""
    global _default_ring
    with _default_lock:
        _default_ring = ring
    return ring


def default_fleet() -> FleetAggregator:
    """The process-wide fleet aggregator (served at /debug/fleet)."""
    global _default_fleet
    if _default_fleet is None:
        with _default_lock:
            if _default_fleet is None:
                _default_fleet = FleetAggregator()
    return _default_fleet


def set_default_fleet(fleet: FleetAggregator) -> FleetAggregator:
    """Swap the process aggregator (the scheduler installs its own)."""
    global _default_fleet
    with _default_lock:
        _default_fleet = fleet
    return fleet


def telemetry_enabled(env=os.environ) -> bool:
    """The master telemetry switch (``TPU_DRA_TELEMETRY``, default on):
    off disables sampling, ring, anomaly detection, and slice-attribute
    publication in one place (the bench overhead gate's off side)."""
    return env.get("TPU_DRA_TELEMETRY", "1") not in ("0", "false",
                                                     "False")
