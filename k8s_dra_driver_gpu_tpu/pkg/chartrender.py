"""Minimal Helm-chart renderer for the in-tree chart.

Two jobs:
1. Render-test the chart in CI without a helm binary (the reference
   relies on `helm lint`/`helm template` in its pipelines; this repo's
   environment has no helm, so the tests use this renderer to prove the
   manifests parse and that every flag/env the templates set is
   accepted by the real binaries).
2. Poor-man's `helm template` for operators:
       python -m k8s_dra_driver_gpu_tpu.pkg.chartrender \
           deployments/helm/tpu-dra-driver [--set a.b=c ...]

Supports exactly the template dialect the chart uses: `.Values.*` /
`.Chart.*` lookups, `|` pipelines (quote, default X, toYaml, nindent N,
b64enc), `if`/`with`/`end` blocks with `{{-`/`-}}` whitespace control,
and `fail "msg"` (the validation.yaml analog of the reference chart).
Anything else raises -- better a loud render-test failure than silently
wrong manifests.
"""

from __future__ import annotations

import argparse
import base64
import os
import re
import sys

import yaml


class ChartRenderError(ValueError):
    pass


class ChartValidationError(ChartRenderError):
    """A template called fail (values rejected by validation rules)."""


_TAG = re.compile(r"(\{\{-?.*?-?\}\})", re.DOTALL)


def _lookup(path: str, ctx: dict):
    """Resolve `.Values.a.b` / `.Chart.X` / `.` against the context."""
    if path == ".":
        return ctx["."]
    cur = ctx
    for seg in path.lstrip(".").split("."):
        if isinstance(cur, dict):
            cur = cur.get(seg)
        else:
            return None
        if cur is None:
            return None
    return cur


def _to_yaml(value) -> str:
    return yaml.safe_dump(value, default_flow_style=False).rstrip("\n")


def _eval_atom(atom: str, ctx: dict):
    atom = atom.strip()
    if atom.startswith('"') and atom.endswith('"'):
        return atom[1:-1]
    if atom.startswith("."):
        return _lookup(atom, ctx)
    if re.fullmatch(r"-?\d+", atom):
        return int(atom)
    raise ChartRenderError(f"unsupported expression atom: {atom!r}")


_FILTER_NAMES = {"quote", "default", "toYaml", "nindent", "b64enc"}


def _eval_expr(expr: str, ctx: dict):
    """Evaluate a pipeline: atom | filter [arg] | ... The first stage may
    also be function-style (`toYaml .`), normalized to `.` | toYaml."""
    stages = [s.strip() for s in expr.split("|")]
    head = stages[0].split(None, 1)
    if head[0] in _FILTER_NAMES and len(head) > 1:
        stages = [head[1], head[0]] + stages[1:]
    value = _eval_atom(stages[0], ctx)
    for stage in stages[1:]:
        parts = stage.split(None, 1)
        name, arg = parts[0], (parts[1] if len(parts) > 1 else None)
        if name == "quote":
            value = '"%s"' % str(value if value is not None else "")
        elif name == "default":
            fallback = _eval_atom(arg, ctx)
            if value in (None, "", 0, False):
                value = fallback
        elif name == "toYaml":
            value = _to_yaml(value)
        elif name == "nindent":
            n = int(arg)
            pad = " " * n
            value = "\n" + "\n".join(
                pad + line if line else line
                for line in str(value).split("\n")
            )
        elif name == "b64enc":
            value = base64.b64encode(str(value).encode()).decode()
        else:
            raise ChartRenderError(f"unsupported filter: {name!r}")
    return value


def _truthy(value) -> bool:
    return bool(value)


class _Node:
    def __init__(self, kind: str, arg: str = ""):
        self.kind = kind  # root | text | expr | if | with
        self.arg = arg
        self.children: list[_Node] = []
        self.else_children: list[_Node] = []
        self._in_else = False

    def sink(self) -> list["_Node"]:
        return self.else_children if self._in_else else self.children


def _parse(text: str) -> _Node:
    """Split into text/tag tokens (with whitespace control applied) and
    build the block tree."""
    tokens = _TAG.split(text)
    # Apply {{- / -}} trimming to neighboring text tokens.
    for i, tok in enumerate(tokens):
        if not tok.startswith("{{"):
            continue
        if tok.startswith("{{-") and i > 0:
            tokens[i - 1] = tokens[i - 1].rstrip(" \t")
            if tokens[i - 1].endswith("\n"):
                tokens[i - 1] = tokens[i - 1][:-1]
        if tok.endswith("-}}") and i + 1 < len(tokens):
            tokens[i + 1] = tokens[i + 1].lstrip(" \t\n")

    root = _Node("root")
    stack = [root]
    for tok in tokens:
        if not tok.startswith("{{"):
            if tok:
                node = _Node("text", tok)
                stack[-1].sink().append(node)
            continue
        body = tok.strip("{}").strip("-").strip()
        if body.startswith("if "):
            node = _Node("if", body[3:].strip())
            stack[-1].sink().append(node)
            stack.append(node)
        elif body.startswith("with "):
            node = _Node("with", body[5:].strip())
            stack[-1].sink().append(node)
            stack.append(node)
        elif body == "else":
            if len(stack) == 1 or stack[-1].kind != "if":
                raise ChartRenderError("{{ else }} outside an if block")
            stack[-1]._in_else = True
        elif body == "end":
            if len(stack) == 1:
                raise ChartRenderError("unbalanced {{ end }}")
            stack.pop()
        elif body.startswith("/*"):
            continue  # comment
        else:
            stack[-1].sink().append(_Node("expr", body))
    if len(stack) != 1:
        raise ChartRenderError("unclosed {{ if/with }} block")
    return root


def _render_node(node: _Node, ctx: dict, out: list[str]) -> None:
    for child in node.children:
        if child.kind == "text":
            out.append(child.arg)
        elif child.kind == "expr":
            body = child.arg
            if body.startswith("fail "):
                raise ChartValidationError(_eval_atom(body[5:], ctx))
            value = _eval_expr(body, ctx)
            out.append("" if value is None else str(value))
        elif child.kind == "if":
            if _truthy(_eval_expr(child.arg, ctx)):
                _render_node(child, ctx, out)
            else:
                branch = _Node("root")
                branch.children = child.else_children
                _render_node(branch, ctx, out)
        elif child.kind == "with":
            value = _eval_expr(child.arg, ctx)
            if _truthy(value):
                sub = dict(ctx)
                sub["."] = value
                _render_node(child, sub, out)


def render_template(text: str, ctx: dict) -> str:
    out: list[str] = []
    _render_node(_parse(text), ctx, out)
    return "".join(out)


def _deep_merge(base: dict, overlay: dict) -> dict:
    out = dict(base)
    for k, v in overlay.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _validate_values(chart_dir: str, values: dict) -> None:
    """Enforce values.schema.json (helm validates it natively; this
    renderer mirrors that so render tests catch bad values too)."""
    schema_path = os.path.join(chart_dir, "values.schema.json")
    if not os.path.exists(schema_path):
        return
    import json  # noqa: PLC0415

    with open(schema_path, encoding="utf-8") as f:
        schema = json.load(f)
    try:
        import jsonschema  # noqa: PLC0415
    except ImportError:  # pragma: no cover - jsonschema is baked in here
        return
    try:
        jsonschema.validate(values, schema)
    except jsonschema.ValidationError as e:
        raise ChartValidationError(
            f"values rejected by values.schema.json: {e.message}"
        ) from e


def render_chart(
    chart_dir: str, overrides: dict | None = None
) -> dict[str, str]:
    """Render every template; returns {relative template path: text}.
    CRDs (helm installs them verbatim) are included under crds/."""
    with open(os.path.join(chart_dir, "Chart.yaml"), encoding="utf-8") as f:
        chart = yaml.safe_load(f)
    with open(os.path.join(chart_dir, "values.yaml"), encoding="utf-8") as f:
        values = yaml.safe_load(f)
    if overrides:
        values = _deep_merge(values, overrides)
    _validate_values(chart_dir, values)
    ctx = {
        "Values": values,
        "Chart": {
            "Name": chart.get("name"),
            "Version": chart.get("version"),
            "AppVersion": chart.get("appVersion"),
        },
        ".": None,
    }
    out: dict[str, str] = {}
    tdir = os.path.join(chart_dir, "templates")
    for name in sorted(os.listdir(tdir)):
        if not name.endswith((".yaml", ".yml")):
            continue
        with open(os.path.join(tdir, name), encoding="utf-8") as f:
            out[f"templates/{name}"] = render_template(f.read(), ctx)
    cdir = os.path.join(chart_dir, "crds")
    if os.path.isdir(cdir):
        for name in sorted(os.listdir(cdir)):
            with open(os.path.join(cdir, name), encoding="utf-8") as f:
                out[f"crds/{name}"] = f.read()
    return out


def manifests(rendered: dict[str, str]) -> list[dict]:
    """Parse rendered output into manifest dicts (skips empty docs)."""
    docs = []
    for _, text in sorted(rendered.items()):
        for doc in yaml.safe_load_all(text):
            if doc:
                docs.append(doc)
    return docs


def _parse_set(expr: str) -> dict:
    key, _, val = expr.partition("=")
    out: dict = {}
    cur = out
    parts = key.split(".")
    for p in parts[:-1]:
        cur[p] = {}
        cur = cur[p]
    parsed: object = val
    if val in ("true", "false"):
        parsed = val == "true"
    elif re.fullmatch(r"-?\d+", val):
        parsed = int(val)
    cur[parts[-1]] = parsed
    return out


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="chartrender",
                                description="render the in-tree helm chart")
    p.add_argument("chart_dir")
    p.add_argument("--set", action="append", default=[],
                   help="value override a.b.c=x (repeatable)")
    args = p.parse_args(argv)
    overrides: dict = {}
    for expr in args.set:
        overrides = _deep_merge(overrides, _parse_set(expr))
    for name, text in render_chart(args.chart_dir, overrides).items():
        body = text.strip()
        if body:
            print(f"---\n# Source: {name}\n{body}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
