"""Node boot-ID reader, used for checkpoint invalidation across reboots.

Reference: pkg/bootid/bootid.go (reads /proc/sys/kernel/random/boot_id;
mutable path seam for tests, bootid.go:14; consumed by the checkpoint
layer to invalidate prepared-claim state after a node reboot,
cmd/gpu-kubelet-plugin/checkpointv.go:74-81).
"""

from __future__ import annotations

# Test seam: tests may reassign this to a temp file (mirrors the
# reference's mutable ``bootIDPath`` package variable).
BOOT_ID_PATH = "/proc/sys/kernel/random/boot_id"


def read_boot_id(path: str | None = None) -> str:
    """Return the node's boot ID, or "" if unreadable.

    An empty boot ID disables reboot-based checkpoint invalidation rather
    than failing startup (same degradation the reference chooses).
    """
    p = path or BOOT_ID_PATH
    try:
        with open(p, "r", encoding="utf-8") as f:
            return f.read().strip()
    except OSError:
        return ""
