"""Driver-wide claim-lifecycle tracing.

The control plane spans four binaries (scheduler, kubelet plugin, CD
plugin, CD controller) plus the partition engine; a single claim's
journey -- pod admission -> scheduler fit/commit -> kube patch ->
NodePrepareResources -> carve-out/CDI -> ready -- used to be
reconstructible only by hand-correlating klog-style ``t_prep_*`` lines
across processes (the gap the reference papers over with log levels,
pkg/timing.py docstring). This module gives every hop a real span:

- **Span contexts** are W3C-traceparent compatible
  (``00-<32 hex trace>-<16 hex span>-<flags>``), so the id that
  crosses a process boundary is the standard header form.
- **Propagation across binaries** rides the claim object itself: the
  scheduler stamps :data:`TRACEPARENT_ANNOTATION` onto the claim in
  the same patch that writes ``status.allocation``, and every consumer
  (kubelet plugin, CD plugin, partition engine) ``extract()``\\ s it, so
  node-side prepare segments become children of the scheduler's commit
  span -- one trace id end to end.
- **Export is in-process and bounded**: a fixed-size ring served as
  JSON at ``/debug/traces`` (every binary's metrics listener, see
  pkg/metrics.MetricsServer) plus an optional append-only JSONL file
  (``TPU_DRA_TRACE_FILE``) for offline analysis. No collector
  dependency, nothing to deploy.
- **Sampling** (``TPU_DRA_TRACE_SAMPLE``, 0.0-1.0, default 1.0) is
  decided once at the trace ROOT and inherited by every child local or
  remote (the traceparent flags byte), so the allocation hot path can
  run with tracing effectively off (``0``) and still stay correct --
  unsampled spans are a shared no-op object, no ids, no export.
  ``bench.py --trace-overhead`` gates the sampled cost.

Public API (lint rule TPUDRA012 enforces the with-guard discipline):

    with tracing.span("sched.commit", attrs={"claim_uid": uid}) as sp:
        ...
        header = sp.context.to_traceparent()

``start_span()`` exists for holders that outlive a lexical scope
(SegmentTimer's operation span); it must be closed via ``finish()``
and is only sanctioned inside the tracing/timing layer itself.
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
from collections import deque
from typing import NamedTuple

logger = logging.getLogger(__name__)

#: Claim annotation carrying the allocating scheduler's commit-span
#: context (W3C traceparent form). Stamped by pkg/scheduler.py in the
#: allocation patch; consumed by both kubelet plugins.
TRACEPARENT_ANNOTATION = "resource.tpu.dra/traceparent"

ENV_SAMPLE = "TPU_DRA_TRACE_SAMPLE"
ENV_TRACE_FILE = "TPU_DRA_TRACE_FILE"
ENV_TRACE_RING = "TPU_DRA_TRACE_RING"
# JSONL sink rotation: at max-MB the file rotates to <path>.1 (shifting
# .1 -> .2 ... up to keep-N, oldest dropped), so a long-lived sampled
# binary can never fill the disk. 0 MB = unbounded (the historical
# behavior); rotation errors disable the sink like write errors --
# never fail a traced op.
ENV_TRACE_FILE_MAX_MB = "TPU_DRA_TRACE_FILE_MAX_MB"
ENV_TRACE_FILE_KEEP = "TPU_DRA_TRACE_FILE_KEEP"
DEFAULT_TRACE_FILE_MAX_MB = 64.0
DEFAULT_TRACE_FILE_KEEP = 3

_VERSION = "00"
DEFAULT_RING_SPANS = 4096


class SpanContext(NamedTuple):
    """W3C-traceparent-compatible trace identity. (A NamedTuple, not a
    frozen dataclass: one is constructed per span on the allocation
    hot path, and frozen-dataclass __init__ costs ~3x.)"""

    trace_id: str  # 32 lowercase hex chars, nonzero
    span_id: str   # 16 lowercase hex chars, nonzero
    sampled: bool = True

    def to_traceparent(self) -> str:
        return (f"{_VERSION}-{self.trace_id}-{self.span_id}-"
                f"{'01' if self.sampled else '00'}")

    @classmethod
    def from_traceparent(cls, header: str) -> "SpanContext | None":
        """Parse a traceparent header; None on anything malformed (a
        bad annotation must never break a prepare)."""
        if not isinstance(header, str):
            return None
        parts = header.strip().split("-")
        if len(parts) != 4:
            return None
        version, trace_id, span_id, flags = parts
        if len(version) != 2 or len(trace_id) != 32 or \
                len(span_id) != 16 or len(flags) != 2:
            return None
        try:
            int(version, 16)
            int(flags, 16)
            if int(trace_id, 16) == 0 or int(span_id, 16) == 0:
                return None
        except ValueError:
            return None
        return cls(trace_id=trace_id.lower(), span_id=span_id.lower(),
                   sampled=bool(int(flags, 16) & 0x01))


def _new_trace_id() -> str:
    return f"{random.getrandbits(128) or 1:032x}"


def _new_span_id() -> str:
    return f"{random.getrandbits(64) or 1:016x}"


class Span:
    """One timed operation. Context-manager entry pushes it onto the
    calling thread's span stack (so nested ``span()`` calls and the
    logging filter see it); exit records the end time and exports."""

    __slots__ = ("name", "context", "parent_id", "start_ts",
                 "start_mono", "end_ts", "attrs", "events", "error",
                 "_finished", "_entered")

    def __init__(self, name: str, context: SpanContext,
                 parent_id: str = "", attrs: dict | None = None):
        self.name = name
        self.context = context
        self.parent_id = parent_id
        self.start_ts = time.time()
        self.start_mono = time.monotonic()
        self.end_ts: float | None = None
        self.attrs: dict = attrs if attrs is not None else {}
        self.events: list[dict] | None = None  # lazy (hot-path cost)
        self.error: str = ""
        self._finished = False
        self._entered = False

    # -- recording -----------------------------------------------------------

    @property
    def recording(self) -> bool:
        return self.context.sampled

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def event(self, name: str, **fields) -> None:
        if self.events is None:
            self.events = []
        self.events.append({"ts": time.time(), "name": name, **fields})

    def finish(self) -> None:
        """Record the end time and export. Idempotent; the normal path
        is the context-manager exit, ``finish()`` is for holders that
        outlive a lexical scope (SegmentTimer.done)."""
        if self._finished:
            return
        self._finished = True
        self.end_ts = time.time()
        if self.recording:
            exporter().export(self)

    def to_dict(self) -> dict:
        end = self.end_ts if self.end_ts is not None else time.time()
        out = {
            "name": self.name,
            "trace_id": self.context.trace_id,
            "span_id": self.context.span_id,
            "parent_id": self.parent_id,
            "start": self.start_ts,
            "duration_ms": round((end - self.start_ts) * 1e3, 3),
        }
        if self.attrs:
            out["attrs"] = self.attrs
        if self.events:
            out["events"] = self.events
        if self.error:
            out["error"] = self.error
        return out

    # -- context manager -----------------------------------------------------

    def __enter__(self) -> "Span":
        _stack().append(self)
        self._entered = True
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._entered:
            stack = _stack()
            if stack and stack[-1] is self:
                stack.pop()
            else:  # misnested exit: remove wherever it sits
                try:
                    stack.remove(self)
                except ValueError:
                    pass
            self._entered = False
        if exc is not None and not self.error:
            self.error = f"{type(exc).__name__}: {exc}"
        self.finish()


class _NoopSpan(Span):
    """Shared no-op span for unsampled traces: no ids, no export, no
    per-call allocation -- what keeps the hot path allocation-bound
    with sampling off."""

    _CTX = SpanContext(trace_id="0" * 32, span_id="0" * 16,
                       sampled=False)

    def __init__(self):
        super().__init__("noop", self._CTX)

    @property
    def recording(self) -> bool:
        return False

    def set_attr(self, key: str, value) -> None:
        pass

    def event(self, name: str, **fields) -> None:
        pass

    def finish(self) -> None:
        pass

    def __enter__(self) -> "Span":
        # The unsampled root still occupies the thread stack: nested
        # span() calls must inherit the root's NO decision, not see an
        # empty stack and re-roll sampling (which would export orphan
        # child traces at fractional rates). The shared object is safe
        # to push from many threads/nestings -- entry/exit are
        # symmetric appends/pops of plain references.
        _stack().append(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        stack = _stack()
        if stack and stack[-1] is self:
            stack.pop()


NOOP_SPAN = _NoopSpan()

_tls = threading.local()


def _stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def current_span() -> Span | None:
    """The innermost active span on the calling thread, or None."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def sample_rate() -> float:
    try:
        rate = float(os.environ.get(ENV_SAMPLE, "1"))
    except ValueError:
        return 1.0
    return min(max(rate, 0.0), 1.0)


def _root_sampled() -> bool:
    rate = sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return random.random() < rate


def start_span(name: str, parent: Span | SpanContext | None = None,
               attrs: dict | None = None) -> Span:
    """Create (and start) a span WITHOUT entering it on the thread
    stack. The caller owns its lifecycle: use it as a context manager,
    or call ``finish()``. ``parent`` may be a Span, a SpanContext
    extracted from a remote carrier, or None (inherit the thread's
    current span; with none active, start a new sampled-or-not root).

    Lint rule TPUDRA012: outside the tracing/timing layer, use the
    with-guarded :func:`span` instead."""
    if parent is None:
        parent = current_span()
    if parent is None:
        if not _root_sampled():
            return NOOP_SPAN
        ctx = SpanContext(trace_id=_new_trace_id(),
                          span_id=_new_span_id(), sampled=True)
        return Span(name, ctx, parent_id="", attrs=attrs)
    parent_ctx = parent.context if isinstance(parent, Span) else parent
    if not parent_ctx.sampled:
        return NOOP_SPAN
    ctx = SpanContext(trace_id=parent_ctx.trace_id,
                      span_id=_new_span_id(), sampled=True)
    return Span(name, ctx, parent_id=parent_ctx.span_id, attrs=attrs)


def span(name: str, parent: Span | SpanContext | None = None,
         attrs: dict | None = None) -> Span:
    """The public with-guarded span API: creates a child of ``parent``
    (default: the thread's current span; a new root when none is
    active); the Span IS the context manager -- entry pushes it for
    the scope, exit exports. (A plain function, not a @contextmanager
    generator: this sits on the allocation hot path and the generator
    frame would double the per-span cost.)"""
    return start_span(name, parent=parent, attrs=attrs)


# -- propagation ---------------------------------------------------------------


def inject(sp: Span | SpanContext, carrier: dict) -> dict:
    """Write the traceparent annotation into ``carrier`` (an
    annotations dict) and return it."""
    ctx = sp.context if isinstance(sp, Span) else sp
    carrier[TRACEPARENT_ANNOTATION] = ctx.to_traceparent()
    return carrier


def extract(annotations: dict | None) -> SpanContext | None:
    """Read the traceparent annotation out of an annotations dict (or
    any object-metadata-shaped mapping); None when absent/invalid."""
    if not annotations:
        return None
    return SpanContext.from_traceparent(
        annotations.get(TRACEPARENT_ANNOTATION, ""))


def trace_id_of(annotations: dict | None) -> str:
    """The sampled trace id carried by an annotations dict, or ''
    (the SLO-histogram exemplar form)."""
    ctx = extract(annotations)
    return ctx.trace_id if ctx is not None and ctx.sampled else ""


# -- export --------------------------------------------------------------------


class TraceExporter:
    """Bounded in-process span ring + optional JSONL file sink.

    The ring is the ``/debug/traces`` source: a fixed number of the
    most recent finished spans, grouped by trace id on read. The JSONL
    path (``TPU_DRA_TRACE_FILE``) appends one span object per line for
    offline analysis; file errors disable the sink rather than ever
    failing a traced operation."""

    def __init__(self, max_spans: int = DEFAULT_RING_SPANS,
                 path: str | None = None,
                 max_file_bytes: int | None = None,
                 keep_files: int | None = None):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(16, int(max_spans)))
        self._path = path or None
        self._file_broken = False
        if max_file_bytes is None:
            max_file_bytes = int(_env_float(
                ENV_TRACE_FILE_MAX_MB, DEFAULT_TRACE_FILE_MAX_MB)
                * 1024 * 1024)
        self._max_file_bytes = max(0, int(max_file_bytes))
        if keep_files is None:
            keep_files = int(_env_float(ENV_TRACE_FILE_KEEP,
                                        DEFAULT_TRACE_FILE_KEEP))
        self._keep_files = max(1, int(keep_files))
        # Size tracked incrementally (stat once at startup for an
        # existing file): the sink must not pay a per-span stat.
        self._file_size = 0
        if self._path:
            try:
                self._file_size = os.path.getsize(self._path)
            except OSError:
                self._file_size = 0
        self.exported_total = 0

    def _rotate_locked(self) -> None:
        """Size cap hit: shift <path>.N-1 -> <path>.N (oldest dropped)
        and move the live file to <path>.1. Any error disables the
        sink -- identical policy to write errors, a traced op never
        fails."""
        for i in range(self._keep_files - 1, 0, -1):
            src = f"{self._path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self._path}.{i + 1}")
        os.replace(self._path, f"{self._path}.1")
        self._file_size = 0

    def export(self, sp: Span) -> None:
        # The ring stores the (terminal, finished) Span object and
        # dict-ifies at READ time: to_dict costs ~2us and export sits
        # on the allocation hot path, while /debug/traces reads are
        # rare and human-paced.
        with self._lock:
            self._ring.append(sp)
            self.exported_total += 1
        if self._path and not self._file_broken:
            line = json.dumps(sp.to_dict(), sort_keys=True) + "\n"
            try:
                with self._lock:
                    if self._max_file_bytes and \
                            self._file_size >= self._max_file_bytes:
                        self._rotate_locked()
                    with open(self._path, "a", encoding="utf-8") as f:
                        f.write(line)
                    self._file_size += len(line)
            except OSError:
                self._file_broken = True
                logger.exception(
                    "trace JSONL sink %s failed; disabling", self._path)

    def spans(self) -> list[dict]:
        with self._lock:
            ring = list(self._ring)
        return [sp.to_dict() for sp in ring]

    def traces(self) -> dict[str, list[dict]]:
        """trace id -> spans sorted by start time."""
        out: dict[str, list[dict]] = {}
        for doc in self.spans():
            out.setdefault(doc["trace_id"], []).append(doc)
        for spans_ in out.values():
            spans_.sort(key=lambda d: d["start"])
        return out

    def trace(self, trace_id: str) -> list[dict]:
        return self.traces().get(trace_id, [])

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # -- /debug/traces endpoints (pkg/httpserver handler signatures) ----------

    def traces_endpoint(self) -> tuple[int, str, bytes]:
        body = json.dumps({"traces": self.traces()},
                          sort_keys=True).encode()
        return 200, "application/json", body

    def trace_endpoint(self, trace_id: str) -> tuple[int, str, bytes]:
        spans_ = self.trace(trace_id.strip("/"))
        if not spans_:
            return 404, "application/json", b'{"error": "unknown trace"}'
        body = json.dumps({"trace_id": trace_id, "spans": spans_},
                          sort_keys=True).encode()
        return 200, "application/json", body


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _ring_size() -> int:
    try:
        return int(os.environ.get(ENV_TRACE_RING, DEFAULT_RING_SPANS))
    except ValueError:
        return DEFAULT_RING_SPANS


_exporter: TraceExporter | None = None
_exporter_lock = threading.Lock()


def exporter() -> TraceExporter:
    """The process-wide exporter (every binary serves it at
    /debug/traces)."""
    global _exporter
    if _exporter is None:
        with _exporter_lock:
            if _exporter is None:
                _exporter = TraceExporter(
                    max_spans=_ring_size(),
                    path=os.environ.get(ENV_TRACE_FILE) or None)
    return _exporter


def set_exporter(exp: TraceExporter) -> TraceExporter:
    """Swap the process exporter (tests / bench isolation)."""
    global _exporter
    with _exporter_lock:
        _exporter = exp
    return exp
