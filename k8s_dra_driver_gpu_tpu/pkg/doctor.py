"""One-command diagnostics bundles: ``python -m ...pkg.doctor``.

Debugging the driver used to mean hand-scraping four binaries'
``/metrics`` and ``/debug/*`` endpoints before the evidence aged out
of the bounded rings. The doctor crawls every binary's full
introspection surface -- ``/metrics``, ``/debug/traces``,
``/debug/claims`` (plus each claim's timeline), ``/debug/stacks``,
``/debug/telemetry``, ``/debug/fleet`` -- into ONE timestamped
``.tar.gz`` incident bundle, together with a correlated per-claim
report that merges the flight-recorder timelines of all binaries into
one ordered story per claim.

CLI::

    python -m k8s_dra_driver_gpu_tpu.pkg.doctor \\
        scheduler=http://127.0.0.1:9090 \\
        plugin=http://127.0.0.1:9091 \\
        cd-plugin=http://127.0.0.1:9092 \\
        --out-dir /tmp --claim default/my-claim

Automatic bundles: the gang-abort (computedomain/plugin/driver.py) and
eviction-deadline (pkg/recovery.py) failure paths call
:func:`auto_bundle` -- when ``TPU_DRA_DOCTOR_DIR`` is set, the
triggering binary drops a bundle of its OWN in-process surfaces (no
HTTP round trip; the rings live in this process) plus any peers listed
in ``TPU_DRA_DOCTOR_ENDPOINTS`` (``name=url,name=url``). Rate-limited
to one bundle per ``TPU_DRA_DOCTOR_MIN_INTERVAL_S`` (default 300s) so
a failure storm can't fill the disk, and ALWAYS best-effort: a doctor
failure never fails the operation that triggered it.
"""

from __future__ import annotations

import argparse
import io
import json
import logging
import os
import tarfile
import threading
import time
import urllib.request

logger = logging.getLogger(__name__)

ENV_DOCTOR_DIR = "TPU_DRA_DOCTOR_DIR"
ENV_DOCTOR_ENDPOINTS = "TPU_DRA_DOCTOR_ENDPOINTS"
ENV_DOCTOR_MIN_INTERVAL = "TPU_DRA_DOCTOR_MIN_INTERVAL_S"

#: The introspection surface crawled per target, in crawl order.
SURFACE_PATHS = (
    "metrics",
    "debug/traces",
    "debug/claims",
    "debug/stacks",
    "debug/telemetry",
    "debug/fleet",
)

#: Per-claim timelines fetched at most for this many claim keys (a
#: huge ring should fatten the bundle, not hang the crawl).
MAX_CLAIM_FETCH = 200

_FETCH_TIMEOUT_S = 3.0


def _fetch(url: str) -> tuple[bytes, str]:
    """GET one URL; returns (body, error) with exactly one non-empty."""
    try:
        with urllib.request.urlopen(url, timeout=_FETCH_TIMEOUT_S) as r:
            return r.read(), ""
    except Exception as e:  # noqa: BLE001 - crawl must finish
        return b"", f"{type(e).__name__}: {e}"


def _member(tar: tarfile.TarFile, name: str, body: bytes,
            mtime: float) -> None:
    info = tarfile.TarInfo(name=name)
    info.size = len(body)
    info.mtime = int(mtime)
    tar.addfile(info, io.BytesIO(body))


def _suffix(path: str) -> str:
    return ".txt" if path in ("metrics", "debug/stacks") else ".json"


def crawl_target(name: str, base_url: str) -> dict:
    """Crawl one binary's surface; returns
    ``{path: {"body": bytes} | {"error": str}}``."""
    base = base_url.rstrip("/")
    out: dict[str, dict] = {}
    for path in SURFACE_PATHS:
        body, err = _fetch(f"{base}/{path}")
        out[path] = {"error": err} if err else {"body": body}
    # Per-claim timelines: expand the /debug/claims index.
    claims_doc = out.get("debug/claims", {})
    keys: list[str] = []
    if "body" in claims_doc:
        try:
            keys = list(json.loads(claims_doc["body"]).get(
                "claims", []))[:MAX_CLAIM_FETCH]
        except (ValueError, AttributeError):
            keys = []
    for key in keys:
        body, err = _fetch(f"{base}/debug/claims/{key}")
        out[f"debug/claims/{key}"] = (
            {"error": err} if err else {"body": body})
    return out


def _correlate(crawls: dict[str, dict]) -> dict:
    """Merge every target's per-claim flight timelines into one
    ordered, source-tagged story per claim -- the report half the
    operator reads first."""
    claims: dict[str, list[dict]] = {}
    traces: dict[str, int] = {}
    anomalies: dict[str, float] = {}
    for target, surface in crawls.items():
        for path, doc in surface.items():
            if "body" not in doc:
                continue
            if path.startswith("debug/claims/"):
                try:
                    payload = json.loads(doc["body"])
                except ValueError:
                    continue
                key = payload.get("key", path.rsplit("/", 1)[-1])
                for ev in payload.get("events", []):
                    claims.setdefault(key, []).append(
                        {**ev, "source": target})
            elif path == "debug/traces":
                try:
                    payload = json.loads(doc["body"])
                except ValueError:
                    continue
                for tid, spans in (payload.get("traces") or {}).items():
                    traces[tid] = traces.get(tid, 0) + len(spans)
            elif path == "metrics":
                for line in doc["body"].decode(
                        "utf-8", "replace").splitlines():
                    if line.startswith("tpu_dra_anomaly_total{"):
                        try:
                            label, val = line.rsplit(" ", 1)
                            anomalies[label] = (anomalies.get(label, 0)
                                                + float(val))
                        except ValueError:
                            pass
    for events in claims.values():
        events.sort(key=lambda ev: ev.get("ts", 0.0))
    return {
        "claims": claims,
        "trace_span_counts": traces,
        "anomaly_counters": anomalies,
    }


def bundle_path(out_dir: str, trigger: str,
                now: float | None = None) -> str:
    stamp = time.strftime("%Y%m%d-%H%M%S",
                          time.gmtime(now if now is not None
                                      else time.time()))
    return os.path.join(
        out_dir, f"tpu-dra-doctor-{stamp}-{trigger}.tar.gz")


def collect_bundle(targets: dict[str, str], out_dir: str = ".",
                   claim: str = "", trigger: str = "manual",
                   extra_members: dict[str, bytes] | None = None,
                   out_path: str | None = None) -> str:
    """Crawl ``targets`` (name -> base URL) and write the bundle;
    returns its path. ``claim`` focuses the report on one claim key
    (everything is still collected). ``extra_members`` lets the
    in-process auto-bundle path add local dumps without a listener;
    ``out_path`` pins the destination (the async auto-bundle computes
    it up front so it can be reported before the crawl finishes)."""
    now = time.time()
    if out_path is None:
        out_path = bundle_path(out_dir, trigger, now)
    crawls = {name: crawl_target(name, url)
              for name, url in targets.items()}
    report = _correlate(crawls)
    if claim:
        focused = {k: v for k, v in report["claims"].items()
                   if claim in (k,) or claim in k}
        report["focus_claim"] = claim
        report["focus_events"] = focused
    manifest = {
        "created": now,
        "trigger": trigger,
        "targets": dict(targets),
        "surface_paths": list(SURFACE_PATHS),
        "errors": {
            f"{t}/{p}": doc["error"]
            for t, surface in crawls.items()
            for p, doc in surface.items() if doc.get("error")
        },
    }
    os.makedirs(out_dir, exist_ok=True)
    with tarfile.open(out_path, "w:gz") as tar:
        for target, surface in crawls.items():
            for path, doc in surface.items():
                if "body" not in doc:
                    continue
                member = f"{target}/{path}{_suffix(path)}" \
                    if not path.startswith("debug/claims/") \
                    else f"{target}/{path}.json"
                _member(tar, member, doc["body"], now)
        for name, body in (extra_members or {}).items():
            _member(tar, name, body, now)
        _member(tar, "report.json",
                json.dumps(report, sort_keys=True, indent=1).encode(),
                now)
        _member(tar, "manifest.json",
                json.dumps(manifest, sort_keys=True, indent=1).encode(),
                now)
    logger.warning("doctor bundle written: %s (%d target(s), %d "
                   "fetch error(s))", out_path, len(targets),
                   len(manifest["errors"]))
    return out_path


# -- automatic incident bundles -----------------------------------------------

_auto_lock = threading.Lock()
_auto_last = 0.0


def _local_surface() -> dict[str, bytes]:
    """This process's own introspection surfaces, dumped without HTTP
    (the triggering binary IS one of the targets, and its listener may
    be disabled)."""
    from . import fleetstate, flightrecorder, tracing  # noqa: PLC0415
    from .debug import debug_stacks_endpoint  # noqa: PLC0415

    out: dict[str, bytes] = {}
    try:
        out["local/debug/traces.json"] = json.dumps(
            {"traces": tracing.exporter().traces()},
            sort_keys=True).encode()
    except Exception:  # noqa: BLE001 - every dump is best-effort
        pass
    try:
        out["local/debug/claims.json"] = json.dumps(
            {"events": flightrecorder.default().events()},
            sort_keys=True).encode()
    except Exception:  # noqa: BLE001
        pass
    try:
        out["local/debug/stacks.txt"] = debug_stacks_endpoint()[2]
    except Exception:  # noqa: BLE001
        pass
    try:
        out["local/debug/telemetry.json"] = json.dumps(
            fleetstate.default_ring().snapshot(),
            sort_keys=True).encode()
    except Exception:  # noqa: BLE001
        pass
    try:
        out["local/debug/fleet.json"] = json.dumps(
            fleetstate.default_fleet().snapshot(),
            sort_keys=True).encode()
    except Exception:  # noqa: BLE001
        pass
    return out


def _parse_endpoints(raw: str) -> dict[str, str]:
    out = {}
    for item in filter(None, (t.strip() for t in raw.split(","))):
        name, _, url = item.partition("=")
        if name and url:
            out[name.strip()] = url.strip()
    return out


def auto_bundle(trigger: str, claim: str = "",
                env=os.environ) -> str | None:
    """Drop an incident bundle for a failure path (gang abort,
    eviction deadline). No-op unless ``TPU_DRA_DOCTOR_DIR`` is set;
    rate-limited; NEVER raises or blocks -- the local in-process
    surfaces are snapshotted synchronously (the evidence that ages
    out of the rings), but the remote-peer crawl + tar write run on a
    daemon thread: during exactly the incident the bundle is for, the
    peers are the slow thing, and the triggering unwind must not wait
    out their fetch timeouts. Returns the bundle's (eventual) path."""
    global _auto_last
    out_dir = env.get(ENV_DOCTOR_DIR, "")
    if not out_dir:
        return None
    try:
        min_interval = float(env.get(ENV_DOCTOR_MIN_INTERVAL, "300"))
    except ValueError:
        min_interval = 300.0
    with _auto_lock:
        now = time.monotonic()
        if _auto_last and now - _auto_last < min_interval:
            return None
        _auto_last = now
    try:
        os.makedirs(out_dir, exist_ok=True)  # fail HERE, not async
        targets = _parse_endpoints(env.get(ENV_DOCTOR_ENDPOINTS, ""))
        # Snapshot the bounded rings NOW, before the triggering
        # operation's own retry churn ages the evidence out.
        extra = _local_surface()
        out_path = bundle_path(out_dir, trigger)

        def write() -> None:
            try:
                collect_bundle(targets, out_dir=out_dir, claim=claim,
                               trigger=trigger, extra_members=extra,
                               out_path=out_path)
            except Exception:  # noqa: BLE001 - diagnostics
                logger.exception("auto doctor bundle failed "
                                 "(trigger=%s)", trigger)

        threading.Thread(target=write, name="doctor-bundle",
                         daemon=True).start()
        return out_path
    except Exception:  # noqa: BLE001 - diagnostics must never hurt
        logger.exception("auto doctor bundle failed (trigger=%s)",
                         trigger)
        return None


def reset_rate_limit() -> None:
    """Tests: allow the next auto_bundle immediately."""
    global _auto_last
    with _auto_lock:
        _auto_last = 0.0


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m k8s_dra_driver_gpu_tpu.pkg.doctor",
        description="Collect a tpu-dra diagnostics bundle from the "
                    "binaries' metrics/debug endpoints.")
    p.add_argument("targets", nargs="+",
                   help="name=base-url pairs, e.g. "
                        "scheduler=http://127.0.0.1:9090")
    p.add_argument("--out-dir", default=".",
                   help="directory for the bundle (default: .)")
    p.add_argument("--claim", default="",
                   help="claim key (uid or ns/name) to focus the "
                        "correlated report on")
    args = p.parse_args(argv)
    targets = _parse_endpoints(",".join(args.targets))
    if not targets:
        p.error("no valid name=url targets")
    path = collect_bundle(targets, out_dir=args.out_dir,
                          claim=args.claim)
    print(path)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
