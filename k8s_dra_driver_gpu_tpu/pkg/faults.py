"""Deterministic fault injection for the driver stack.

Reference analog: the bats robustness sweep (test_gpu_robustness.bats)
kills and restarts components at fixed points; mock-NVML injects health
events through a control file. This module generalizes both into NAMED
FAULT POINTS compiled into every external-interaction seam of the
runtime -- kube API calls (pkg/retry.py), watch streams
(pkg/kubeclient.py), tpulib enumeration/health (tpulib/binding.py,
kubeletplugin/health.py), flock acquisition (pkg/flock.py), checkpoint
write/fsync (kubeletplugin/checkpoint.py), every SegmentTimer segment of
the prepare/unprepare pipeline (pkg/timing.py), and the CD daemon's
rendezvous service (computedomain/daemon/rendezvous.py).

A fault point is a cheap no-op until armed. Arming happens through the
API (tests: ``with inject("kube.request", mode="error"): ...``) or the
environment (chaos bench / e2e):

    TPU_DRA_FAULTS="kube.request:error:p=0.3:count=5;ckpt.fsync:crash:count=1"
    TPU_DRA_FAULTS_SEED=20260803

Modes:
  error    raise (the call site's default exception, usually the one its
           retry machinery classifies as retriable, else InjectedFault)
  crash    raise InjectedCrash -- a BaseException, so ``except
           Exception`` wire boundaries cannot swallow it; simulates
           process death at the seam for checkpoint-recovery tests
  exit     os._exit(86) (the SIGKILL analog; subprocess harnesses)
  latency  sleep ``latency`` seconds, then continue

Spec keys: ``p=<0..1>`` fire probability (seeded RNG -> deterministic
schedules), ``count=<n>`` max fires, ``after=<n>`` skip the first n
evaluations, ``latency=<s>``.

The registry is process-wide and keeps per-point evaluation/fire
counters (``snapshot()``) so the chaos bench can report what the
schedule actually did.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass

logger = logging.getLogger(__name__)

ENV_FAULTS = "TPU_DRA_FAULTS"
ENV_FAULTS_SEED = "TPU_DRA_FAULTS_SEED"

_MODES = ("error", "crash", "exit", "latency")


class InjectedFault(RuntimeError):
    """Default exception of an ``error``-mode fault point."""


class InjectedCrash(BaseException):
    """A ``crash``-mode firing. Deliberately NOT an Exception: the
    driver's wire boundaries catch Exception to keep serving, and a
    simulated process death must sail through them exactly like a
    SIGKILL would -- only the checkpoint/lease recovery machinery may
    observe the aftermath."""


@dataclass
class FaultSpec:
    """One armed fault point."""

    point: str
    mode: str = "error"
    probability: float = 1.0
    count: int | None = None  # max fires; None = unlimited
    after: int = 0  # skip the first N evaluations
    latency: float = 0.0
    message: str = ""

    def __post_init__(self):
        if self.mode not in _MODES:
            raise ValueError(f"unknown fault mode {self.mode!r}")

    @classmethod
    def parse(cls, token: str) -> "FaultSpec":
        """``point:mode[:k=v...]`` -- the TPU_DRA_FAULTS grammar."""
        parts = [p for p in token.strip().split(":") if p]
        if not parts:
            raise ValueError("empty fault spec")
        point = parts[0]
        mode = parts[1] if len(parts) > 1 else "error"
        spec = cls(point=point, mode=mode)
        for kv in parts[2:]:
            key, _, val = kv.partition("=")
            if key in ("p", "probability"):
                spec.probability = float(val)
            elif key == "count":
                spec.count = int(val)
            elif key == "after":
                spec.after = int(val)
            elif key == "latency":
                spec.latency = float(val)
            else:
                raise ValueError(f"unknown fault spec key {key!r}")
        return spec


class FaultRegistry:
    """Process-wide registry of armed fault points (seeded RNG)."""

    def __init__(self, seed: int | None = None):
        self._lock = threading.Lock()
        self._specs: dict[str, FaultSpec] = {}
        self._rng = random.Random(seed)
        self.evaluations: dict[str, int] = {}
        self.fires: dict[str, int] = {}

    @property
    def active(self) -> bool:
        return bool(self._specs)

    def reseed(self, seed: int | None) -> None:
        with self._lock:
            self._rng = random.Random(seed)

    def arm(self, spec: FaultSpec) -> None:
        with self._lock:
            self._specs[spec.point] = spec

    def disarm(self, point: str) -> None:
        with self._lock:
            self._specs.pop(point, None)

    def reset(self) -> None:
        with self._lock:
            self._specs.clear()
            self.evaluations.clear()
            self.fires.clear()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "armed": sorted(self._specs),
                "evaluations": dict(self.evaluations),
                "fires": dict(self.fires),
            }

    def configure_from_env(self, env=os.environ) -> int:
        """Arm every spec in TPU_DRA_FAULTS; returns how many."""
        raw = env.get(ENV_FAULTS, "")
        seed = env.get(ENV_FAULTS_SEED)
        if seed:
            try:
                self.reseed(int(seed))
            except ValueError:
                logger.warning("bad %s=%r ignored", ENV_FAULTS_SEED, seed)
        n = 0
        for token in filter(None, (t.strip() for t in raw.split(";"))):
            try:
                self.arm(FaultSpec.parse(token))
                n += 1
            except ValueError:
                logger.warning("bad fault spec %r ignored", token)
        return n

    def fire(self, point: str, error=None) -> None:
        """Evaluate ``point``; raise/sleep per its armed spec (no-op when
        unarmed). ``error`` is the call site's exception factory
        (``error(message) -> BaseException``) for ``error`` mode."""
        with self._lock:
            spec = self._specs.get(point)
            if spec is None:
                return
            seen = self.evaluations.get(point, 0) + 1
            self.evaluations[point] = seen
            if seen <= spec.after:
                return
            if spec.count is not None and \
                    self.fires.get(point, 0) >= spec.count:
                return
            if spec.probability < 1.0 and \
                    self._rng.random() >= spec.probability:
                return
            self.fires[point] = self.fires.get(point, 0) + 1
            mode, latency = spec.mode, spec.latency
            message = spec.message or f"injected fault at {point}"
        if mode == "latency":
            time.sleep(latency)
            return
        logger.warning("fault injection: %s at %s", mode, point)
        if mode == "exit":
            os._exit(86)
        if mode == "crash":
            raise InjectedCrash(message)
        raise (error(message) if error is not None
               else InjectedFault(message))


# The process-wide registry. Env arming happens on first import so any
# entrypoint launched with TPU_DRA_FAULTS set participates.
_REGISTRY = FaultRegistry()
_REGISTRY.configure_from_env()


def registry() -> FaultRegistry:
    return _REGISTRY


def active() -> bool:
    return _REGISTRY.active


def fault_point(point: str, error=None) -> None:
    """The seam call compiled into external-interaction layers. Cheap
    when nothing is armed (one attribute read + bool check)."""
    if _REGISTRY.active:
        _REGISTRY.fire(point, error=error)


def arm(point: str, mode: str = "error", probability: float = 1.0,
        count: int | None = None, after: int = 0, latency: float = 0.0,
        message: str = "") -> FaultSpec:
    spec = FaultSpec(point=point, mode=mode, probability=probability,
                     count=count, after=after, latency=latency,
                     message=message)
    _REGISTRY.arm(spec)
    return spec


def disarm(point: str) -> None:
    _REGISTRY.disarm(point)


def reset() -> None:
    _REGISTRY.reset()


def reseed(seed: int | None) -> None:
    _REGISTRY.reseed(seed)


def snapshot() -> dict:
    return _REGISTRY.snapshot()


@contextmanager
def inject(point: str, mode: str = "error", probability: float = 1.0,
           count: int | None = None, after: int = 0, latency: float = 0.0,
           message: str = ""):
    """Test fixture: arm one point for the duration of the block."""
    arm(point, mode=mode, probability=probability, count=count,
        after=after, latency=latency, message=message)
    try:
        yield _REGISTRY
    finally:
        disarm(point)
