"""Permanent-failure recovery: claim eviction & migration controller.

PR 4 made the control plane survive *transient* faults (retries, gang
deadlines, quarantine taints). This module handles the failures that
never heal: a host that dies, a chip that fails fatally, a kubelet
plugin wiped mid-prepare. The reference driver's core promise is that
claims *converge* after any failure (gang-prepare + unwind semantics);
here that promise is extended past process death to hardware death.

Three cooperating pieces:

- :class:`FailureDetector` -- escalates transient badness to a declared
  **permanent failure**: a node ``NotReady`` past a grace deadline, a
  node deleted outright, or a device carrying a fatal taint
  (``tpu.dra.dev/failed`` from the health layer's quarantine
  escalation, or any fatal ``NoExecute`` health taint).
- :class:`EvictionController` -- for every allocated claim touched by a
  permanent failure: declare a ``PermanentFailure`` condition on the
  claim, taint the node ``tpu.dra.dev/failed``, then drive a staged
  eviction (drain consumer pods -> drop reservations -> deallocate) so
  the event-driven scheduler (pkg/scheduler) re-places the claim on
  surviving capacity. Gang claims (ComputeDomain channels sharing a
  ``domainID``) are evicted as a unit -- a gang with one dead member
  can never rendezvous, so its surviving nodes are drained too (their
  plugins unwind via the reconcile sweep, reusing
  ``CDDeviceState.unwind_failed_prepare`` semantics). Moves are
  *planned*: each eviction group is scored by migration cost vs. gang
  disruption (the MIG-aware VM placement framing, 2502.01909) and
  admitted under a bounded concurrency cap, cheapest recovery first.
- Durable progress -- every in-flight eviction is one record in a
  group-committed CheckpointManager (kubeletplugin/checkpoint.py) under
  the ``eviction`` TransitionPolicy (pkg/analysis/statemachine.py), so
  a controller crash mid-eviction resumes idempotently from the
  durable state, and an illegal stage skip fails the commit loudly.

Per-claim recovery deadlines bound the tail: a claim that cannot be
re-placed within ``TPU_DRA_RECOVERY_DEADLINE_S`` retires as *cleanly
failed* -- ``PermanentFailure`` condition with reason
``RecoveryDeadlineExceeded``, no allocation, no in-flight record --
never stuck mid-eviction.

The node-plugin half of the story (the cross-layer reconciliation
sweep) lives in ``kubeletplugin/reconcile.py``; both export
``tpu_dra_recovery_*`` metrics (pkg/metrics.RecoveryMetrics).
"""

from __future__ import annotations

import logging
import threading
import time

from . import json_copy, positive_float_env
from . import faults, flightrecorder, tracing
from .analysis.statemachine import (
    EVICTION_DEALLOCATED,
    EVICTION_DRAINING,
    EVICTION_PLANNED,
    EVICTION_POLICY,
)
from .kubeclient import ConflictError, KubeError, NotFoundError

logger = logging.getLogger(__name__)

RESOURCE = ("resource.k8s.io", "v1")

#: Node + device taint key of a DECLARED permanent failure. On a node:
#: NoExecute, applied by the controller at escalation. On a device:
#: published by the health layer's quarantine escalation
#: (kubeletplugin/health.py) and treated as fatal here.
FAILED_TAINT_KEY = "tpu.dra.dev/failed"

#: ResourceClaim condition type carrying the declared failure (and,
#: with status False / reason Recovered, the successful migration).
PERMANENT_FAILURE_CONDITION = "PermanentFailure"

#: Device-taint prefix whose NoExecute entries count as fatal chip
#: events (hbm_uncorrectable, chip_lost, ... -- health.py maps fatal
#: tpulib events to NoExecute taints under this prefix).
_HEALTH_TAINT_PREFIX = "tpu.dra.dev/"

# Operator knobs (docs/operations.md "Permanent-failure recovery").
NOTREADY_GRACE_S = positive_float_env(
    "TPU_DRA_RECOVERY_NOTREADY_S", default=60.0, floor=0.01)
RECOVERY_DEADLINE_S = positive_float_env(
    "TPU_DRA_RECOVERY_DEADLINE_S", default=300.0, floor=0.01)
MAX_CONCURRENT_EVICTIONS = int(positive_float_env(
    "TPU_DRA_RECOVERY_MAX_CONCURRENT", default=4, floor=1))
#: Weight of one disrupted healthy gang companion relative to one
#: migrated device in the move score (2502.01909: recovered capacity
#: is traded against disruption, not taken for free).
DISRUPTION_WEIGHT = positive_float_env(
    "TPU_DRA_RECOVERY_DISRUPTION_WEIGHT", default=4.0, floor=0.0)
#: Weight of one fully-aged claim (uptime >= AGE_SCALE_S) in the move
#: score: migrating a claim that has been running for hours throws
#: away hours of work (checkpoint distance, warmed caches), so the
#: planner prefers moving young claims over long-running training
#: gangs when either recovers the same capacity.
AGE_WEIGHT = positive_float_env(
    "TPU_DRA_RECOVERY_AGE_WEIGHT", default=2.0, floor=0.0)
#: Uptime at which a claim counts as fully aged (the age term
#: saturates there -- a week-old gang is not 50x costlier than a
#: 3-hour one, it is simply "old").
AGE_SCALE_S = positive_float_env(
    "TPU_DRA_RECOVERY_AGE_SCALE_S", default=3600.0, floor=1.0)

#: Claims carrying this annotation (any value but "false") declare the
#: cooperative checkpoint-then-switch contract (pkg/migration): the
#: workload checkpoints on demand when signaled, so moving it costs a
#: bounded checkpoint-restore instead of a cold restart.
MIGRATION_CAPABLE_ANNOTATION = "resource.tpu.dra/migration-capable"

#: The second price tier of the 2502.01909 migration-cost model: a
#: move group whose every member is migration-capable scores at this
#: fraction of its cold cost. 0.25 means a cooperative gang is four
#: times cheaper to displace -- recovery admission, defrag victim
#: selection, and the autoscaler's repack hysteresis all converge more
#: aggressively on workloads that promised to cooperate.
COOP_COST_FACTOR = positive_float_env(
    "TPU_DRA_COOP_COST_FACTOR", default=0.25, floor=0.0)


def claim_migration_capable(claim: dict) -> bool:
    raw = (_meta(claim).get("annotations") or {}).get(
        MIGRATION_CAPABLE_ANNOTATION)
    return raw is not None and raw not in ("false", "False", "0")


def coop_cost_multiplier(claims: list[dict],
                         factor: float | None = None) -> float:
    """Cooperative discount for one move group: ``factor`` when EVERY
    member declares the checkpoint-then-switch contract, 1.0
    otherwise. All-or-nothing on purpose: a gang with one cold-only
    member still pays a full cold rendezvous, so discounting it would
    misprice the move."""
    if not claims:
        return 1.0
    factor = COOP_COST_FACTOR if factor is None else factor
    if all(claim_migration_capable(c) for c in claims):
        return min(max(factor, 0.0), 1.0)
    return 1.0


def _meta(obj: dict) -> dict:
    return obj.get("metadata", {})


def _node_ready(node: dict) -> bool:
    """A node with no Ready condition at all reads as Ready: bare test
    environments (and freshly registered nodes) must not be mass-failed
    by an absent status block."""
    for cond in node.get("status", {}).get("conditions") or []:
        if cond.get("type") == "Ready":
            return cond.get("status") == "True"
    return True


def claim_gang_id(claim: dict) -> str | None:
    """The ComputeDomain uid a channel claim belongs to, or None.
    Gangs are the unit of eviction: one permanently failed member
    strands the whole rendezvous."""
    for cfg in claim.get("spec", {}).get("devices", {}).get(
            "config", []) or []:
        params = (cfg.get("opaque") or {}).get("parameters") or {}
        if params.get("kind") == "ComputeDomainChannelConfig" and \
                params.get("domainID"):
            return params["domainID"]
    return None


def allocation_nodes(claim: dict) -> set[str]:
    """Node names an allocation pins (from its nodeSelector)."""
    alloc = claim.get("status", {}).get("allocation") or {}
    nodes: set[str] = set()
    for term in alloc.get("nodeSelector", {}).get(
            "nodeSelectorTerms", []):
        for mf in term.get("matchFields", []):
            if mf.get("key") == "metadata.name":
                nodes.update(mf.get("values") or [])
    return nodes


def allocation_device_keys(claim: dict) -> set[tuple[str, str, str]]:
    alloc = claim.get("status", {}).get("allocation") or {}
    return {
        (r.get("driver", ""), r.get("pool", ""), r.get("device", ""))
        for r in alloc.get("devices", {}).get("results", [])
    }


def claim_age_s(claim: dict, now: float | None = None) -> float:
    """Claim uptime in seconds from ``metadata.creationTimestamp``
    (RFC3339); 0.0 when absent or unparseable -- an ageless claim is
    scored as brand new, i.e. cheap to move, which fails safe (the
    planner can only UNDER-protect a claim it cannot date)."""
    ts = _meta(claim).get("creationTimestamp")
    if not ts or not isinstance(ts, str):
        return 0.0
    import datetime  # noqa: PLC0415 - leaf helper, cold path

    try:
        created = datetime.datetime.fromisoformat(
            ts.replace("Z", "+00:00"))
    except ValueError:
        return 0.0
    if created.tzinfo is None:
        created = created.replace(tzinfo=datetime.timezone.utc)
    now = time.time() if now is None else now
    return max(now - created.timestamp(), 0.0)


def age_cost(claims: list[dict], age_weight: float = AGE_WEIGHT,
             age_scale_s: float = AGE_SCALE_S,
             now: float | None = None) -> float:
    """The uptime term of a migration-cost score, summed over a move
    group: each claim contributes ``age_weight x min(uptime /
    age_scale, 1)``. Shared by the eviction planner and the defrag
    planner (pkg/defrag) so 'prefer young victims' means the same
    thing in both."""
    now = time.time() if now is None else now
    return age_weight * sum(
        min(claim_age_s(c, now) / age_scale_s, 1.0) for c in claims)


def consumer_pods_of(claim: dict, pods: list[dict]) -> list[dict]:
    """Pods consuming a claim: reservedFor entries, resourceClaims
    refs/statuses, and the extended-resource claim status."""
    ns = _meta(claim).get("namespace", "default")
    name = _meta(claim).get("name", "")
    reserved = {
        (ns, r.get("name", ""))
        for r in claim.get("status", {}).get("reservedFor") or []
        if r.get("resource") == "pods"
    }
    out = []
    for pod in pods:
        pns = _meta(pod).get("namespace", "default")
        if pns != ns:
            continue
        if (pns, _meta(pod).get("name", "")) in reserved:
            out.append(pod)
            continue
        statuses = {s.get("resourceClaimName")
                    for s in pod.get("status", {}).get(
                        "resourceClaimStatuses") or []}
        refs = {r.get("resourceClaimName")
                for r in pod.get("spec", {}).get(
                    "resourceClaims") or []}
        ext = (pod.get("status", {}).get(
            "extendedResourceClaimStatus") or {}).get(
            "resourceClaimName")
        if name in statuses or name in refs or name == ext:
            out.append(pod)
    return out


def drain_claim(kube, claim: dict, pods: list[dict]) -> None:
    """The drain stage both migration controllers share (eviction +
    defrag): evict BOUND consumer pods and drop the reservations.
    Unbound pods survive -- they simply wait for the re-placement;
    deleted pods come back through their controllers (Jobs,
    DaemonSets) exactly like a real eviction."""
    ns = _meta(claim).get("namespace", "default")
    for pod in consumer_pods_of(claim, pods):
        if not pod.get("spec", {}).get("nodeName"):
            continue
        try:
            kube.delete("", "v1", "pods", _meta(pod)["name"],
                        namespace=ns)
            logger.warning("evicted pod %s/%s (consumer of migrating "
                           "claim %s)", ns, _meta(pod)["name"],
                           _meta(claim).get("uid", ""))
        except NotFoundError:
            pass
    if claim.get("status", {}).get("reservedFor"):
        try:
            kube.patch(*RESOURCE, "resourceclaims",
                       _meta(claim)["name"],
                       {"status": {"reservedFor": None}},
                       namespace=ns)
        except (NotFoundError, ConflictError):
            pass


def clear_allocation(kube, claim: dict) -> bool:
    """The deallocate stage both migration controllers share: clear
    the claim's allocation so the incremental scheduler owns
    re-placement. Returns False when the write was refused (NotFound /
    Conflict) -- the caller re-examines next pass."""
    try:
        kube.patch(*RESOURCE, "resourceclaims", _meta(claim)["name"],
                   {"status": {"allocation": None}},
                   namespace=_meta(claim).get("namespace", "default"))
    except (NotFoundError, ConflictError):
        return False
    return True


def set_permanent_failure_condition(kube, claim: dict, status: str,
                                    reason: str, message: str) -> bool:
    """Upsert the claim's PermanentFailure condition (deduped on
    status+reason). Shared by the eviction controller and the node
    plugins' reconcile sweep. Returns True when a patch was written."""
    ns = _meta(claim).get("namespace", "default")
    name = _meta(claim).get("name", "")
    conditions = claim.get("status", {}).get("conditions") or []
    for c in conditions:
        if c.get("type") == PERMANENT_FAILURE_CONDITION and \
                c.get("status") == status and \
                c.get("reason") == reason:
            return False  # already says exactly this
    kept = [c for c in conditions
            if c.get("type") != PERMANENT_FAILURE_CONDITION]
    kept.append({
        "type": PERMANENT_FAILURE_CONDITION,
        "status": status,
        "reason": reason,
        "message": message,
    })
    try:
        kube.patch(*RESOURCE, "resourceclaims", name,
                   {"status": {"conditions": kept}}, namespace=ns)
    except (NotFoundError, ConflictError):
        return False
    return True


class FailureDetector:
    """Escalates node/device badness to declared permanent failures.

    State is in-memory and re-derived every observation pass; the
    DURABLE failure markers are the node taint and the claim condition
    the controller writes, plus the deleted node's retired slices --
    so a restarted controller re-detects everything that still
    matters and nothing that healed."""

    def __init__(self, notready_grace_s: float = NOTREADY_GRACE_S,
                 clock=time.monotonic):
        self.notready_grace_s = notready_grace_s
        self._clock = clock
        self._known: set[str] = set()
        self._not_ready_since: dict[str, float] = {}
        #: Nodes declared permanently failed (NotReady past grace, or
        #: carrying the failed taint already -- the durable marker).
        self.failed_nodes: set[str] = set()
        #: Nodes that existed and were deleted (positive evidence: the
        #: node list that no longer contains them SUCCEEDED).
        self.deleted_nodes: set[str] = set()

    def observe_nodes(self, nodes: list[dict]) -> None:
        now = self._clock()
        present = {_meta(n)["name"] for n in nodes if _meta(n).get("name")}
        self.deleted_nodes |= self._known - present
        self.deleted_nodes -= present  # a re-registered node is alive
        self._known |= present
        failed: set[str] = set()
        for node in nodes:
            name = _meta(node).get("name")
            if not name:
                continue
            tainted = any(
                t.get("key") == FAILED_TAINT_KEY
                for t in node.get("spec", {}).get("taints") or [])
            if _node_ready(node) and not tainted:
                self._not_ready_since.pop(name, None)
                continue
            since = self._not_ready_since.setdefault(name, now)
            if tainted or now - since >= self.notready_grace_s:
                failed.add(name)
        self.failed_nodes = failed

    @property
    def permanently_failed(self) -> set[str]:
        return self.failed_nodes | self.deleted_nodes

    @staticmethod
    def fatal_device_keys(slices: list[dict]) -> set[tuple[str, str, str]]:
        """(driver, pool, device) keys carrying a declared-failed taint
        or any fatal (NoExecute) health taint."""
        fatal: set[tuple[str, str, str]] = set()
        for s in slices:
            spec = s.get("spec", {})
            driver = spec.get("driver", "")
            pool = spec.get("pool", {}).get("name", "")
            for dev in spec.get("devices", []) or []:
                for taint in dev.get("taints") or []:
                    key = taint.get("key", "")
                    if key == FAILED_TAINT_KEY or (
                            taint.get("effect") == "NoExecute"
                            and key.startswith(_HEALTH_TAINT_PREFIX)):
                        fatal.add((driver, pool, dev.get("name", "")))
                        break
        return fatal


class EvictionController:
    """Plans and drives permanent-failure evictions; designed to run
    inside the event-driven scheduler loop (``attach_recovery``) or be
    driven directly (``sync_once``) by tests and the chaos bench."""

    #: Meta device name carrying the eviction record's plan payload
    #: (failed node, source, planned-at wall clock, score) in its
    #: ``live`` dict -- the checkpoint schema's one free-form slot.
    _META_DEVICE = "eviction"

    def __init__(self, kube, root: str, metrics=None,
                 notready_grace_s: float = NOTREADY_GRACE_S,
                 deadline_s: float = RECOVERY_DEADLINE_S,
                 max_concurrent: int = MAX_CONCURRENT_EVICTIONS,
                 disruption_weight: float = DISRUPTION_WEIGHT,
                 age_weight: float = AGE_WEIGHT,
                 clock=time.monotonic):
        # Imported here, not at module top: pkg -> kubeletplugin is a
        # one-way street everywhere else; keeping it function-local
        # preserves pkg's import-light surface for non-driver users.
        from ..kubeletplugin.checkpoint import (  # noqa: PLC0415
            CheckpointManager,
        )

        self.kube = kube
        self.metrics = metrics  # pkg.metrics.RecoveryMetrics | None
        self.deadline_s = deadline_s
        self.max_concurrent = max(1, int(max_concurrent))
        self.disruption_weight = disruption_weight
        self.age_weight = age_weight
        self.detector = FailureDetector(
            notready_grace_s=notready_grace_s, clock=clock)
        # Eviction lifecycle records, durable + transition-validated:
        # the idempotent-resume anchor (see module docstring).
        self._checkpoint = CheckpointManager(
            root, transition_policy=EVICTION_POLICY)
        self._lock = threading.Lock()
        self._excluded: frozenset[str] = frozenset()
        # Optional read surface (pkg/schedcache.ClusterView), set by
        # DraScheduler.attach_recovery: event mode serves these reads
        # from informer caches, so a recovery pass costs ZERO kube
        # list calls; writes always go through the kube client.
        self.view = None
        # Resumed records count (cheap busy() signal for the
        # scheduler's claim-event gating).
        self._active_count = len(self._checkpoint.get().claims)
        self.last_sync: dict = {}
        # Claim-lifecycle SLO sink (pkg/metrics.ClaimSLOMetrics): set
        # by DraScheduler.attach_recovery so eviction e2e latency
        # (plan -> re-placement) reports as the "evict" phase on the
        # scheduler's registry. None = standalone controller, no SLO.
        self.slo = None
        # Per-claim flight recorder: every eviction stage transition
        # lands in the ring, and a deadline failure dumps the claim's
        # whole timeline into the log.
        self.flight = flightrecorder.default()

    # -- scheduler surface ----------------------------------------------------

    def excluded_nodes(self) -> frozenset[str]:
        """Nodes allocation must avoid; cheap cached read for the
        scheduler's per-claim fit."""
        with self._lock:
            return self._excluded

    def busy(self) -> bool:
        """True while any eviction record is in flight -- the
        scheduler gates per-claim-event recovery enqueues on this so
        ordinary claim churn never triggers recovery passes."""
        with self._lock:
            return self._active_count > 0

    def active_evictions(self) -> dict[str, str]:
        """uid -> eviction state of every in-flight record."""
        return {uid: rec.state
                for uid, rec in self._checkpoint.get().claims.items()}

    # -- reads ----------------------------------------------------------------
    # Through the scheduler's ClusterView when attached (informer
    # caches in event mode, identical KubeError semantics in direct
    # mode); straight off the kube client otherwise. Cache staleness
    # is safe here: every advance step is an idempotent patch, and the
    # safety resync re-drives anything a stale read deferred.

    def _list_nodes(self) -> list[dict]:
        if self.view is not None:
            return self.view.nodes()
        return self.kube.list("", "v1", "nodes")

    def _list_slices(self) -> list[dict]:
        if self.view is not None:
            return self.view.slices()
        return self.kube.list(*RESOURCE, "resourceslices")

    def _list_claims(self) -> list[dict]:
        if self.view is not None:
            return self.view.claims()
        return self.kube.list(*RESOURCE, "resourceclaims")

    # -- sync -----------------------------------------------------------------

    def sync_once(self) -> dict:
        """One full detect -> plan -> advance pass. Every stage is
        idempotent; a crash anywhere resumes from the durable records.
        Returns a counts summary (also kept as ``last_sync``)."""
        faults.fault_point("recovery.sync")
        counts = {"victims": 0, "planned": 0, "drained": 0,
                  "deallocated": 0, "replaced": 0, "failed": 0,
                  "canceled": 0}
        try:
            nodes = self._list_nodes()
        except KubeError:
            nodes = None
        try:
            slices = self._list_slices()
            claims = self._list_claims()
        except KubeError:
            logger.warning("recovery sync: inventory list failed; "
                           "retrying next pass")
            return counts
        if nodes is not None:
            self.detector.observe_nodes(nodes)
        failed_nodes = self.detector.permanently_failed
        fatal_devices = self.detector.fatal_device_keys(slices)
        with self._lock:
            self._excluded = frozenset(failed_nodes)

        if nodes is not None:
            self._taint_failed_nodes(nodes)
        self._retire_deleted_node_slices(slices)

        victims = self._find_victims(claims, failed_nodes, fatal_devices)
        counts["victims"] = len(victims)
        self._plan(victims, claims, counts)
        self._advance(claims, failed_nodes, fatal_devices, counts)

        active = len(self._checkpoint.get().claims)
        with self._lock:
            self._active_count = active
        if self.metrics is not None:
            self.metrics.active_evictions.set(active)
        self.last_sync = counts
        return counts

    # -- escalation -----------------------------------------------------------

    def _taint_failed_nodes(self, nodes: list[dict]) -> None:
        """Durably mark failed nodes (NoExecute): the taint is the
        restart-safe failure marker and the operator-visible signal."""
        for node in nodes:
            name = _meta(node).get("name")
            if not name or name not in self.detector.failed_nodes:
                continue
            taints = node.get("spec", {}).get("taints") or []
            if any(t.get("key") == FAILED_TAINT_KEY for t in taints):
                continue
            new_taints = json_copy(taints) + [{
                "key": FAILED_TAINT_KEY, "value": "true",
                "effect": "NoExecute",
            }]
            try:
                self.kube.patch("", "v1", "nodes", name,
                                {"spec": {"taints": new_taints}})
                logger.warning("node %s declared permanently failed "
                               "(%s taint applied)", name,
                               FAILED_TAINT_KEY)
            except (NotFoundError, ConflictError):
                pass

    def _retire_deleted_node_slices(self, slices: list[dict]) -> None:
        """A deleted node's ResourceSlices are orphans (a real cluster
        GCs them via ownerRefs): retire them so the inventory snapshot
        stops offering capacity that no longer exists."""
        for s in slices:
            node = s.get("spec", {}).get("nodeName")
            if node and node in self.detector.deleted_nodes:
                try:
                    self.kube.delete(*RESOURCE, "resourceslices",
                                     _meta(s)["name"])
                except NotFoundError:
                    continue
                if self.metrics is not None:
                    self.metrics.orphans_repaired.labels("slice").inc()
                logger.warning(
                    "retired orphan slice %s of deleted node %s",
                    _meta(s).get("name"), node)

    def _find_victims(self, claims, failed_nodes, fatal_devices
                      ) -> dict[str, str]:
        """uid -> failure source for every allocated claim touched by a
        permanent failure, expanded to whole gangs."""
        by_gang: dict[str, list[dict]] = {}
        victims: dict[str, str] = {}
        direct: dict[str, dict] = {}
        for claim in claims:
            if not claim.get("status", {}).get("allocation"):
                continue
            if _meta(claim).get("deletionTimestamp"):
                continue
            gang = claim_gang_id(claim)
            if gang:
                by_gang.setdefault(gang, []).append(claim)
            uid = _meta(claim).get("uid", "")
            if not uid:
                continue
            if allocation_nodes(claim) & failed_nodes:
                victims[uid] = "node"
                direct[uid] = claim
            elif allocation_device_keys(claim) & fatal_devices:
                victims[uid] = "device"
                direct[uid] = claim
        # Gang expansion: every allocated companion of a failed member
        # must drain too (surviving nodes unwind via their plugins'
        # reconcile sweep).
        for gang, members in by_gang.items():
            if not any(_meta(m).get("uid") in victims for m in members):
                continue
            for m in members:
                uid = _meta(m).get("uid", "")
                if uid and uid not in victims:
                    victims[uid] = "gang"
        return victims

    # -- planning -------------------------------------------------------------

    def _plan(self, victims: dict[str, str], claims: list[dict],
              counts: dict) -> None:
        """Score and admit new evictions under the concurrency cap.
        Groups (whole gangs / singletons) are admitted atomically,
        cheapest recovery first: score = devices to migrate +
        disruption_weight x healthy companions disturbed."""
        if not victims:
            return
        records = self._checkpoint.get().claims
        new = {uid: src for uid, src in victims.items()
               if uid not in records}
        if not new:
            return
        with self._lock:
            # Eager busy(): the condition/record writes below fire
            # synchronous informer events whose recovery enqueues are
            # gated on it -- the count proper lands at end of sync.
            self._active_count = max(self._active_count, 1)
        by_uid = {_meta(c).get("uid", ""): c for c in claims}
        groups: dict[str, list[str]] = {}
        for uid in new:
            claim = by_uid.get(uid)
            gang = claim_gang_id(claim) if claim else None
            groups.setdefault(gang or f"solo-{uid}", []).append(uid)
        scored = []
        now = time.time()
        for gid, uids in groups.items():
            cost = sum(len(allocation_device_keys(by_uid[u]))
                       for u in uids if u in by_uid)
            disruption = sum(1 for u in uids if new.get(u) == "gang")
            # Uptime term: admission order prefers young claims, so a
            # long-running training gang waits behind a fresh
            # singleton when the concurrency cap forces a choice.
            aged = age_cost([by_uid[u] for u in uids if u in by_uid],
                            self.age_weight, now=now)
            # Cooperative tier: a group that checkpoints on demand
            # loses a bounded restore, not its uptime -- its recovery
            # is admitted ahead of equally-sized cold groups.
            coop = coop_cost_multiplier(
                [by_uid[u] for u in uids if u in by_uid])
            score = (cost + self.disruption_weight * disruption
                     + aged) * coop
            scored.append((score, gid, uids, cost, disruption))
        scored.sort(key=lambda t: (t[0], t[1]))
        faults.fault_point("recovery.plan")
        active = len(records)
        for score, gid, uids, cost, disruption in scored:
            if active + len(uids) > self.max_concurrent and active > 0:
                logger.info(
                    "deferring eviction group %s (%d claims, score "
                    "%.1f): %d eviction(s) already in flight", gid,
                    len(uids), score, active)
                continue
            for uid in uids:
                claim = by_uid.get(uid)
                if claim is None:
                    continue
                self._declare_failure(claim, new[uid])
                self._write_record(
                    claim, EVICTION_PLANNED, source=new[uid],
                    score=score, cost=cost, disruption=disruption)
                active += 1
                counts["planned"] += 1
                if self.metrics is not None:
                    self.metrics.evictions.inc()
                    self.metrics.permanent_failures.labels(
                        new[uid]).inc()
                logger.warning(
                    "eviction planned for claim %s/%s (uid %s, source "
                    "%s, score %.1f: %d device(s) to migrate, %d "
                    "healthy companion(s) disturbed)",
                    _meta(claim).get("namespace", "default"),
                    _meta(claim).get("name"), uid, new[uid], score,
                    cost, disruption)

    def _declare_failure(self, claim: dict, source: str) -> None:
        reason = {"node": "NodeFailed", "device": "DeviceFailed",
                  "gang": "GangCompanionFailed"}.get(source, "Failed")
        self._set_condition(
            claim, "True", reason,
            f"permanent failure declared (source: {source}); claim "
            "queued for eviction and migration")

    def _set_condition(self, claim: dict, status: str, reason: str,
                       message: str) -> None:
        set_permanent_failure_condition(self.kube, claim, status,
                                        reason, message)

    def _write_record(self, claim: dict, state: str, source: str = "",
                      score: float = 0.0, cost: int = 0,
                      disruption: int = 0,
                      prev=None) -> None:
        from ..kubeletplugin.checkpoint import (  # noqa: PLC0415
            CheckpointedClaim,
            CheckpointedDevice,
        )

        uid = _meta(claim).get("uid", "")
        if prev is not None:
            live = dict(prev.devices[0].live or {}) if prev.devices else {}
        else:
            live = {"plannedAt": time.time(), "source": source,
                    "score": score, "cost": cost,
                    "disruption": disruption,
                    "nodes": sorted(allocation_nodes(claim))}
        self._checkpoint.update_claim(uid, CheckpointedClaim(
            uid=uid,
            namespace=_meta(claim).get("namespace", "default"),
            name=_meta(claim).get("name", ""),
            state=state,
            devices=[CheckpointedDevice(
                canonical_name=self._META_DEVICE, kind=self._META_DEVICE,
                live=live)],
        ))
        # One flight-recorder event per durable stage transition: the
        # eviction ladder shows up in /debug/claims/<uid> next to the
        # claim's scheduling and prepare history.
        self.flight.record(
            uid, "eviction",
            alias=(f"{_meta(claim).get('namespace', 'default')}/"
                   f"{_meta(claim).get('name', '')}"),
            state=state, source=live.get("source", ""))

    # -- staged advance -------------------------------------------------------

    @staticmethod
    def _record_meta(rec) -> dict:
        return (rec.devices[0].live or {}) if rec.devices else {}

    def _advance(self, claims: list[dict], failed_nodes: set[str],
                 fatal_devices: set, counts: dict) -> None:
        by_uid = {_meta(c).get("uid", ""): c for c in claims}
        pods = None  # lazily listed, once, only if something drains
        for uid, rec in list(self._checkpoint.get().claims.items()):
            claim = by_uid.get(uid)
            if claim is None or _meta(claim).get("deletionTimestamp"):
                # The claim is gone: whatever stage we were at, the
                # eviction is moot. (A template claim deleted in the
                # drain stage retires here too.)
                self._checkpoint.update_claim(uid, None)
                counts["canceled"] += 1
                continue
            if rec.state == EVICTION_PLANNED:
                if pods is None:
                    pods = self._pods()
                self._drain(uid, rec, claim, pods)
                counts["drained"] += 1
            elif rec.state == EVICTION_DRAINING:
                if self._deallocate(uid, rec, claim):
                    counts["deallocated"] += 1
                else:
                    counts["canceled"] += 1
            elif rec.state == EVICTION_DEALLOCATED:
                self._try_retire(uid, rec, claim, failed_nodes,
                                 fatal_devices, counts)

    def _pods(self) -> list[dict]:
        try:
            if self.view is not None:
                return self.view.pods()
            return self.kube.list("", "v1", "pods")
        except KubeError:
            return []

    def _consumer_pods(self, claim: dict, pods: list[dict]) -> list[dict]:
        return consumer_pods_of(claim, pods)

    def _drain(self, uid: str, rec, claim: dict,
               pods: list[dict]) -> None:
        """Evict BOUND consumer pods (their node is dead, or their gang
        claim is being moved under them) and drop the reservations
        (the shared ``drain_claim`` stage)."""
        faults.fault_point("recovery.drain")
        drain_claim(self.kube, claim, pods)
        self._write_record(claim, EVICTION_DRAINING, prev=rec)

    def _deallocate(self, uid: str, rec, claim: dict) -> bool:
        """Clear the allocation (or GC a template claim whose owner pod
        is gone -- the recreated pod generates a fresh claim); from here
        the incremental scheduler owns re-placement. Returns False when
        the claim was deleted instead of deallocated."""
        faults.fault_point("recovery.dealloc")
        ns = _meta(claim).get("namespace", "default")
        owner_pod = next(
            (o for o in _meta(claim).get("ownerReferences") or []
             if o.get("kind") == "Pod" and o.get("controller")), None)
        if owner_pod is not None and self._pod_gone(
                ns, owner_pod.get("name", "")):
            try:
                self.kube.delete(*RESOURCE, "resourceclaims",
                                 _meta(claim)["name"], namespace=ns)
            except NotFoundError:
                pass
            self._checkpoint.update_claim(uid, None)
            logger.warning(
                "deleted orphaned generated claim %s/%s (uid %s); its "
                "recreated consumer pod generates a fresh claim",
                ns, _meta(claim).get("name"), uid)
            return False
        if not clear_allocation(self.kube, claim):
            return True  # re-examined (and retired) next pass
        self._write_record(claim, EVICTION_DEALLOCATED, prev=rec)
        logger.warning("deallocated failed claim %s/%s (uid %s); "
                       "awaiting re-placement", ns,
                       _meta(claim).get("name"), uid)
        return True

    def _pod_gone(self, ns: str, name: str) -> bool:
        if not name:
            return True
        try:
            self.kube.get("", "v1", "pods", name, namespace=ns)
            return False
        except NotFoundError:
            return True
        except KubeError:
            return False  # unknown: keep the claim, retry next pass

    def _try_retire(self, uid: str, rec, claim: dict,
                    failed_nodes: set[str], fatal_devices: set,
                    counts: dict) -> None:
        alloc = claim.get("status", {}).get("allocation")
        if alloc:
            nodes = allocation_nodes(claim)
            devices = allocation_device_keys(claim)
            if nodes & failed_nodes or devices & fatal_devices:
                # Re-placed straight back onto failed capacity: a
                # scheduler predating the exclusion (or a raced sync).
                # Re-run the eviction from the deallocate stage.
                logger.warning(
                    "claim %s re-placed onto failed capacity; "
                    "re-evicting", uid)
                self._deallocate(uid, rec, claim)
                return
            self._set_condition(
                claim, "False", "Recovered",
                "claim migrated to surviving capacity after a "
                "permanent failure")
            planned_at = float(self._record_meta(rec).get(
                "plannedAt", 0.0))
            self._checkpoint.update_claim(uid, None)
            counts["replaced"] += 1
            if self.metrics is not None:
                self.metrics.replaced.inc()
            if self.slo is not None and planned_at:
                # Eviction e2e: plan -> re-placement, the recovery
                # controller's slice of the claim-SLO histogram.
                self.slo.observe(
                    "evict", max(time.time() - planned_at, 0.0),
                    tracing.trace_id_of(
                        _meta(claim).get("annotations") or {}))
            self.flight.record(uid, "eviction", state="Recovered",
                               nodes=sorted(allocation_nodes(claim)))
            logger.warning("claim %s recovered: re-placed on %s", uid,
                           sorted(allocation_nodes(claim)))
            return
        planned_at = float(self._record_meta(rec).get("plannedAt", 0.0))
        if planned_at and time.time() - planned_at > self.deadline_s:
            self._set_condition(
                claim, "True", "RecoveryDeadlineExceeded",
                f"no surviving capacity re-placed this claim within "
                f"{self.deadline_s:.0f}s; eviction retired cleanly "
                "(the claim remains pending and schedulable)")
            self._checkpoint.update_claim(uid, None)
            counts["failed"] += 1
            if self.metrics is not None:
                self.metrics.failed.inc()
            self.flight.record(uid, "eviction",
                               state="DeadlineExceeded")
            # Eviction failure: dump the claim's whole flight-recorder
            # timeline so the operator sees the ladder (plan -> drain
            # -> deallocate -> the wait that never converged) in one
            # log block instead of reconstructing it by hand.
            logger.error(
                "claim %s failed recovery: deadline exceeded with no "
                "re-placement; flight record:\n%s", uid,
                self.flight.dump(uid))
            # Incident bundle (pkg/doctor, TPU_DRA_DOCTOR_DIR-gated,
            # rate-limited): a blown recovery deadline means capacity
            # or control-plane trouble -- snapshot the rings now.
            from . import doctor  # noqa: PLC0415

            doctor.auto_bundle("eviction-deadline", claim=uid)
