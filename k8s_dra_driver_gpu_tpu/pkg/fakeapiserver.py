"""Fake Kubernetes API server: the REST surface over HTTP.

Reference analog: the mock-NVML CI pipeline proves the reference stack
against real cluster components on CPU-only runners
(.github/workflows/mock-nvml-e2e.yaml, hack/ci/mock-nvml/). Without
container tooling, the nearest executable proof is this process: it
serves the exact REST subset ``KubeClient`` speaks (CRUD, merge-patch,
selectors, streamed ``?watch=true``) over real HTTP, backed by the
in-memory ``FakeKubeClient`` store -- so the REAL driver binaries run
with their REAL ``KubeClient`` against a live server, exercising URL
construction, error mapping, and watch framing that a purely in-process
fake never touches.

Run standalone:
    python -m k8s_dra_driver_gpu_tpu.pkg.fakeapiserver --port 8080
"""

from __future__ import annotations

import argparse
import json
import logging
import queue
import re
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse

from .kubeclient import ConflictError, FakeKubeClient, KubeError, NotFoundError

logger = logging.getLogger(__name__)

# /api/v1/... (core) or /apis/<group>/<version>/...; optional namespace
# segment; then plural; then optional name; then optional subresource.
_PATH_RE = re.compile(
    r"^/(?:api/(?P<core_version>[^/]+)|apis/(?P<group>[^/]+)/(?P<version>[^/]+))"
    r"(?:/namespaces/(?P<namespace>[^/]+))?"
    r"/(?P<resource>[^/]+)"
    r"(?:/(?P<name>[^/]+))?"
    r"(?:/(?P<subresource>[^/]+))?$"
)


def _status_body(code: int, reason: str, message: str) -> bytes:
    return json.dumps({
        "kind": "Status", "apiVersion": "v1", "status": "Failure",
        "message": message, "reason": reason, "code": code,
    }).encode()


class _Handler(BaseHTTPRequestHandler):
    # HTTP/1.0 framing: no Content-Length on watch streams means
    # read-until-close, which is exactly what KubeClient.watch expects.
    protocol_version = "HTTP/1.0"
    server_version = "FakeKubeApiserver/1.0"

    @property
    def store(self) -> FakeKubeClient:
        return self.server.store  # type: ignore[attr-defined]

    def log_message(self, fmt, *args):  # noqa: A003 - quiet by default
        logger.debug("%s %s", self.address_string(), fmt % args)

    # -- plumbing -------------------------------------------------------------

    def _send_json(self, code: int, obj) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_error(self, exc: Exception) -> None:
        if isinstance(exc, NotFoundError):
            code, reason = 404, "NotFound"
        elif isinstance(exc, ConflictError):
            code, reason = 409, "AlreadyExists"
        elif isinstance(exc, KubeError):
            code, reason = exc.status or 500, "InternalError"
        else:
            code, reason = 500, "InternalError"
        body = _status_body(code, reason, str(exc))
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length", "0"))
        raw = self.rfile.read(length) if length else b""
        return json.loads(raw) if raw else {}

    def _route(self):
        """(group, version, namespace, resource, name, subresource,
        query) or None after responding with 404."""
        parsed = urlparse(self.path)
        m = _PATH_RE.match(parsed.path)
        if not m:
            self._send_error(NotFoundError(f"unroutable path {parsed.path}"))
            return None
        d = m.groupdict()
        group = d["group"] or ""
        version = d["core_version"] or d["version"]
        return (group, version, d["namespace"], d["resource"], d["name"],
                d["subresource"], parse_qs(parsed.query))

    # -- verbs ----------------------------------------------------------------

    def do_GET(self):  # noqa: N802
        parsed = urlparse(self.path)
        if parsed.path == "/version":
            self._send_json(200, self.store.server_version())
            return
        if parsed.path in ("/healthz", "/readyz", "/livez"):
            self.send_response(200)
            self.send_header("Content-Length", "2")
            self.end_headers()
            self.wfile.write(b"ok")
            return
        route = self._route()
        if route is None:
            return
        group, version, namespace, resource, name, sub, query = route
        try:
            if sub == "log" and resource == "pods":
                text = self.store.read_raw(parsed.path)
                body = text.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if name is not None:
                self._send_json(200, self.store.get(
                    group, version, resource, name, namespace=namespace))
                return
            if query.get("watch", ["false"])[0] == "true":
                self._serve_watch(group, resource, namespace)
                return
            items = self.store.list(
                group, version, resource, namespace=namespace,
                label_selector=unquote(
                    query.get("labelSelector", [""])[0]) or None,
                field_selector=unquote(
                    query.get("fieldSelector", [""])[0]) or None,
            )
            self._send_json(200, {
                "kind": "List", "apiVersion": "v1",
                "metadata": {"resourceVersion": "1"},
                "items": items,
            })
        except Exception as e:  # noqa: BLE001 - wire boundary
            self._send_error(e)

    def do_POST(self):  # noqa: N802
        route = self._route()
        if route is None:
            return
        group, version, namespace, resource, name, _, _ = route
        try:
            if name is not None:
                raise KubeError(405, "POST with name")
            body = self._read_body()
            self._admit(group, version, resource, namespace, body)
            obj = self.store.create(
                group, version, resource, body, namespace=namespace)
            self._send_json(201, obj)
        except Exception as e:  # noqa: BLE001
            self._send_error(e)

    def _admit(self, group, version, resource, namespace, body,
               operation: str = "CREATE") -> None:
        """Validating-admission leg: POST an AdmissionReview to the
        configured webhook (the ValidatingWebhookConfiguration analog)
        for the resources the chart's webhook registers. Fail policy
        ``Fail``: an unreachable webhook rejects the write, like the
        chart's fail-closed configuration."""
        admission = getattr(self.server, "admission", None)
        if not admission or resource not in (
                "resourceclaims", "resourceclaimtemplates"):
            return
        import urllib.request
        import uuid

        review = {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {
                "uid": uuid.uuid4().hex,
                "operation": operation,
                "resource": {"group": group, "version": version,
                             "resource": resource},
                "namespace": namespace or "default",
                "object": body,
            },
        }
        url, ssl_ctx = admission
        req = urllib.request.Request(
            url, data=json.dumps(review).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        try:
            with urllib.request.urlopen(req, timeout=10,
                                        context=ssl_ctx) as resp:
                out = json.loads(resp.read())
        except OSError as e:
            raise KubeError(
                500, f"admission webhook unreachable (failurePolicy="
                     f"Fail): {e}") from e
        response = out.get("response") or {}
        if not response.get("allowed", False):
            status = response.get("status") or {}
            raise KubeError(
                status.get("code", 400),
                "admission webhook denied the request: "
                + status.get("message", "denied"))

    def do_PUT(self):  # noqa: N802
        route = self._route()
        if route is None:
            return
        group, version, namespace, resource, name, _, _ = route
        try:
            if name is None:
                raise KubeError(405, "PUT without name")
            body = self._read_body()
            # The chart's webhook registers CREATE and UPDATE.
            self._admit(group, version, resource, namespace, body,
                        operation="UPDATE")
            obj = self.store.update(
                group, version, resource, name, body,
                namespace=namespace)
            self._send_json(200, obj)
        except Exception as e:  # noqa: BLE001
            self._send_error(e)

    def do_PATCH(self):  # noqa: N802
        route = self._route()
        if route is None:
            return
        group, version, namespace, resource, name, _, _ = route
        try:
            if name is None:
                raise KubeError(405, "PATCH without name")
            obj = self.store.patch(
                group, version, resource, name, self._read_body(),
                namespace=namespace)
            self._send_json(200, obj)
        except Exception as e:  # noqa: BLE001
            self._send_error(e)

    def do_DELETE(self):  # noqa: N802
        route = self._route()
        if route is None:
            return
        group, version, namespace, resource, name, _, _ = route
        try:
            if name is None:
                raise KubeError(405, "DELETE without name")
            # K8s DELETE of a missing object is a 404; KubeClient.delete
            # swallows it client-side, so surface it faithfully.
            self.store.get(group, version, resource, name,
                           namespace=namespace)
            self.store.delete(group, version, resource, name,
                              namespace=namespace)
            self._send_json(200, {"kind": "Status", "status": "Success"})
        except Exception as e:  # noqa: BLE001
            self._send_error(e)

    # -- watch ----------------------------------------------------------------

    def _serve_watch(self, group: str, resource: str,
                     namespace: str | None) -> None:
        """Stream JSON-lines watch events until the client disconnects.
        Watches start from "now" (no replay), matching an un-versioned
        k8s watch; consumers pair this with list (informer-style)."""
        events: queue.Queue = queue.Queue()

        def on_event(g, r, ns, ev_type, obj):
            if g != group or r != resource:
                return
            if namespace and ns != namespace:
                return
            events.put((ev_type, obj))

        self.store.add_resource_watcher(on_event)
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            # No Content-Length: HTTP/1.0 read-until-close streaming.
            self.end_headers()
            self.wfile.flush()
            while True:
                try:
                    ev_type, obj = events.get(timeout=5.0)
                    line = json.dumps(
                        {"type": ev_type, "object": obj}) + "\n"
                    self.wfile.write(line.encode())
                except queue.Empty:
                    # Bookmark keep-alive: proves liveness and flushes
                    # through proxies; KubeClient skips BOOKMARKs.
                    self.wfile.write((json.dumps({
                        "type": "BOOKMARK",
                        "object": {"metadata": {"resourceVersion": "1"}},
                    }) + "\n").encode())
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # client hung up: normal watch teardown
        finally:
            self.store.remove_resource_watcher(on_event)


class FakeApiServer:
    """The fake apiserver as an embeddable object (tests) or CLI."""

    def __init__(self, store: FakeKubeClient | None = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.store = store or FakeKubeClient()
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.store = self.store  # type: ignore[attr-defined]
        self._httpd.admission = None  # type: ignore[attr-defined]
        self._httpd.daemon_threads = True
        self._thread: threading.Thread | None = None

    def set_admission_webhook(self, url: str, ca_cert: str | None = None):
        """Register a validating webhook for resource claims/templates
        (ValidatingWebhookConfiguration analog). ``ca_cert`` verifies
        the webhook's serving cert (the chart's caBundle)."""
        import ssl as _ssl

        ctx = None
        if url.startswith("https"):
            ctx = _ssl.create_default_context()
            if ca_cert:
                ctx.load_verify_locations(ca_cert)
            ctx.check_hostname = False
        self._httpd.admission = (url, ctx)  # type: ignore[attr-defined]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self._httpd.server_address[0]}:{self.port}"

    def start(self) -> "FakeApiServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fake-apiserver",
            daemon=True)
        self._thread.start()
        logger.info("fake apiserver on %s", self.url)
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="tpu-dra-fake-apiserver")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8001)
    p.add_argument("--seed", default="",
                   help="JSON file: [{group,version,resource,namespace?,"
                        "object}, ...] created at startup")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    server = FakeApiServer(host=args.host, port=args.port)
    if args.seed:
        with open(args.seed, encoding="utf-8") as f:
            for entry in json.load(f):
                server.store.create(
                    entry["group"], entry["version"], entry["resource"],
                    entry["object"], namespace=entry.get("namespace"))
    server.start()
    print(f"listening on {server.url}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
