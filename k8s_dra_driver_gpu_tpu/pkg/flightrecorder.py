"""Per-claim flight recorder: a bounded ring of lifecycle events.

Traces (pkg/tracing.py) answer "how long did each hop take"; the
flight recorder answers "what happened to THIS claim, in order" --
dirty-key enqueues, fit outcomes, try_commit conflicts, allocation
patches, prepare segment breakdowns, partition attaches, eviction
stages. It is always on (no sampling: the ring is fixed-size and an
event is one small dict append under a lock), so when a gang-prepare
aborts or an eviction blows its deadline the operator gets the whole
timeline dumped into the log instead of doing archaeology across four
binaries' log streams.

Keys: producers record under the claim UID when they have it (node
plugins, partition engine, recovery) and under ``namespace/name``
before the UID is known (the scheduler's dirty-key enqueue); an
``alias`` ties the two, and queries match either -- so
``/debug/claims/<uid>`` and ``/debug/claims/<ns>/<name>`` both return
the full story. Domain-shaped producers (the CD controller) use the
domain UID the same way.

Construct events only through :meth:`FlightRecorder.record` (lint rule
TPUDRA012 fences bare ``FlightEvent(`` construction like bare spans).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from dataclasses import dataclass, field

DEFAULT_CAPACITY = 4096


@dataclass(frozen=True)
class FlightEvent:
    """One structured lifecycle event (create via
    FlightRecorder.record; TPUDRA012 fences bare construction)."""

    ts: float
    key: str
    event: str
    alias: str = ""
    trace_id: str = ""
    fields: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        out = {"ts": self.ts, "key": self.key, "event": self.event}
        if self.alias:
            out["alias"] = self.alias
        if self.trace_id:
            out["trace_id"] = self.trace_id
        if self.fields:
            out.update(self.fields)
        return out


class FlightRecorder:
    """Fixed-size ring of FlightEvents with a per-key view."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._ring: deque[FlightEvent] = deque(
            maxlen=max(16, int(capacity)))
        self.recorded_total = 0

    def record(self, key: str, event: str, *, alias: str = "",
               trace_id: str = "", **fields) -> None:
        """Append one event. ``key`` is the claim UID (or ns/name when
        the UID is not known yet); ``alias`` the other identity when
        both are known; extra keyword fields become event payload."""
        if not key:
            return
        ev = FlightEvent(ts=time.time(), key=str(key), event=str(event),
                         alias=str(alias or ""),
                         trace_id=str(trace_id or ""), fields=fields)
        with self._lock:
            self._ring.append(ev)
            self.recorded_total += 1

    def events(self, key: str = "") -> list[dict]:
        """Events for one key, oldest first; everything when ``key`` is
        empty. Matching is identity-closed over aliases: a UID query
        also returns events recorded under the claim's ``ns/name``
        BEFORE the UID was known (the scheduler's enqueue), because a
        later event carrying both identities ties them together."""
        with self._lock:
            ring = list(self._ring)
        if not key:
            return [ev.to_dict() for ev in ring]
        ids = {key}
        # Two passes reach a fixpoint for the two-identity (uid <->
        # ns/name) chains producers record; aliased events seen in
        # pass one pull their other identity's alias-less events in
        # pass two.
        for _ in range(2):
            for ev in ring:
                if ev.key in ids or (ev.alias and ev.alias in ids):
                    ids.add(ev.key)
                    if ev.alias:
                        ids.add(ev.alias)
        return [ev.to_dict() for ev in ring
                if ev.key in ids or (ev.alias and ev.alias in ids)]

    def keys(self) -> list[str]:
        with self._lock:
            return sorted({ev.key for ev in self._ring})

    def dump(self, key: str) -> str:
        """Human-readable timeline for one claim -- what gang-abort /
        eviction-failure handlers put in the log."""
        events = self.events(key)
        if not events:
            return f"(no flight-recorder events for {key!r})"
        lines = []
        for ev in events:
            extra = {k: v for k, v in ev.items()
                     if k not in ("ts", "key", "event")}
            detail = " ".join(f"{k}={v}" for k, v in sorted(extra.items()))
            lines.append(f"  {ev['ts']:.3f} {ev['event']:<20} {detail}")
        return "\n".join(lines)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # -- /debug/claims endpoints (pkg/httpserver handler signatures) ----------

    def claims_endpoint(self, rest: str) -> tuple[int, str, bytes]:
        """GET /debug/claims/<uid-or-ns/name>."""
        key = rest.strip("/")
        if not key:
            body = json.dumps({"claims": self.keys()}).encode()
            return 200, "application/json", body
        events = self.events(key)
        if not events:
            return (404, "application/json",
                    b'{"error": "no events for key"}')
        body = json.dumps({"key": key, "events": events},
                          sort_keys=True).encode()
        return 200, "application/json", body

    def index_endpoint(self) -> tuple[int, str, bytes]:
        """GET /debug/claims -- the keys currently in the ring."""
        body = json.dumps({"claims": self.keys()}).encode()
        return 200, "application/json", body


_default: FlightRecorder | None = None
_default_lock = threading.Lock()


def default() -> FlightRecorder:
    """The process-wide recorder (served at /debug/claims)."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = FlightRecorder()
    return _default


def set_default(recorder: FlightRecorder) -> FlightRecorder:
    """Swap the process recorder (tests)."""
    global _default
    with _default_lock:
        _default = recorder
    return recorder
