"""Polling flock(2) wrapper with timeout and cancellation.

Reference: pkg/flock/flock.go (release-on-fd-close crash safety; used for
the node-global prepare/unprepare mutex and the checkpoint
read-modify-write lock, for multi-process safety across plugin upgrades).

Design notes (TPU build): same semantics -- a named lock file, acquired
with LOCK_EX | LOCK_NB in a poll loop so acquisition honors a timeout and
an optional cancel event. The lock is released either explicitly or by the
kernel when the fd closes (process crash safety).
"""

from __future__ import annotations

import fcntl
import os
import threading
import time

from . import faults


class FlockTimeoutError(TimeoutError):
    """Raised when the lock cannot be acquired within the timeout."""


class FlockReentrantError(RuntimeError):
    """The holding thread tried to re-acquire its own non-reentrant lock.

    Without this check a re-entrant acquire would spin against the
    holder's own thread lock until the timeout -- a silent 10s stall
    that reads like cross-process contention. Failing fast names the
    actual bug (a lock-ordering error in the caller)."""


class Flock:
    """A file-based advisory lock.

    Usage:
        lock = Flock("/var/run/tpu-dra/pu.lock")
        with lock.acquire(timeout=10.0):
            ...
    """

    def __init__(self, path: str):
        self._path = path
        self._fd: int | None = None
        # Serializes acquire/release within this process; flock(2) itself
        # only excludes other processes' fds.
        self._thread_lock = threading.Lock()
        # Held-state tracking: ident of the owning thread while held.
        # Only the owner ever matches its own ident, so the unlocked
        # read in acquire() is race-free for the re-entrancy check.
        self._owner: int | None = None

    @property
    def path(self) -> str:
        return self._path

    def acquire(
        self,
        timeout: float = 10.0,
        poll_interval: float = 0.01,
        cancel: threading.Event | None = None,
    ) -> "_FlockGuard":
        """Acquire the lock, polling until ``timeout`` seconds elapse.

        Raises FlockTimeoutError on timeout, FlockReentrantError when
        the calling thread already holds this lock, and InterruptedError
        if ``cancel`` is set while waiting.
        """
        if self._owner == threading.get_ident():
            raise FlockReentrantError(
                f"thread {self._owner} already holds {self._path}; "
                "Flock is not re-entrant"
            )
        # Fault seam: latency here simulates cross-process lock
        # contention; error simulates a wedged holder (the caller sees
        # the same FlockTimeoutError a real 10s stall produces).
        faults.fault_point(
            "flock.acquire",
            error=lambda m: FlockTimeoutError(f"{m} ({self._path})"))
        deadline = time.monotonic() + timeout
        # Honor timeout/cancel for intra-process contention from OTHER
        # threads (the thread lock is non-reentrant; the holding thread
        # itself was rejected above).
        # The lock IMPLEMENTATION itself: the guard object (not a
        # finally) owns the release, and every failure path below
        # releases explicitly. tpudra: allow=TPUDRA002
        while not self._thread_lock.acquire(timeout=poll_interval):
            if cancel is not None and cancel.is_set():
                raise InterruptedError(
                    f"lock acquisition on {self._path} canceled"
                )
            if time.monotonic() >= deadline:
                raise FlockTimeoutError(
                    f"timed out after {timeout}s acquiring {self._path}"
                )
        try:
            os.makedirs(os.path.dirname(self._path) or ".", exist_ok=True)
            fd = os.open(self._path, os.O_CREAT | os.O_RDWR, 0o644)
        except BaseException:
            self._thread_lock.release()
            raise
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                self._fd = fd
                self._owner = threading.get_ident()
                return _FlockGuard(self)
            except BlockingIOError:
                if cancel is not None and cancel.is_set():
                    os.close(fd)
                    self._thread_lock.release()
                    raise InterruptedError(
                        f"lock acquisition on {self._path} canceled"
                    ) from None
                if time.monotonic() >= deadline:
                    os.close(fd)
                    self._thread_lock.release()
                    raise FlockTimeoutError(
                        f"timed out after {timeout}s acquiring {self._path}"
                    ) from None
                time.sleep(poll_interval)
            except BaseException:
                os.close(fd)
                self._thread_lock.release()
                raise

    def release(self) -> None:
        if self._fd is None:
            return
        try:
            fcntl.flock(self._fd, fcntl.LOCK_UN)
        finally:
            os.close(self._fd)
            self._fd = None
            self._owner = None
            self._thread_lock.release()

    @property
    def held(self) -> bool:
        return self._fd is not None


class _FlockGuard:
    def __init__(self, lock: Flock):
        self._lock = lock

    def __enter__(self) -> Flock:
        return self._lock

    def __exit__(self, *exc) -> None:
        self._lock.release()
