"""Retrying kube client: jittered backoff, deadlines, circuit breaker.

Reference analog: client-go's rest client retries (retryAfter on 429/5xx)
plus the reference driver's workqueue rate limiters (pkg/workqueue).
This wrapper is the SINGLE sanctioned path to the API server for every
long-running component (kubelet plugins, CD controller/daemon,
scheduler, webhook bootstrap) -- lint rule TPUDRA008 flags raw
``KubeClient`` construction outside it.

Semantics:

- **Per-call deadline.** Every verb gets ``policy.deadline_s`` of total
  budget; each attempt carries an explicit per-attempt server timeout
  (``policy.attempt_timeout_s``) so one dead TCP peer can't eat the
  whole budget.
- **Retriable classification.** 429 + 5xx statuses, connection resets /
  refusals / timeouts (``OSError`` family incl. ``URLError``), and
  injected faults retry with jittered exponential backoff. 404 is a
  result, not a failure. 409 Conflict is classified ``conflict``: it is
  surfaced immediately, because replaying the SAME stale write can
  never succeed -- the caller owns the fetch-modify-update loop (every
  conflict-aware call site in this repo already has one). Set
  ``policy.retry_conflicts=True`` for blind-retry semantics where a
  caller really wants them.
- **Circuit breaker.** ``breaker_threshold`` consecutive failures open
  the circuit for ``breaker_reset_s``: calls fail fast with
  ``CircuitOpenError`` (itself a retriable 503 for outer loops) instead
  of piling timed-out sockets onto a down apiserver. One half-open
  probe closes it again.

Counters (`tpu_dra_retry_total` by verb, `tpu_dra_circuit_open_total`)
export through ``pkg.metrics.ResilienceMetrics`` when one is wired;
integer counters on the wrapper itself are always maintained for tests
and the chaos bench.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from dataclasses import dataclass, field

from . import faults
from .kubeclient import ConflictError, KubeError, NotFoundError

logger = logging.getLogger(__name__)

RETRIABLE_STATUSES = frozenset({429, 500, 502, 503, 504})


@dataclass(frozen=True)
class RetryPolicy:
    """Backoff/deadline knobs (env-tunable, see ``from_env``)."""

    base_delay: float = 0.1
    max_delay: float = 2.0
    jitter: float = 0.2  # fraction of the delay added uniformly at random
    deadline_s: float = 30.0  # total per-call budget
    attempt_timeout_s: float = 10.0  # per-attempt server timeout
    retry_conflicts: bool = False  # 409: caller-owned refetch by default

    @classmethod
    def from_env(cls, env=os.environ) -> "RetryPolicy":
        def f(name: str, default: float) -> float:
            try:
                return float(env.get(name, default))
            except ValueError:
                return default

        return cls(
            base_delay=f("TPU_DRA_KUBE_RETRY_BASE_S", cls.base_delay),
            max_delay=f("TPU_DRA_KUBE_RETRY_MAX_S", cls.max_delay),
            jitter=f("TPU_DRA_KUBE_RETRY_JITTER", cls.jitter),
            deadline_s=f("TPU_DRA_KUBE_DEADLINE_S", cls.deadline_s),
            attempt_timeout_s=f("TPU_DRA_KUBE_ATTEMPT_TIMEOUT_S",
                                cls.attempt_timeout_s),
        )

    def delay_for(self, attempt: int, rng: random.Random) -> float:
        exp = min(max(attempt - 1, 0), 32)
        d = min(self.base_delay * (2 ** exp), self.max_delay)
        if self.jitter:
            d += d * self.jitter * rng.random()
        return d


def classify(exc: BaseException, policy: RetryPolicy) -> str:
    """``retriable`` | ``conflict`` | ``permanent``."""
    if isinstance(exc, faults.InjectedCrash):
        return "permanent"  # simulated process death, never absorbed
    if isinstance(exc, NotFoundError):
        return "permanent"
    if isinstance(exc, ConflictError):
        return "retriable" if policy.retry_conflicts else "conflict"
    if isinstance(exc, KubeError):
        return ("retriable" if exc.status in RETRIABLE_STATUSES
                else "permanent")
    if isinstance(exc, faults.InjectedFault):
        return "retriable"
    # URLError / ConnectionResetError / socket timeouts are OSError
    # subclasses; TimeoutError covers socket.timeout on 3.10+.
    if isinstance(exc, (OSError, TimeoutError)):
        return "retriable"
    return "permanent"


class CircuitOpenError(KubeError):
    """Fail-fast while the breaker is open. A 503 so outer retry loops
    (kubelet, workqueues) treat it as the transient condition it is."""

    def __init__(self, message: str = "kube circuit breaker open"):
        super().__init__(503, message)


class CircuitBreaker:
    """Consecutive-failure breaker with a half-open probe."""

    def __init__(self, threshold: int = 5, reset_s: float = 15.0,
                 clock=time.monotonic):
        self.threshold = threshold
        self.reset_s = reset_s
        self._clock = clock
        self._lock = threading.Lock()
        self._failures = 0
        self._opened_at: float | None = None
        self._probing = False
        self.trips = 0  # lifetime open transitions

    @property
    def open(self) -> bool:
        with self._lock:
            return self._opened_at is not None

    def allow(self) -> bool:
        """True when a call may proceed (closed, or the one half-open
        probe after the reset window)."""
        with self._lock:
            if self._opened_at is None:
                return True
            if self._clock() - self._opened_at < self.reset_s:
                return False
            if self._probing:
                return False  # someone else holds the probe slot
            self._probing = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._opened_at = None
            self._probing = False

    def record_failure(self) -> bool:
        """Returns True when THIS failure tripped the breaker open."""
        with self._lock:
            self._failures += 1
            if self._probing:
                # Failed half-open probe: re-open the window.
                self._opened_at = self._clock()
                self._probing = False
                return False
            if self._opened_at is None and self._failures >= self.threshold:
                self._opened_at = self._clock()
                self.trips += 1
                return True
            return False


class RetryingKubeClient:
    """Wraps any object with the KubeClient surface (real or fake).

    Non-verb attributes (watch, add_watcher, objects, ...) delegate to
    the inner client untouched -- the watch has its own
    reconnect/resume machinery in KubeClient.watch.
    """

    def __init__(self, kube, policy: RetryPolicy | None = None,
                 breaker: CircuitBreaker | None = None,
                 metrics=None, seed: int | None = None,
                 sleep=time.sleep, clock=time.monotonic):
        self.kube = kube
        self.policy = policy or RetryPolicy.from_env()
        self.breaker = breaker or CircuitBreaker()
        self.metrics = metrics  # pkg.metrics.ResilienceMetrics | None
        self._rng = random.Random(seed)
        self._sleep = sleep
        self._clock = clock
        # Always-on integer counters (tests / chaos bench).
        self.retry_count = 0
        self.retries_by_verb: dict[str, int] = {}

    def __getattr__(self, name):
        # Only reached for names not defined on the wrapper: delegate
        # watch/add_watcher/objects/... to the inner client.
        return getattr(self.kube, name)

    # -- wrapped verbs --------------------------------------------------------

    def get(self, *a, **kw):
        return self._call("get", a, kw)

    def list(self, *a, **kw):
        return self._call("list", a, kw)

    def create(self, *a, **kw):
        return self._call("create", a, kw)

    def update(self, *a, **kw):
        return self._call("update", a, kw)

    def patch(self, *a, **kw):
        return self._call("patch", a, kw)

    def delete(self, *a, **kw):
        return self._call("delete", a, kw)

    def server_version(self, *a, **kw):
        return self._call("server_version", a, kw)

    def read_raw(self, *a, **kw):
        return self._call("read_raw", a, kw)

    # -- engine ---------------------------------------------------------------

    def _record_retry(self, verb: str) -> None:
        self.retry_count += 1
        self.retries_by_verb[verb] = self.retries_by_verb.get(verb, 0) + 1
        if self.metrics is not None:
            self.metrics.retries.labels(verb).inc()

    def _call(self, verb: str, args: tuple, kwargs: dict):
        fn = getattr(self.kube, verb)
        kwargs = dict(kwargs)
        kwargs.setdefault("timeout", self.policy.attempt_timeout_s)
        deadline = self._clock() + self.policy.deadline_s
        attempt = 0
        while True:
            if not self.breaker.allow():
                raise CircuitOpenError(
                    f"circuit open; refusing kube {verb} for up to "
                    f"{self.breaker.reset_s}s")
            attempt += 1
            try:
                # THE kube fault point: one seam for every client type
                # (real or fake), firing once per attempt so retry
                # schedules see independent trials.
                faults.fault_point(
                    "kube.request",
                    error=lambda m: KubeError(503, m))
                result = fn(*args, **kwargs)
            except BaseException as e:
                kind = classify(e, self.policy)
                if kind != "retriable":
                    # A 404/409/422-class outcome means the apiserver
                    # ANSWERED: close the circuit (this also releases a
                    # half-open probe slot) before surfacing the result.
                    # Any OTHER permanent exception (malformed response
                    # body, InjectedCrash, a client bug) must still
                    # release the probe slot or the breaker wedges open
                    # forever -- it counts as a failure, not a success.
                    if isinstance(e, KubeError):
                        self.breaker.record_success()
                    elif self.breaker.record_failure():
                        logger.warning(
                            "kube circuit breaker OPEN after %d "
                            "consecutive failures (last: %s)",
                            self.breaker.threshold, e)
                        if self.metrics is not None:
                            self.metrics.circuit_open.inc()
                    raise
                tripped = self.breaker.record_failure()
                if tripped:
                    logger.warning(
                        "kube circuit breaker OPEN after %d consecutive "
                        "failures (last: %s)", self.breaker.threshold, e)
                    if self.metrics is not None:
                        self.metrics.circuit_open.inc()
                delay = self.policy.delay_for(attempt, self._rng)
                if self._clock() + delay >= deadline:
                    logger.warning(
                        "kube %s: retry budget (%.1fs) exhausted after "
                        "%d attempt(s): %s",
                        verb, self.policy.deadline_s, attempt, e)
                    raise
                self._record_retry(verb)
                logger.info("kube %s failed (attempt %d), retrying in "
                            "%.2fs: %s", verb, attempt, delay, e)
                self._sleep(delay)
            else:
                self.breaker.record_success()
                return result
