"""Demand forecasting over the fleet time-series rings.

The telemetry plane (pkg/fleetstate) already records per-pool
partition-slot occupancy and pending-claim history every scheduler
pass; this module turns those rings into a *latency* optimization:
project near-term partition demand per pool so the autoscale
controller can pre-realize carve-outs BEFORE the burst's first
attaches arrive -- a warm partition's attach skips the
``partition.create`` fsyncs on the claim-e2e critical path
(pkg/partition/engine.set_prewarm is the node-side consumer).

Deliberately boring math, matched to what the rings can support:

- **Trend**: a least-squares slope over the recent
  ``partition_slots_used`` points, projected ``horizon_s`` ahead. Only
  a RISING trend forecasts anything -- flat or decaying pools predict
  zero (pre-warming is purely additive; the idle sweep owns decay).
- **Freshness**: points older than ``window_s`` are ignored and a ring
  whose newest point is older than ``stale_s`` forecasts zero -- a
  burst that came and went ages out instead of warming a dead pool
  forever.
- **Starvation boost**: claims pending RIGHT NOW (the
  ``pending_history`` ring, same sustained-max read the autoscaler's
  urgency check uses) are immediate demand on top of the trend.

The forecaster is pure and stateless: rings in, ``{pool: additional
slots}`` out. The controller owns everything stateful (the CRD hint
annotation, convergence, bounds).
"""

from __future__ import annotations

import math
import time

from .. import positive_float_env

#: How far ahead the trend is projected (seconds).
FORECAST_HORIZON_S = positive_float_env(
    "TPU_DRA_FORECAST_HORIZON_S", default=120.0, floor=1.0)
#: Ring points older than this never enter the regression.
FORECAST_WINDOW_S = positive_float_env(
    "TPU_DRA_FORECAST_WINDOW_S", default=600.0, floor=5.0)
#: A pool whose newest point is older than this forecasts zero.
FORECAST_STALE_S = positive_float_env(
    "TPU_DRA_FORECAST_STALE_S", default=180.0, floor=1.0)
#: Minimum ring points before the trend is trusted.
FORECAST_MIN_POINTS = int(positive_float_env(
    "TPU_DRA_FORECAST_MIN_POINTS", default=4, floor=2))


class DemandForecaster:
    """Projects per-pool partition-slot demand from the
    FleetAggregator's rings (see module docstring)."""

    def __init__(self, horizon_s: float = 0.0, window_s: float = 0.0,
                 stale_s: float = 0.0, min_points: int = 0):
        self.horizon_s = horizon_s or FORECAST_HORIZON_S
        self.window_s = window_s or FORECAST_WINDOW_S
        self.stale_s = stale_s or FORECAST_STALE_S
        self.min_points = min_points or FORECAST_MIN_POINTS

    # -- one pool -------------------------------------------------------------

    def forecast_slots(self, history: list[dict],
                       now: float | None = None) -> int:
        """Projected ADDITIONAL slot demand for one pool ring at
        ``now + horizon_s`` (0 unless a fresh, sustained ramp is in
        flight)."""
        now = time.time() if now is None else now
        pts = [(float(p["ts"]), int(p["partition_slots_used"]))
               for p in history or []
               if p.get("partition_slots_used") is not None
               and p.get("ts") is not None]
        recent = [(t, v) for t, v in pts if now - t <= self.window_s]
        if len(recent) < self.min_points:
            return 0
        last_t, last_v = recent[-1]
        if now - last_t > self.stale_s:
            # The ring stopped moving: whatever ramp was in flight has
            # decayed out of relevance (the aged-out-burst contract).
            return 0
        if last_v <= recent[-2][1]:
            # The ramp must still be RISING at the newest sample: a
            # step that already landed and plateaued is served
            # capacity, not in-flight demand -- without this, the
            # regression keeps projecting a just-finished burst's
            # slope forward and the hint churns writes in steady
            # state.
            return 0
        slope = self._slope(recent)
        if slope <= 0:
            return 0
        # The projection minus the current level IS the trend term.
        return max(int(math.ceil(slope * self.horizon_s)), 0)

    @staticmethod
    def _slope(points: list[tuple[float, int]]) -> float:
        """Least-squares slope (slots per second) of (ts, used)."""
        n = len(points)
        mean_t = sum(t for t, _ in points) / n
        mean_v = sum(v for _, v in points) / n
        denom = sum((t - mean_t) ** 2 for t, _ in points)
        if denom <= 0:
            return 0.0
        return sum((t - mean_t) * (v - mean_v)
                   for t, v in points) / denom

    # -- the whole fleet ------------------------------------------------------

    def forecast(self, fleet_snapshot: dict,
                 now: float | None = None) -> dict[str, int]:
        """``{pool label: additional slots}`` over every pool in a
        FleetAggregator snapshot; pools forecasting zero are omitted.
        The sustained pending-claim count (fleet-GLOBAL -- the ring
        cannot attribute a pending claim to a pool) boosts only pools
        whose OWN ring already shows a rising trend: demand at the
        door amplifies an in-flight ramp, but must not fan out across
        every flat pool in the fleet (N pools x pending carve-outs of
        phantom warm capacity). Starvation with no ramp anywhere is
        the autoscale planner's urgent-re-plan territory, not a
        pre-warm signal."""
        now = time.time() if now is None else now
        pending = 0
        tail = (fleet_snapshot.get("pending_history") or [])[-5:]
        if tail:
            pending = max(int(p.get("pending", 0)) for p in tail)
            if now - float(tail[-1].get("ts", 0)) > self.stale_s:
                pending = 0
        out: dict[str, int] = {}
        for label, entry in (fleet_snapshot.get("pools")
                             or {}).items():
            history = entry.get("history") or []
            current = entry.get("current") or {}
            if current.get("partition_slots_total") in (None, 0):
                continue  # pool publishes no partition slots
            add = self.forecast_slots(history, now=now)
            if add > 0:
                out[label] = add + pending
        return out
