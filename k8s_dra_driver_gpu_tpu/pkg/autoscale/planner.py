"""Demand -> desired-PartitionSet planning (MISO sizing + ParvaGPU
packing) with a hysteresis band.

The planner is pure: observed demand percentiles in
(:class:`~..partition.profiles.TenantProfileStore`), desired
:class:`~..partition.spec.PartitionSet` out. The controller owns
everything stateful (sustain clocks, durable rollout records, the
apiserver).

Sizing (MISO 2207.11428): per tenant key, the smallest slot count
whose per-tenant budget covers the demand percentile -- evaluated
against a catalog of one-chip-backed profiles at the configured slot
counts, with per-slot budgets derived from the SAME chip capacities
the nodes publish as KEP-4815 shared counters
(:func:`pool_chip_caps`), so the plan can never promise a budget the
counter model will refuse.

Hysteresis: a tenant whose active profile still covers its demand is
only REPACKED to a finer profile when the demand sits clearly below
the finer budget (``band`` fraction of headroom) -- demand oscillating
around a slot boundary must not flap the fleet between layouts.
Upsizes (demand above the active budget) always fire: an
under-provisioned serving tenant is an SLO breach, not a style
preference.

Priority (per-profile CEL, :class:`~.crd.PriorityRule`): a tenant
matching a rule with priority > 0 is latency-critical and is sized
against maxTenants == 1 profiles only -- packed away from
oversubscribed devices (the ParvaGPU interference-avoidance move).

Profile names are VERSIONED by shape (``<tenant>-s<slots>``): a
re-size retires the old NAME and introduces a new one instead of
re-shaping a live profile, which is what makes rollouts live-tenant
safe -- the node engine refuses to re-shape held carve-outs, new
tenants land on the new profile, and the retired name drains through
``prune_retired_partitions`` once its last tenant detaches.
"""

from __future__ import annotations

import logging
import re
from dataclasses import dataclass, field

from ..cel import Quantity
from ..partition.profiles import SizingPolicy
from ..partition.spec import PartitionProfile, PartitionSet
from .crd import PriorityRule

logger = logging.getLogger(__name__)

#: Claim annotations declaring a tenant's demand to the scheduler-side
#: store (the apiserver-visible twin of the node-local tpulib
#: telemetry feed): the controller folds these every pass, so live
#: claims keep their demand fresh in the sliding window and retired
#: claims age out.
TENANT_DEMAND_HBM_ANNOTATION = "resource.tpu.dra/tenant-demand-hbm"
TENANT_DEMAND_CORES_ANNOTATION = "resource.tpu.dra/tenant-demand-cores"

_NAME_SANITIZE_RE = re.compile(r"[^a-z0-9-]+")


def profile_name_for(tenant: str, slots: int) -> str:
    """Shape-versioned profile name (see module docstring)."""
    san = _NAME_SANITIZE_RE.sub("-", tenant.lower()).strip("-") or "t"
    return f"{san}-s{slots}"


_PROFILE_NAME_RE = re.compile(r"^(.*)-s(\d+)$")


def tenant_of_profile(name: str) -> tuple[str, int] | None:
    m = _PROFILE_NAME_RE.match(name)
    if not m:
        return None
    return m.group(1), int(m.group(2))


def pool_chip_caps(slices: list[dict]) -> tuple[int, int]:
    """(hbm_bytes_per_chip, cores_per_chip) from published
    ResourceSlice shared counters -- the fleet's largest chip class
    (uniform-fleet assumption; heterogeneous pools get the
    conservative treatment of being sized against the largest chip
    and validated per-node by the engine's counter model)."""
    hbm = 0
    cores_by_chip: dict[str, set[str]] = {}
    cores = 0
    for s in slices:
        for cs in s.get("spec", {}).get("sharedCounters") or []:
            cores_by_chip.clear()
            for cname, val in (cs.get("counters") or {}).items():
                if cname.startswith("hbm-"):
                    try:
                        hbm = max(hbm, Quantity.parse(
                            str(val.get("value", "0"))).milli // 1000)
                    except ValueError:
                        continue
                elif cname.startswith("core-"):
                    parts = cname.split("-")
                    if len(parts) >= 3:
                        cores_by_chip.setdefault(
                            parts[1], set()).add(parts[2])
            if cores_by_chip:
                cores = max(cores, max(
                    len(v) for v in cores_by_chip.values()))
    return hbm, max(cores, 1)


@dataclass(frozen=True)
class CatalogEntry:
    """A sizing-catalog entry: duck-typed for SizingPolicy.pick (the
    same ``tenant_hbm_bytes`` / ``tenant_core_milli`` / ``cores``
    surface PartitionInfo publishes), with budgets derived from the
    published chip counters instead of a node-local host handle."""

    profile: PartitionProfile
    cores: int
    tenant_hbm_bytes: int
    tenant_core_milli: int


@dataclass
class PlanResult:
    """One planning pass: the desired PartitionSet, whether it differs
    from the active one, and whether the difference is urgent (an
    upsize / new tenant -- fire now) or cosmetic repacking (wait out
    the sustain window)."""

    desired: PartitionSet
    changed: bool = False
    urgent: bool = False
    #: tenant -> {"slots", "budget", "demand", "action", "priority"}
    decisions: dict = field(default_factory=dict)


class AutoscalePlanner:
    def __init__(self, percentile: float = 0.95, band: float = 0.1,
                 slot_counts: tuple[int, ...] = (1, 2, 4, 8),
                 subslice: str = "1x1"):
        self.percentile = percentile
        self.band = max(0.0, min(float(band), 0.9))
        self.slot_counts = tuple(sorted(set(
            int(s) for s in slot_counts if int(s) >= 1)))
        self.subslice = subslice
        self._policy = SizingPolicy(percentile)

    # -- catalog --------------------------------------------------------------

    def _catalog(self, tenant: str, chip_hbm: int, cores_per_chip: int,
                 slot_counts: tuple[int, ...]
                 ) -> list[tuple[PartitionProfile, CatalogEntry]]:
        out = []
        for slots in slot_counts:
            prof = PartitionProfile(
                name=profile_name_for(tenant, slots),
                subslice=self.subslice, max_tenants=slots)
            entry = CatalogEntry(
                profile=prof, cores=cores_per_chip,
                tenant_hbm_bytes=chip_hbm // slots,
                tenant_core_milli=1000 * cores_per_chip // slots)
            out.append((prof, entry))
        return out

    @staticmethod
    def _priority_of(tenant: str, hbm: int, cores: int,
                     rules: tuple[PriorityRule, ...]) -> int:
        return max((r.priority for r in rules
                    if r.matches(tenant, hbm, cores)), default=0)

    # -- the plan -------------------------------------------------------------

    def plan(self, store, active: PartitionSet,
             rules: tuple[PriorityRule, ...] = (),
             chip_hbm: int = 0, cores_per_chip: int = 1,
             live_tenants: set[str] | None = None,
             pending_tenants: set[str] | None = None,
             pools: tuple[str, ...] = (),
             now: float | None = None,
             coop_tenants: set[str] | None = None) -> PlanResult:
        """Size every fresh tenant key against the catalog and diff
        the result against ``active``.

        ``live_tenants``: tenant keys with live claims -- their
        profiles are retained even when every sample aged out of the
        window (never yank a serving tenant's profile under it).
        ``pending_tenants``: tenant keys with PENDING claims -- a
        missing/undersized profile for one of these is urgent.
        ``coop_tenants``: tenant keys whose every live claim declares
        the cooperative migration contract (pkg/migration) -- their
        repack-down hysteresis band shrinks by the cooperative cost
        factor, because resizing them costs a bounded
        checkpoint-restore instead of a cold restart."""
        live_tenants = live_tenants or set()
        pending_tenants = pending_tenants or set()
        coop_tenants = coop_tenants or set()
        active_by_name = {p.name: p for p in active.profiles}
        fresh = set(store.fresh_tenants(now=now)) | set(live_tenants)
        decisions: dict = {}
        profiles: dict[str, PartitionProfile] = {}
        urgent = False

        if chip_hbm <= 0:
            # No published counters to budget against (empty fleet):
            # nothing can be sized -- keep the active layout verbatim.
            return PlanResult(desired=active)

        for tenant in sorted(fresh):
            demand = store.demand(tenant, self.percentile, now=now)
            if demand is None:
                # Live claims but zero observed samples ever: keep any
                # active profiles for this tenant untouched (below).
                self._retain_active(tenant, active_by_name, profiles)
                continue
            prio = self._priority_of(tenant, demand.hbm_bytes,
                                     demand.cores, rules)
            slot_counts = (1,) if prio > 0 else self.slot_counts
            catalog = self._catalog(tenant, chip_hbm, cores_per_chip,
                                    slot_counts)
            choice = self._policy.pick(demand, catalog)
            if choice is None:
                # Whole-chip-class demand: no partition profile; any
                # active one for this tenant retires (urgent only if
                # the tenant is pending -- it needs whole chips now).
                decisions[tenant] = {"action": "no-fit",
                                     "demand": demand.hbm_bytes,
                                     "priority": prio}
                urgent = urgent or tenant in pending_tenants
                continue
            s_new = choice.profile.max_tenants
            cur = self._active_profile(tenant, active_by_name)
            action = "new"
            if cur is not None:
                s_old = cur.max_tenants
                budget_old = chip_hbm // max(s_old, 1)
                if prio > 0 and s_old > 1:
                    action = "isolate"  # latency-critical: off shared
                    urgent = True
                elif demand.hbm_bytes > budget_old:
                    action = "upsize"  # active budget blown: SLO risk
                    urgent = True
                elif s_new > s_old:
                    # Could pack finer -- but only when demand sits
                    # clearly below the finer budget (hysteresis). A
                    # cooperative tenant's band shrinks: its resize is
                    # a cheap checkpoint-then-switch, so the planner
                    # converges on it aggressively instead of
                    # rationing the disruption.
                    band = self.band
                    if tenant in coop_tenants:
                        from ..recovery import (  # noqa: PLC0415
                            COOP_COST_FACTOR,
                        )

                        band = self.band * min(max(
                            COOP_COST_FACTOR, 0.0), 1.0)
                    budget_new = chip_hbm // s_new
                    if demand.hbm_bytes > budget_new * (1 - band):
                        choice = self._keep(cur, chip_hbm,
                                            cores_per_chip)
                        action = "keep"
                    else:
                        action = "repack"
                else:
                    choice = self._keep(cur, chip_hbm, cores_per_chip)
                    action = "keep"
            else:
                urgent = urgent or tenant in pending_tenants
            profiles[choice.profile.name] = choice.profile
            decisions[tenant] = {
                "action": action,
                "slots": choice.profile.max_tenants,
                "budget": choice.per_tenant_hbm,
                "demand": demand.hbm_bytes,
                "priority": prio,
            }

        desired = PartitionSet(
            profiles=tuple(profiles[name] for name in sorted(profiles)),
            pools=tuple(pools) or active.pools)
        changed = ({p.name for p in desired.profiles}
                   != set(active_by_name)
                   or desired.pools != active.pools)
        # A retired profile (tenant aged out entirely) is never urgent.
        return PlanResult(desired=desired, changed=changed,
                          urgent=urgent and changed,
                          decisions=decisions)

    @staticmethod
    def _active_profile(tenant: str, active_by_name: dict
                        ) -> PartitionProfile | None:
        """The tenant's current profile in the active set (by the
        shape-versioned naming contract)."""
        best = None
        for name, prof in active_by_name.items():
            parsed = tenant_of_profile(name)
            if parsed and parsed[0] == tenant:
                # Multiple shapes mid-drain: the finest (newest
                # sizing) is the planning anchor.
                if best is None or prof.max_tenants > best.max_tenants:
                    best = prof
        return best

    @staticmethod
    def _retain_active(tenant: str, active_by_name: dict,
                       profiles: dict) -> None:
        for name, prof in active_by_name.items():
            parsed = tenant_of_profile(name)
            if parsed and parsed[0] == tenant:
                profiles[name] = prof

    def _keep(self, cur: PartitionProfile, chip_hbm: int,
              cores_per_chip: int):
        """Wrap the kept active profile in the choice shape (budgets
        computed exactly like the catalog path's CatalogEntry, so a
        kept and a freshly-sized choice never disagree)."""
        from ..partition.profiles import SizedChoice  # noqa: PLC0415

        slots = max(cur.max_tenants, 1)
        return SizedChoice(
            profile=cur,
            per_tenant_hbm=chip_hbm // slots,
            per_tenant_core_milli=1000 * cores_per_chip // slots)
