"""The autoscale controller: continuous demand-driven re-planning.

Rides the scheduler loop (``DraScheduler.attach_autoscaler``, so it is
leader-elected and informer-fed exactly like recovery/defrag) or runs
directly (``sync_once``) in tests and the autoscale bench. Each pass:

1. **Ingest** -- fold apiserver-declared tenant demand (claim
   annotations ``resource.tpu.dra/tenant-demand-hbm`` / ``-cores``)
   into the sliding-window TenantProfileStore. Node-side tpulib
   telemetry reaches the same store when the deployment co-locates the
   feed; either way the window (``TPU_DRA_PROFILE_WINDOW_S``) makes
   retired demand age out.
2. **Advance** -- drive any in-flight re-plan record through its
   ladder (Planned -> Applying -> confirmed/superseded). Records are
   durable (CheckpointManager under the ``autoscale``
   TransitionPolicy), so a controller crash at ANY fault point
   (``autoscale.sync`` / ``plan`` / ``apply`` / ``confirm``) resumes
   idempotently onto the SAME plan -- the desired spec is pinned in
   the Planned record.
3. **Plan** -- run the MISO/ParvaGPU planner over the demand
   percentiles + fleet pending demand; on drift past the hysteresis
   band (urgent upsizes immediately, repacks after
   ``TPU_DRA_AUTOSCALE_SUSTAIN_S``) write a durable Planned record and
   start the rollout. A converged pass (desired == active) writes
   NOTHING to the apiserver -- the steady-state-zero-writes contract
   the bench gates.

The controller owns exactly one CRD (``crd_name``, default
``tpu-dra-autoscale``) and never touches objects it does not manage:
an operator flipping ``resource.tpu.dra/autoscale-managed`` to
``"false"`` freezes re-planning (manual override), and a spec that
changed under an in-flight rollout supersedes the rollout (the
operator's content wins).
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time

from .. import faults, flightrecorder, positive_float_env
from ..analysis.statemachine import (
    AUTOSCALE_APPLYING,
    AUTOSCALE_PLANNED,
    AUTOSCALE_POLICY,
)
from ..kubeclient import ConflictError, KubeError, NotFoundError
from ..partition.profiles import (
    TENANT_PROFILE_ANNOTATION,
    TenantProfileStore,
)
from ..partition.spec import PartitionSet, PartitionSpecError
from . import crd
from .forecast import DemandForecaster
from .planner import (
    TENANT_DEMAND_CORES_ANNOTATION,
    TENANT_DEMAND_HBM_ANNOTATION,
    AutoscalePlanner,
    pool_chip_caps,
)

logger = logging.getLogger(__name__)

RESOURCE = ("resource.k8s.io", "v1")
CRD = (crd.AUTOSCALE_CRD_GROUP, crd.AUTOSCALE_CRD_VERSION,
       crd.AUTOSCALE_CRD_RESOURCE)


#: Repack (non-urgent) drift must persist this long before a rollout
#: fires; urgent drift (upsizes, latency-critical isolation, pending
#: demand with no profile) fires immediately.
AUTOSCALE_SUSTAIN_S = positive_float_env(
    "TPU_DRA_AUTOSCALE_SUSTAIN_S", default=120.0, floor=0.0)
#: Hysteresis band: a repack-down needs this much headroom below the
#: finer budget before it is proposed.
AUTOSCALE_BAND = positive_float_env(
    "TPU_DRA_AUTOSCALE_BAND", default=0.1, floor=0.0)
#: Quiet period after a completed rollout before the next plan.
AUTOSCALE_COOLDOWN_S = positive_float_env(
    "TPU_DRA_AUTOSCALE_COOLDOWN_S", default=60.0, floor=0.0)
#: Pause switch: "1"/"true" stops NEW plans; in-flight rollouts still
#: advance to completion (never park a half-applied CRD).
PAUSE_ENV = "TPU_DRA_AUTOSCALE_PAUSE"


def _meta(obj: dict) -> dict:
    return obj.get("metadata", {})


class AutoscaleController:
    """Plans and rolls out PartitionSet re-plans; designed to ride the
    event-driven scheduler loop (``attach_autoscaler``) or be driven
    directly (``sync_once``) by tests and ``bench.py --autoscale``."""

    _META_DEVICE = "autoscale"

    def __init__(self, kube, root: str, store=None, fleet=None,
                 metrics=None, crd_name: str = "tpu-dra-autoscale",
                 percentile: float = 0.95,
                 band: float = AUTOSCALE_BAND,
                 sustain_s: float = AUTOSCALE_SUSTAIN_S,
                 cooldown_s: float = AUTOSCALE_COOLDOWN_S,
                 slot_counts: tuple[int, ...] = (1, 2, 4, 8),
                 subslice: str = "1x1",
                 pools: tuple[str, ...] = ()):
        # Function-local import like pkg/recovery and pkg/defrag: pkg
        # -> kubeletplugin stays a one-way street for non-driver users.
        from ...kubeletplugin.checkpoint import (  # noqa: PLC0415
            CheckpointManager,
        )

        self.kube = kube
        self.store = store if store is not None else TenantProfileStore(
            defaults={})
        self.fleet = fleet  # pkg/fleetstate.FleetAggregator | None
        self.metrics = metrics  # pkg.metrics.AutoscaleMetrics | None
        self.crd_name = crd_name
        self.planner = AutoscalePlanner(
            percentile=percentile, band=band, slot_counts=slot_counts,
            subslice=subslice)
        self.sustain_s = sustain_s
        self.cooldown_s = cooldown_s
        self.pools = tuple(pools)
        # Predictive pre-warming: the forecaster projects near-term
        # per-pool partition demand from the fleet rings; the result
        # lands as the prewarm ANNOTATION on our CRD (advisory -- no
        # spec change, no rollout) and the node watchers drive
        # PartitionEngine.set_prewarm from it. None = disabled.
        self.forecaster = (DemandForecaster()
                           if os.environ.get("TPU_DRA_PREWARM", "1")
                           not in ("0", "false", "False") else None)
        # Prewarm-hint hysteresis, PER POOL: wall clock since a pool's
        # forecast first read zero while its hint stands. A pool's
        # entry clears only after the forecaster's stale window -- a
        # hint wobbling down must not write per pass, a plateau keeps
        # its warmth until demand has plausibly gone for good, and one
        # pool's ramp must never clobber another pool's held hint.
        self._prewarm_zero_since: dict[str, float] = {}
        self._checkpoint = CheckpointManager(
            root, transition_policy=AUTOSCALE_POLICY)
        self._lock = threading.Lock()
        self._active_count = len(self._checkpoint.get().claims)
        # Non-urgent drift sustain clock: fingerprint of the drifted
        # desired spec -> wall clock first observed. A DIFFERENT drift
        # restarts the clock (the fleet is still moving).
        self._drift_since: tuple[str, float] | None = None
        self._cooldown_until = 0.0
        # Optional informer-backed read surface
        # (pkg/schedcache.ClusterView), set by attach_autoscaler.
        self.view = None
        self.flight = flightrecorder.default()
        self.last_sync: dict = {}

    # -- scheduler surface ----------------------------------------------------

    def busy(self) -> bool:
        """True while a rollout record is in flight. Read by tests and
        the bench converge loops (the scheduler enqueues autoscale
        keys on EVERY partitionsets event, busy or not -- an operator
        edit must reach the defer/supersede logic promptly, unlike the
        recovery/defrag controllers whose per-claim event floods are
        gated on their busy())."""
        with self._lock:
            return self._active_count > 0

    @staticmethod
    def paused() -> bool:
        return os.environ.get(PAUSE_ENV, "") in ("1", "true", "True")

    # -- reads ----------------------------------------------------------------

    def _list_claims(self) -> list[dict]:
        if self.view is not None:
            return self.view.claims()
        return self.kube.list(*RESOURCE, "resourceclaims")

    def _list_slices(self) -> list[dict]:
        if self.view is not None:
            return self.view.slices()
        return self.kube.list(*RESOURCE, "resourceslices")

    def _list_partition_sets(self) -> list[dict]:
        if self.view is not None:
            return self.view.partition_sets()
        return self.kube.list(*CRD)

    # -- sync -----------------------------------------------------------------

    def sync_once(self) -> dict:
        """One ingest -> advance -> plan pass. Every stage is
        idempotent; a crash anywhere resumes from the durable
        record."""
        faults.fault_point("autoscale.sync")
        counts = {"advanced": 0, "applied": 0, "completed": 0,
                  "superseded": 0, "planned": 0, "converged": 0,
                  "deferred": 0}
        try:
            claims = self._list_claims()
            crds = self._list_partition_sets()
        except KubeError:
            logger.warning("autoscale sync: list failed; retrying "
                           "next pass")
            return counts
        live, pending, coop = self._ingest_claim_demand(claims)
        self._advance(counts)
        if not self.paused():
            self._detect_and_plan(crds, live, pending, counts,
                                  coop=coop)
            self._plan_prewarm(crds, counts)
        if counts["planned"]:
            # Issue the freshly planned rollout's CRD write in the
            # SAME pass (the record is already durable): the write's
            # own partitionsets informer event then drives the confirm
            # stage, so a rollout never waits out the safety resync.
            self._advance(counts, apply_only=True)
        active = len(self._checkpoint.get().claims)
        with self._lock:
            self._active_count = active
        if self.metrics is not None:
            self.metrics.active_rollouts.set(active)
        self.last_sync = counts
        return counts

    # -- demand ingest --------------------------------------------------------

    def _ingest_claim_demand(self, claims: list[dict]
                             ) -> tuple[set[str], set[str], set[str]]:
        """Fold annotation-declared demand into the store; returns
        (live tenant keys, pending tenant keys, cooperative tenant
        keys). Re-observed every pass on purpose: live claims keep
        their demand fresh inside the sliding window, and a retired
        claim's samples age out -- the decay half of the diurnal loop.

        A tenant is COOPERATIVE when every one of its live claims
        declares the checkpoint-then-switch contract
        (``resource.tpu.dra/migration-capable``): resizing it is a
        cheap cooperative move, so its repack hysteresis relaxes."""
        from ..recovery import claim_migration_capable  # noqa: PLC0415

        live: set[str] = set()
        pending: set[str] = set()
        cold: set[str] = set()
        for claim in claims:
            md = _meta(claim)
            if md.get("deletionTimestamp"):
                continue
            ann = md.get("annotations") or {}
            tenant = ann.get(TENANT_PROFILE_ANNOTATION)
            if not tenant:
                continue
            live.add(tenant)
            if not claim_migration_capable(claim):
                cold.add(tenant)
            if not claim.get("status", {}).get("allocation"):
                pending.add(tenant)
            raw = ann.get(TENANT_DEMAND_HBM_ANNOTATION)
            if raw is None:
                continue
            try:
                hbm = int(raw)
                cores = int(ann.get(TENANT_DEMAND_CORES_ANNOTATION, 1))
            except (TypeError, ValueError):
                continue  # malformed demand: observe nothing
            self.store.observe(tenant, hbm, cores=cores)
        return live, pending, live - cold

    # -- planning -------------------------------------------------------------

    def _our_crd(self, crds: list[dict]) -> dict | None:
        for obj in crds:
            if _meta(obj).get("name") == self.crd_name:
                return obj
        return None

    def _detect_and_plan(self, crds: list[dict], live: set[str],
                         pending: set[str], counts: dict,
                         coop: set[str] | None = None) -> None:
        if self._checkpoint.get().claims:
            return  # one rollout at a time: finish it first
        now = time.time()
        if now < self._cooldown_until:
            return
        our = self._our_crd(crds)
        rules: tuple = ()
        active = PartitionSet(pools=self.pools)
        if our is not None:
            if not crd.is_managed(our):
                # Operator took manual control: plan nothing until the
                # managed annotation returns.
                counts["deferred"] += 1
                return
            try:
                active, rules = crd.partition_set_from_crd(our)
            except PartitionSpecError as e:
                # Our own CRD hand-edited into garbage: fail closed --
                # replanning against an unknowable baseline could
                # stampede the fleet. The operator surface is the log
                # + the lint-clean CRD they are editing.
                logger.error("autoscale: managed PartitionSet %s is "
                             "malformed (%s); deferring re-plans",
                             self.crd_name, e)
                counts["deferred"] += 1
                return
        try:
            slices = self._list_slices()
        except KubeError:
            return
        chip_hbm, cores_per_chip = pool_chip_caps(slices)
        plan = self.planner.plan(
            self.store, active, rules=rules, chip_hbm=chip_hbm,
            cores_per_chip=cores_per_chip, live_tenants=live,
            pending_tenants=pending,
            pools=self.pools, coop_tenants=coop)
        if not plan.changed:
            counts["converged"] += 1
            self._drift_since = None
            if self.metrics is not None:
                self.metrics.converged.inc()
            return
        desired_spec = crd.spec_dict(plan.desired, rules)
        fp = crd.fingerprint(desired_spec)
        # The fleet pending-demand ring (pkg/fleetstate): sustained
        # pending claims while tenants wait means the current layout
        # is slot-starved -- a repack to finer profiles ADDS capacity,
        # so it must not idle out the sustain window.
        starving = bool(pending) and self.fleet is not None and \
            self.fleet.pending_recent() > 0
        if not plan.urgent and not starving:
            # Repack drift waits out the sustain window; the clock
            # restarts when the drift CONTENT moves (fleet still
            # settling).
            if self._drift_since is None or self._drift_since[0] != fp:
                self._drift_since = (fp, now)
            if now - self._drift_since[1] < self.sustain_s:
                counts["deferred"] += 1
                return
        self._drift_since = None
        faults.fault_point("autoscale.plan")
        self._write_record(fp, AUTOSCALE_PLANNED, live={
            "spec": desired_spec,
            "fingerprint": fp,
            "crd": self.crd_name,
            "plannedAt": now,
            "urgent": plan.urgent,
            "decisions": {t: d.get("action", "")
                          for t, d in plan.decisions.items()},
            "baseRevision": crd.revision_of(our) if our else 0,
        })
        counts["planned"] += 1
        with self._lock:
            self._active_count = max(self._active_count, 1)
        if self.metrics is not None:
            self.metrics.plans.inc()
        logger.warning(
            "autoscale re-plan %s: %d profile(s) [%s]%s", fp,
            len(plan.desired.profiles),
            ", ".join(f"{t}:{d.get('action')}"
                      for t, d in sorted(plan.decisions.items())),
            " (urgent)" if plan.urgent else "")

    # -- predictive pre-warming (forecast -> CRD hint) ------------------------

    @staticmethod
    def _parse_prewarm(raw: str) -> tuple[dict, bool]:
        """Tolerant parse of the standing prewarm annotation into
        ``{pool: {profile: int}}`` plus a garbage flag. EVERY
        malformed fragment (bad JSON, non-dict pools, non-int counts)
        reads as absent-and-garbage -- a hand-edited annotation must
        degrade to a rewrite, never crash the sync pass that carries
        real rollouts."""
        if not raw:
            return {}, False
        try:
            parsed = json.loads(raw)
        except (TypeError, ValueError):
            return {}, True
        if not isinstance(parsed, dict):
            return {}, True
        out: dict[str, dict[str, int]] = {}
        garbage = False
        for pool, profs in parsed.items():
            if not isinstance(profs, dict):
                garbage = True
                continue
            entry: dict[str, int] = {}
            for prof, n in profs.items():
                try:
                    n = int(n)
                except (TypeError, ValueError):
                    garbage = True
                    continue
                if n > 0:
                    entry[str(prof)] = n
            if entry:
                out[str(pool)] = entry
        return out, garbage

    def _plan_prewarm(self, crds: list[dict], counts: dict) -> None:
        """Project near-term partition demand per pool from the fleet
        rings and converge the prewarm annotation on our CRD. A
        converged forecast (or none) writes NOTHING -- the
        steady-state-zero-writes contract covers this stage too.
        Reads ride the pass's informer-backed CRD listing (an
        advisory hint needs no fresh-GET discipline; a stale view at
        worst re-issues an idempotent patch)."""
        if self.forecaster is None or self.fleet is None:
            return
        live = self._our_crd(crds)
        if live is None:
            return  # no governing CRD: nothing to hint
        if not crd.is_managed(live):
            return  # manual override freezes pre-warming too
        try:
            ps, _rules = crd.partition_set_from_crd(live)
        except PartitionSpecError:
            return  # malformed spec: the plan stage already defers
        per_pool = self.forecaster.forecast(self.fleet.snapshot())
        cap = int(positive_float_env("TPU_DRA_PREWARM_MAX",
                                     default=8, floor=0))
        hints: dict[str, dict[str, int]] = {}
        if ps.profiles and cap > 0:
            # New tenants land on the finest (highest-slot) profile;
            # that is the shape worth warming.
            best = max(ps.profiles, key=lambda p: p.max_tenants)
            for label, slots in sorted(per_pool.items()):
                pool = label.split("/", 1)[-1]
                devices = min(
                    math.ceil(slots / max(best.max_tenants, 1)), cap)
                if devices > 0:
                    hints[pool] = {best.name: devices}
        raw = (live.get("metadata", {}).get("annotations")
               or {}).get(crd.PREWARM_ANNOTATION, "")
        cur, garbage = self._parse_prewarm(raw)
        # Write-stability hysteresis (the zero-write steady-state
        # contract), judged PER POOL: GROWTH writes immediately (a
        # burst must warm now) and carries every other pool's held
        # hint along (one ramp must not clobber a plateau's warmth);
        # a shrinking/wobbling forecast holds the standing hint (no
        # per-pass rewrites while a trend decays); a pool whose
        # forecast stays ZERO for the forecaster's stale window drops
        # out once -- the idle sweep then returns its chips.
        now = time.time()
        for pool in list(self._prewarm_zero_since):
            if pool in hints or pool not in cur:
                del self._prewarm_zero_since[pool]
        for pool in cur:
            if pool not in hints:
                self._prewarm_zero_since.setdefault(pool, now)
        expired = {pool for pool, ts in
                   self._prewarm_zero_since.items()
                   if now - ts >= self.forecaster.stale_s}
        held = {pool: profs for pool, profs in cur.items()
                if pool not in hints and pool not in expired}
        merged = {**held, **hints}
        grown = any(
            n > (cur.get(pool) or {}).get(prof, 0)
            for pool, profs in hints.items()
            for prof, n in profs.items())
        if not garbage and not grown and set(merged) == set(cur):
            return  # converged or wobbling: zero writes
        if grown:
            value = crd.prewarm_value(merged)
        else:
            # Expiry / garbage repair without growth: hold every
            # still-live pool's STANDING counts (a not-grown forecast
            # never lowers a held hint -- that is the hold), drop only
            # the expired pools.
            value = crd.prewarm_value(
                {pool: profs for pool, profs in cur.items()
                 if pool not in expired})
        try:
            self.kube.patch(*CRD, self.crd_name, {
                "metadata": {"annotations": {
                    crd.PREWARM_ANNOTATION: value or None,
                }},
            })
        except (ConflictError, NotFoundError, KubeError):
            return  # advisory hint: retried next pass
        for pool in expired:
            self._prewarm_zero_since.pop(pool, None)
        counts["prewarmed"] = counts.get("prewarmed", 0) + 1
        self.flight.record(self.crd_name, "autoscale",
                           state="Prewarm", hint=value or "(cleared)")
        logger.info("autoscale prewarm hint: %s", value or "cleared")

    # -- durable records ------------------------------------------------------

    def _write_record(self, uid_fp: str, state: str,
                      live: dict | None = None, prev=None) -> None:
        from ...kubeletplugin.checkpoint import (  # noqa: PLC0415
            CheckpointedClaim,
            CheckpointedDevice,
        )

        uid = f"replan-{uid_fp}"
        if prev is not None:
            live = dict(prev.devices[0].live or {}) \
                if prev.devices else {}
        self._checkpoint.update_claim(uid, CheckpointedClaim(
            uid=uid, state=state,
            devices=[CheckpointedDevice(
                canonical_name=self._META_DEVICE,
                kind=self._META_DEVICE, live=live or {})],
        ))
        self.flight.record(uid, "autoscale", state=state,
                           fingerprint=uid_fp)

    @staticmethod
    def _record_meta(rec) -> dict:
        return (rec.devices[0].live or {}) if rec.devices else {}

    # -- rollout ladder -------------------------------------------------------

    def _advance(self, counts: dict, apply_only: bool = False) -> None:
        records = self._checkpoint.get().claims
        for uid in sorted(records):
            rec = records[uid]
            meta = self._record_meta(rec)
            fp = meta.get("fingerprint", "")
            if rec.state == AUTOSCALE_PLANNED:
                if self._apply(uid, fp, meta, counts):
                    counts["advanced"] += 1
                    counts["applied"] += 1
            elif rec.state == AUTOSCALE_APPLYING and not apply_only:
                self._confirm(uid, fp, meta, counts)

    def _supersede(self, uid: str, counts: dict, why: str) -> None:
        self._checkpoint.update_claim(uid, None)
        counts["superseded"] += 1
        if self.metrics is not None:
            self.metrics.superseded.inc()
        self.flight.record(uid, "autoscale", state="Superseded")
        logger.warning("autoscale rollout %s superseded: %s; operator "
                       "content wins", uid, why)

    def _apply(self, uid: str, fp: str, meta: dict,
               counts: dict) -> bool:
        """Write the pinned spec to the apiserver (create or
        merge-patch), then durably mark Applying. Idempotent: a resume
        after a crash mid-write re-issues the same content. An
        operator who flipped the managed annotation off while the
        record was in flight wins: the rollout retires untouched --
        the write below must never stomp a manual-override flip (only
        the CREATE path may stamp the annotation)."""
        faults.fault_point("autoscale.apply")
        spec = meta.get("spec") or {}
        revision = int(meta.get("baseRevision", 0)) + 1
        try:
            live = self.kube.get(*CRD, self.crd_name)
        except NotFoundError:
            live = None
        except KubeError:
            return False  # retry next pass
        if live is not None and not crd.is_managed(live):
            self._supersede(uid, counts,
                            "managed annotation flipped off mid-plan")
            return False
        try:
            if live is None:
                self.kube.create(*CRD, crd.crd_object_from_spec(
                    self.crd_name, spec, revision=revision))
            else:
                self.kube.patch(*CRD, self.crd_name, {
                    "metadata": {"annotations": {
                        crd.REVISION_ANNOTATION: str(revision),
                    }},
                    "spec": spec,
                })
        except ConflictError:
            return False  # re-examined next pass
        except KubeError:
            logger.warning("autoscale: CRD apply failed; retrying")
            return False
        rec = self._checkpoint.get().claims.get(uid)
        self._write_record(fp, AUTOSCALE_APPLYING, prev=rec)
        return True

    def _confirm(self, uid: str, fp: str, meta: dict,
                 counts: dict) -> None:
        """Fresh-read the CRD; our content standing = rollout
        complete, anything else = superseded (the operator's content
        wins -- we never fight a manual edit)."""
        faults.fault_point("autoscale.confirm")
        try:
            live = self.kube.get(*CRD, self.crd_name)
        except NotFoundError:
            live = None
        except KubeError:
            return  # retry next pass
        counts["advanced"] += 1
        if live is not None and \
                crd.fingerprint(live.get("spec", {})) == fp:
            self._checkpoint.update_claim(f"replan-{fp}", None)
            counts["completed"] += 1
            self._cooldown_until = time.time() + self.cooldown_s
            planned_at = float(meta.get("plannedAt", 0.0))
            if self.metrics is not None:
                self.metrics.applies.inc()
                if planned_at:
                    self.metrics.rollout_seconds.observe(
                        max(time.time() - planned_at, 0.0))
            self.flight.record(f"replan-{fp}", "autoscale",
                               state="Completed")
            logger.warning("autoscale rollout %s complete", fp)
        else:
            self._supersede(f"replan-{fp}", counts,
                            "concurrent PartitionSet edit")
