"""Node-side PartitionSet CRD watch: CRD updates -> engine re-plan.

The kubelet plugin used to load its partition layout ONCE from the
``--partition-set`` file at startup; re-plans needed a manual
``Driver.apply_partition_set`` call. This watcher makes the
cluster-scoped PartitionSet CRD the source of truth: an informer over
``partitionsets.resource.tpu.dra`` converges every matching update
into ``Driver.apply_partition_set`` (which republishes through the
content-hash diff -- a converged re-apply costs zero kube writes). The
file survives as the BOOTSTRAP fallback: it is the plan while no CRD
governs this pool, and the plan the node reverts to when the governing
CRD is deleted.

Fail-closed contract (the satellite the CRD->node seam tests pin):

- a MALFORMED winning CRD keeps the last good plan active
  (``last_error`` surfaces the parse failure, ``failed_total``
  counts it);
- an UNREALIZABLE plan (a profile naming a carve-out this host cannot
  cut, or a re-shape of a live-tenant profile -- both
  ``PartitionSpecError`` from the engine) is rejected the same way;
- a restarted plugin converges to the same carve-out set as a live
  one: the informer's initial list drives the same ``_reconcile``
  path an event does.
"""

from __future__ import annotations

import logging
import threading

from ..informer import Informer
from ..partition.spec import PartitionSet, PartitionSpecError
from . import crd

logger = logging.getLogger(__name__)


class PartitionSetWatcher:
    """Watches PartitionSet CRDs and applies the winning plan for one
    pool through ``apply_fn`` (``Driver.apply_partition_set``)."""

    def __init__(self, kube, pool: str, apply_fn,
                 bootstrap: PartitionSet | None = None,
                 resync_period: float = 300.0,
                 prewarm_fn=None):
        self.pool = pool
        self._apply_fn = apply_fn
        # Predictive pre-warming (``Driver.apply_prewarm``): the
        # winning CRD's prewarm ANNOTATION (the scheduler-side
        # forecaster's hint) converges through this on every
        # reconcile, independent of the spec fingerprint -- an
        # annotation-only patch must reach the engine without a
        # layout re-apply.
        self._prewarm_fn = prewarm_fn
        self._applied_prewarm: dict[str, int] | None = None
        self._bootstrap = bootstrap
        self._bootstrap_fp = (
            crd.fingerprint(bootstrap.to_dict())
            if bootstrap is not None else None)
        # The fingerprint of the currently APPLIED plan: None until
        # the first reconcile; the bootstrap plan (already applied by
        # DeviceState construction) is the implicit initial state.
        self._applied_fp: str | None = self._bootstrap_fp
        self._lock = threading.Lock()
        self.last_error: str | None = None
        self.applied_total = 0
        self.failed_total = 0
        self._informer = Informer(
            kube, crd.AUTOSCALE_CRD_GROUP, crd.AUTOSCALE_CRD_VERSION,
            crd.AUTOSCALE_CRD_RESOURCE, kind=crd.AUTOSCALE_CRD_KIND,
            resync_period=resync_period)
        self._informer.add_event_hook(self._on_event)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "PartitionSetWatcher":
        self._informer.start()
        # The initial list IS the first reconcile: a freshly restarted
        # plugin converges to the cluster's current plan before any
        # event arrives.
        self.reconcile()
        return self

    def stop(self) -> None:
        self._informer.stop()

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return self._informer.wait_for_sync(timeout)

    @property
    def applied_fingerprint(self) -> str | None:
        with self._lock:
            return self._applied_fp

    # -- reconcile ------------------------------------------------------------

    def _fail(self, msg: str) -> None:
        """Fail-closed bookkeeping (caller holds the lock): the log
        AND the counter dedupe on the error text, so one persistent
        malformed CRD counts ONCE instead of once per event/resync --
        the counter distinguishes a stuck plan from a flapping
        fleet."""
        if msg != self.last_error:
            logger.error("autoscale watch: %s; keeping the last good "
                         "plan active (fail closed)", msg)
            self.failed_total += 1
        self.last_error = msg

    def _converge_prewarm(self, hints: dict[str, int],
                          force: bool = False) -> None:
        """Apply a changed pre-warm hint through ``prewarm_fn``
        (Driver.apply_prewarm -> engine.set_prewarm). Best-effort: a
        failing engine must never block plan convergence. ``force``
        re-applies even an unchanged hint (a plan was just applied;
        the warm set must re-converge onto the new layout)."""
        if self._prewarm_fn is None:
            return
        with self._lock:
            if not force and hints == self._applied_prewarm:
                return
        try:
            self._prewarm_fn(hints)
        except Exception as e:  # noqa: BLE001 - advisory latency hint
            # NOT memoized either way: the next reconcile retries the
            # shortfall. A PartitionEngineError is the engine's
            # expected partial-application signal (name-matched: the
            # engine class is not importable here without pulling the
            # kubeletplugin stack into pkg/autoscale); anything else
            # is a bug worth a traceback.
            if type(e).__name__ == "PartitionEngineError":
                logger.warning(
                    "autoscale watch: prewarm hint partially applied "
                    "(%s); retrying next reconcile", e)
            else:
                logger.exception("autoscale watch: prewarm hint "
                                 "failed; lazy creates still serve")
            return
        with self._lock:
            self._applied_prewarm = dict(hints)

    def _on_event(self, _ev_type: str, _obj: dict) -> None:
        # Cheap full reconcile per event: selection is global (the
        # winning CRD may CHANGE when any object appears/vanishes), so
        # per-object incremental upkeep would re-derive the same
        # ordering anyway. Runs on the informer's notify thread.
        self.reconcile()

    def reconcile(self) -> bool:
        """Converge the node onto the winning plan, then the plan's
        pre-warm hint. Returns True when a plan was (re-)applied."""
        outcome, payload, obj = crd.select_for_pool(
            self._informer.list(), self.pool)
        applied = self._reconcile_plan(outcome, payload, obj)
        if outcome != "malformed":
            # The advisory pre-warm hint converges on EVERY reconcile,
            # AFTER the plan apply above -- set_prewarm can only
            # realize carve-outs for profiles the engine already
            # projects, so a hint arriving with its plan must see the
            # new layout (and a re-applied plan re-converges even an
            # unchanged hint: the apply may have reaped/retired warm
            # records). A malformed winning spec keeps the last good
            # hint, like the plan; no governing CRD = no hint = the
            # engine releases its warm set to the idle sweep.
            self._converge_prewarm(
                crd.prewarm_hints_of(obj, self.pool), force=applied)
        return applied

    def _reconcile_plan(self, outcome: str, payload, obj) -> bool:
        with self._lock:
            if outcome == "malformed":
                name = (obj or {}).get("metadata", {}).get("name", "?")
                self._fail(f"PartitionSet {name}: {payload}")
                return False
            if outcome == "none":
                if self._bootstrap is None or \
                        self._applied_fp == self._bootstrap_fp:
                    self.last_error = None  # converged: error resolved
                    return False
                plan, fp = self._bootstrap, self._bootstrap_fp
                source = "bootstrap file"
            else:
                plan, _rules, fp = payload
                if fp == self._applied_fp:
                    self.last_error = None  # converged: error resolved
                    return False
                source = (obj or {}).get("metadata", {}).get(
                    "name", "?")
            try:
                self._apply_fn(plan)
            except PartitionSpecError as e:
                self._fail(f"plan from {source} rejected: {e}")
                return False
            except Exception as e:  # noqa: BLE001 - node must survive
                # Republish hiccups (transient kube errors) are not a
                # plan failure; the next event / publish recheck
                # heals. The plan itself applied.
                logger.warning("autoscale watch: republish after "
                               "apply failed (%s); will self-heal", e)
            self._applied_fp = fp
            self.last_error = None
            self.applied_total += 1
            logger.info(
                "autoscale watch: applied partition plan from %s "
                "(%d profile(s)) on pool %s", source,
                len(plan.profiles), self.pool)
            return True
