"""The PartitionSet CRD: the fleet-wide desired partition layout.

Cluster-scoped ``partitionsets.resource.tpu.dra/v1beta1`` objects carry
the SAME spec the node-local layout file did (``profiles`` +
``pools``), plus the autoscaler's operator inputs:

- ``spec.priorityRules``: per-profile CEL-selectable priority. Each
  rule is ``{"selector": <CEL over the tenant>, "priority": <int>}``;
  the expression sees a ``tenant`` variable
  (``{"key": str, "hbmBytes": int, "cores": int}``). A tenant matching
  any rule with priority > 0 is latency-critical: the planner sizes it
  against NON-oversubscribed profiles only (maxTenants == 1), packing
  it away from shared devices.
- ``metadata.annotations["resource.tpu.dra/autoscale-managed"]``:
  ``"true"`` on CRDs the controller owns and may rewrite. An operator
  flips it to ``"false"`` to take manual control -- the controller
  stops planning against that object (the manual-override procedure,
  docs/operations.md). CRDs the controller did not create are never
  rewritten.

Node-side selection is deterministic: among the cluster's
PartitionSets whose ``spec.pools`` globs match this node's pool, the
LEXICOGRAPHICALLY FIRST by name wins -- so an operator-authored
``00-manual-override`` object out-ranks the controller's
``tpu-dra-autoscale`` without any coordination. A malformed winning
object fails CLOSED: the watcher keeps the last good plan active and
surfaces the parse error.

Construction of PartitionSet/PartitionProfile specs (and
``partitionsets`` apiserver writes) is fenced to pkg/autoscale/ +
pkg/partition/spec.py by lint rule TPUDRA014.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass
from fnmatch import fnmatch
from functools import lru_cache

from ..cel import CelEvalError, CelParseError, compile_expression
from ..partition.spec import PartitionSet, PartitionSpecError


@lru_cache(maxsize=256)
def _compiled_selector(selector: str):
    """One CelProgram per distinct rule source: matches() runs per
    tenant per rule per planning pass, for expressions that never
    change (the AST underneath is process-memoized too; this also
    skips re-wrapping it)."""
    return compile_expression(selector)

AUTOSCALE_CRD_GROUP = "resource.tpu.dra"
AUTOSCALE_CRD_VERSION = "v1beta1"
AUTOSCALE_CRD_RESOURCE = "partitionsets"
AUTOSCALE_CRD_KIND = "PartitionSet"

#: "true" on controller-managed CRDs; an operator flips it to "false"
#: to freeze the object against re-plans (manual override).
MANAGED_ANNOTATION = "resource.tpu.dra/autoscale-managed"
#: Revision counter the controller bumps per applied re-plan
#: (observability only -- the content fingerprint is the identity).
REVISION_ANNOTATION = "resource.tpu.dra/autoscale-revision"
#: Predictive pre-warm hint (the forecaster's output, pkg/autoscale/
#: forecast.py): JSON ``{"<pool glob>": {"<profile>": count}}``. An
#: ANNOTATION, not spec -- the hint is advisory and must neither move
#: the spec fingerprint (no rollout/supersede churn) nor survive as
#: layout. Node watchers read their pool's entry and drive
#: ``PartitionEngine.set_prewarm``; a malformed value reads as no hint
#: (fail closed to the lazy-create behavior).
PREWARM_ANNOTATION = "resource.tpu.dra/prewarm"


@dataclass(frozen=True)
class PriorityRule:
    """One CEL-selected tenant priority class."""

    selector: str
    priority: int

    def to_dict(self) -> dict:
        return {"selector": self.selector, "priority": self.priority}

    def matches(self, tenant: str, hbm_bytes: int, cores: int) -> bool:
        """Evaluate the selector against one tenant. Errors mean "does
        not match" (the claim-selector CEL contract): a broken rule
        must never grant or deny priority by crashing the planner."""
        try:
            prog = _compiled_selector(self.selector)
            result = prog.evaluate({"tenant": {
                "key": tenant, "hbmBytes": hbm_bytes, "cores": cores,
            }})
        except (CelParseError, CelEvalError):
            return False
        return result is True


def parse_priority_rules(raw: list | None) -> tuple[PriorityRule, ...]:
    """Strict-parse ``spec.priorityRules``; malformed rules raise
    PartitionSpecError (the whole CRD then fails closed)."""
    rules = []
    for i, entry in enumerate(raw or []):
        if not isinstance(entry, dict) or not entry.get("selector"):
            raise PartitionSpecError(
                f"priorityRules[{i}]: want {{selector, priority}}")
        selector = str(entry["selector"])
        try:
            compile_expression(selector)
        except CelParseError as e:
            raise PartitionSpecError(
                f"priorityRules[{i}]: bad CEL selector "
                f"{selector!r}: {e}") from e
        try:
            priority = int(entry.get("priority", 0))
        except (TypeError, ValueError) as e:
            raise PartitionSpecError(
                f"priorityRules[{i}]: priority must be an int") from e
        rules.append(PriorityRule(selector=selector, priority=priority))
    return tuple(rules)


def partition_set_from_crd(obj: dict) -> tuple[PartitionSet,
                                               tuple[PriorityRule, ...]]:
    """Strict-parse one PartitionSet CRD object. Raises
    PartitionSpecError on anything malformed (callers fail closed)."""
    spec = obj.get("spec")
    if not isinstance(spec, dict):
        raise PartitionSpecError(
            f"PartitionSet {obj.get('metadata', {}).get('name')!r}: "
            "missing spec")
    ps = PartitionSet.from_dict(spec)
    return ps, parse_priority_rules(spec.get("priorityRules"))


def fingerprint(spec: dict) -> str:
    """Content identity of one CRD spec (order-insensitive): the
    rollout-confirmation and steady-state-no-write comparisons both
    key on this, so a semantically identical spec never re-applies."""
    return hashlib.sha256(
        json.dumps(spec, sort_keys=True).encode()).hexdigest()[:16]


def spec_dict(partition_set: PartitionSet,
              priority_rules: tuple[PriorityRule, ...] = ()) -> dict:
    out = partition_set.to_dict()
    if priority_rules:
        out["priorityRules"] = [r.to_dict() for r in priority_rules]
    return out


def crd_object_from_spec(name: str, spec: dict, revision: int = 1,
                         managed: bool = True) -> dict:
    """The canonical managed-CRD object shape -- the ONE authoring
    site for apiVersion/kind/metadata, shared by crd_object() and the
    controller's create path."""
    return {
        "apiVersion": f"{AUTOSCALE_CRD_GROUP}/{AUTOSCALE_CRD_VERSION}",
        "kind": AUTOSCALE_CRD_KIND,
        "metadata": {
            "name": name,
            "annotations": {
                MANAGED_ANNOTATION: "true" if managed else "false",
                REVISION_ANNOTATION: str(revision),
            },
        },
        "spec": spec,
    }


def crd_object(name: str, partition_set: PartitionSet,
               priority_rules: tuple[PriorityRule, ...] = (),
               revision: int = 1, managed: bool = True) -> dict:
    return crd_object_from_spec(
        name, spec_dict(partition_set, priority_rules),
        revision=revision, managed=managed)


def is_managed(obj: dict) -> bool:
    ann = (obj.get("metadata", {}).get("annotations") or {})
    return ann.get(MANAGED_ANNOTATION) == "true"


def prewarm_value(hints: dict[str, dict[str, int]]) -> str:
    """Canonical (sorted) annotation encoding of pool -> profile ->
    count hints; "" means the annotation should be absent."""
    cleaned = {
        pool: {prof: int(n) for prof, n in profs.items() if int(n) > 0}
        for pool, profs in (hints or {}).items()
    }
    cleaned = {pool: profs for pool, profs in cleaned.items() if profs}
    return json.dumps(cleaned, sort_keys=True) if cleaned else ""


def prewarm_hints_of(obj: dict | None, pool: str) -> dict[str, int]:
    """``{profile: count}`` this pool should keep warm, parsed from
    the winning CRD's prewarm annotation (pool keys are fnmatch globs,
    like ``spec.pools``). Malformed annotations read as {} -- the
    fail-closed direction for an advisory latency hint is OFF."""
    if obj is None:
        return {}
    raw = (obj.get("metadata", {}).get("annotations")
           or {}).get(PREWARM_ANNOTATION)
    if not raw:
        return {}
    try:
        parsed = json.loads(raw)
    except (TypeError, ValueError):
        return {}
    if not isinstance(parsed, dict):
        return {}
    out: dict[str, int] = {}
    for pat, profs in parsed.items():
        if not isinstance(profs, dict) or \
                not fnmatch(pool, str(pat)):
            continue
        for prof, count in profs.items():
            try:
                n = int(count)
            except (TypeError, ValueError):
                continue
            if n > 0:
                out[str(prof)] = max(out.get(str(prof), 0), n)
    return out


def revision_of(obj: dict) -> int:
    ann = (obj.get("metadata", {}).get("annotations") or {})
    try:
        return int(ann.get(REVISION_ANNOTATION, 0))
    except (TypeError, ValueError):
        return 0


def _pools_of(obj: dict) -> list[str]:
    """Lenient read of spec.pools (selection must work even when the
    rest of the spec is malformed, so a broken winning CRD is
    DETECTED rather than silently skipped in favor of a lower-ranked
    one the operator did not intend to win)."""
    spec = obj.get("spec") or {}
    pools = spec.get("pools") or []
    if not isinstance(pools, list):
        return []
    return [str(p) for p in pools]


def applies_to_pool(obj: dict, pool: str) -> bool:
    pools = _pools_of(obj)
    if not pools:
        return True
    return any(fnmatch(pool, pat) for pat in pools)


def select_for_pool(objs: list[dict], pool: str
                    ) -> tuple[str, object, dict | None]:
    """Pick the PartitionSet governing ``pool``: lexicographically
    first by name among the objects whose pool globs match.

    Returns one of:
    - ``("ok", (partition_set, rules, fingerprint), obj)``
    - ``("malformed", error_message, obj)`` -- the winning object
      cannot be parsed; the caller keeps its last good plan (fail
      closed)
    - ``("none", None, None)`` -- nothing governs this pool; the
      caller falls back to its bootstrap plan.
    """
    matching = sorted(
        (o for o in objs if applies_to_pool(o, pool)),
        key=lambda o: o.get("metadata", {}).get("name", ""))
    if not matching:
        return "none", None, None
    winner = matching[0]
    try:
        ps, rules = partition_set_from_crd(winner)
    except PartitionSpecError as e:
        return "malformed", str(e), winner
    return "ok", (ps, rules, fingerprint(winner.get("spec", {}))), winner
