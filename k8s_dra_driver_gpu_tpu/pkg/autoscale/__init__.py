"""Serving autoscaler: the demand-driven PartitionSet control plane.

ROADMAP open item 4 (the millions-of-users path). PR 8's partition
engine sized tenants ONCE from a static ``--partition-set`` file and
re-planned only through a manual ``Driver.apply_partition_set``; PR 9
already streams live per-tenant HBM/core demand into the
``TenantProfileStore``. This package closes the loop:

- **crd.py** -- the cluster-scoped ``PartitionSet`` CRD
  (``partitionsets.resource.tpu.dra/v1beta1``): the fleet-wide desired
  partition layout, watched through the existing informer machinery.
  It replaces the node-local layout file as the source of truth; the
  file survives as the bootstrap fallback.
- **planner.py** -- MISO (2207.11428) profile-guided sizing + ParvaGPU
  (2409.14447) demand-aware packing over the observed demand
  percentiles, with a hysteresis band so the fleet tracks diurnal load
  without flapping, and per-profile CEL-selectable priority so
  latency-critical tenants are packed away from oversubscribed
  devices.
- **controller.py** -- the re-planning controller riding the scheduler
  loop (``DraScheduler.attach_autoscaler``, leader-elected like
  recovery/defrag): durable re-plan records under the ``autoscale``
  TransitionPolicy make a crash mid-rollout resume idempotently.
- **nodewatch.py** -- the node plugin's CRD watcher: every matching
  PartitionSet update converges the node's published partition devices
  through ``Driver.apply_partition_set`` (live-tenant-safe: the engine
  refuses to re-shape held carve-outs, and retired profiles drain
  through ``prune_retired_partitions``); a malformed CRD fails CLOSED,
  keeping the last good plan active.

Lint rule TPUDRA014 fences PartitionSet spec/profile construction and
``partitionsets`` apiserver writes to this package plus the
``pkg/partition/spec.py`` definition site.
"""

from .controller import AutoscaleController
from .crd import (
    AUTOSCALE_CRD_GROUP,
    AUTOSCALE_CRD_KIND,
    AUTOSCALE_CRD_RESOURCE,
    AUTOSCALE_CRD_VERSION,
    MANAGED_ANNOTATION,
    PriorityRule,
    crd_object,
    fingerprint,
    partition_set_from_crd,
    select_for_pool,
)
from .nodewatch import PartitionSetWatcher
from .planner import AutoscalePlanner, PlanResult, pool_chip_caps

__all__ = [
    "AUTOSCALE_CRD_GROUP",
    "AUTOSCALE_CRD_KIND",
    "AUTOSCALE_CRD_RESOURCE",
    "AUTOSCALE_CRD_VERSION",
    "MANAGED_ANNOTATION",
    "AutoscaleController",
    "AutoscalePlanner",
    "PartitionSetWatcher",
    "PlanResult",
    "PriorityRule",
    "crd_object",
    "fingerprint",
    "partition_set_from_crd",
    "pool_chip_caps",
    "select_for_pool",
]
