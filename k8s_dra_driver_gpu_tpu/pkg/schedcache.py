"""Indexed allocation snapshots + informer-backed cluster view.

The scheduler stand-in used to re-derive the world on every 0.25s pass:
re-list every watched resource, rebuild every candidate device list,
re-evaluate every CEL selector per claim. This module is the
incremental-state backbone that replaces that:

- ``InventorySnapshot``: the device inventory (candidates, per-node
  index, KEP-4815 counter seeds, static CEL selector evaluations, the
  topology scorer's ordering memos) built ONCE per ResourceSlice
  change and shared across claims and sync passes. The snapshot
  signature covers every slice's (name, resourceVersion, pool
  generation): any slice write -- including a pool-generation bump --
  invalidates it.
- ``AllocationState``: the allocated-device set and the debited
  counter ledger, maintained INCREMENTALLY from ResourceClaim events
  (observe/forget) instead of being rebuilt per claim per pass.
- ``ClusterView``: one read surface for the scheduler's sync paths.
  Event-driven mode backs it with per-resource informers (list+watch
  caches, pkg/informer.py) so a sync pass performs zero kube reads;
  direct mode (unit tests, one-shot sync) falls through to the kube
  client. Scheduler sync code must read through this view -- lint rule
  TPUDRA009 (pkg/analysis) forbids raw ``kube.list`` of watched
  resources inside pkg/scheduler.py.

Reference: controller-runtime's informer-indexed reconcilers and the
structured-parameters DRA plugin's allocator snapshot (see PAPERS.md);
the reference driver consumes CRs exclusively through informer caches.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from functools import lru_cache
from typing import Callable

from . import tracing
from .cel import CelProgram, Quantity, compile_expression
from .informer import RELIST_PRIORITY, Informer, RelistCoordinator
from .kubeclient import KubeError, NotFoundError
from .topology.score import attr_int as _attr_int, \
    device_headroom_penalty

logger = logging.getLogger(__name__)

RESOURCE = ("resource.k8s.io", "v1")

# -- power as a budgeted resource (2501.17752) --------------------------------
#
# Per-host power is modeled like a KEP-4815 counter: every node has a
# power cap (the slice attribute below, stamped by the node plugin from
# its TPU_DRA_POWER_CAP_W, or the scheduler-side env default) and every
# allocated device debits its expected draw -- the published rated
# draw, falling back to the live telemetry attribute, falling back to
# the TPU_DRA_CHIP_POWER_W default. ``AllocationState.try_commit``
# judges the node budget atomically alongside the chip counters, so a
# power-capped rack structurally cannot over-commit even under racing
# workers. Caps/draws of 0 (the default) disable the model entirely --
# the historical behavior.
ATTR_POWER_CAP = "powerCapWatts"
ATTR_POWER_RATED = "powerRatedWatts"
# Mirror of pkg/fleetstate.ATTR_POWER (kept literal like CD_GROUP: the
# attribute contract, not an import edge).
ATTR_POWER_TELEMETRY = "telemetryPowerWatts"


@lru_cache(maxsize=8)
def _parse_watts(raw: str) -> int:
    try:
        return max(int(float(raw)), 0)
    except ValueError:
        return 0


def power_cap_env(env=None) -> int:
    """Scheduler-side default per-node power cap in watts
    (``TPU_DRA_POWER_CAP_W``); 0 = no cap (model off) for nodes that
    publish no ``powerCapWatts`` attribute. Called per node on the
    fit/commit paths: the env read stays live (tests flip it), the
    parse is memoized on the raw string."""
    return _parse_watts((env or os.environ).get(
        "TPU_DRA_POWER_CAP_W", "0"))


def chip_power_default_env(env=None) -> int:
    """Default expected draw in watts for a non-partition device that
    publishes neither ``powerRatedWatts`` nor live telemetry
    (``TPU_DRA_CHIP_POWER_W``); 0 = such devices debit nothing.
    Same live-read/memoized-parse discipline as ``power_cap_env``
    (called per device at snapshot build)."""
    return _parse_watts((env or os.environ).get(
        "TPU_DRA_CHIP_POWER_W", "0"))

# ComputeDomain CRD coordinates (kept literal: importing the
# computedomain package here would cycle through the plugin stack).
CD_GROUP = "resource.tpu.dra"
CD_VERSION = "v1beta1"
PREFERRED_NODES_ANNOTATION = "resource.tpu.dra/preferredNodes"


def tolerates(taint: dict, tolerations: list[dict]) -> bool:
    for tol in tolerations or []:
        if tol.get("effect") and tol["effect"] != taint.get("effect"):
            continue
        op = tol.get("operator", "Equal")
        if op == "Exists":
            if not tol.get("key") or tol["key"] == taint.get("key"):
                return True
        elif tol.get("key") == taint.get("key") and \
                tol.get("value", "") == taint.get("value", ""):
            return True
    return False


class CompiledSelectors:
    """Expression -> CelProgram cache; a selector that fails to compile
    permanently matches nothing (and is logged once), like a CEL
    compile error surfaced in the scheduler.

    The cache is shared process-wide (class-level, lock-guarded) and
    keyed by source text: a scheduler instantiated per sync pass still
    reuses every previously compiled selector. cel.compile_expression
    additionally memoizes the parsed AST, so even a fresh cache entry
    skips the lex+parse for text seen anywhere else in the process."""

    _shared: dict[str, CelProgram | None] = {}
    _shared_lock = threading.Lock()
    _MAX = 4096  # selectors are operator-authored; this is a leak bound

    def __init__(self):
        self._cache = self._shared

    def get(self, expression: str) -> CelProgram | None:
        with self._shared_lock:
            if expression in self._cache:
                return self._cache[expression]
        try:
            prog = compile_expression(expression)
        except Exception as e:  # noqa: BLE001 - compile boundary
            logger.error("selector does not compile (%s): %s",
                         e, expression)
            prog = None
        with self._shared_lock:
            if len(self._cache) >= self._MAX:
                self._cache.clear()
            self._cache[expression] = prog
        return prog


class CounterLedger:
    """Available KEP-4815 counters per (driver, pool, counterSet),
    seeded from sharedCounters and debited by consumesCounters."""

    def __init__(self):
        self._avail: dict[tuple, dict[str, int]] = {}

    def seed(self, driver: str, pool: str, counter_sets: list[dict]):
        for cs in counter_sets or []:
            key = (driver, pool, cs.get("name", ""))
            if key in self._avail:
                continue
            self._avail[key] = {
                name: Quantity.parse(val.get("value", "0")).milli
                for name, val in (cs.get("counters") or {}).items()
            }

    def _iter_demand(self, driver, pool, consumes):
        for block in consumes or []:
            key = (driver, pool, block.get("counterSet", ""))
            for name, val in (block.get("counters") or {}).items():
                yield key, name, Quantity.parse(
                    val.get("value", "0")).milli

    def fits(self, driver: str, pool: str, consumes: list[dict]) -> bool:
        for key, name, milli in self._iter_demand(driver, pool, consumes):
            have = self._avail.get(key, {}).get(name)
            if have is None or have < milli:
                return False
        return True

    def debit(self, driver: str, pool: str, consumes: list[dict]):
        for key, name, milli in self._iter_demand(driver, pool, consumes):
            if key in self._avail and name in self._avail[key]:
                self._avail[key][name] -= milli

    def credit(self, driver: str, pool: str, consumes: list[dict]):
        """Undo a debit (the backtracking allocator un-picks devices)."""
        for key, name, milli in self._iter_demand(driver, pool, consumes):
            if key in self._avail and name in self._avail[key]:
                self._avail[key][name] += milli


class Candidate:
    __slots__ = ("driver", "pool", "node", "device", "blocking_taints",
                 "slots", "is_partition", "power_watts",
                 "headroom_penalty")

    def __init__(self, driver, pool, node, device):
        self.driver = driver
        self.pool = pool
        self.node = node
        self.device = device
        # Pre-extracted at snapshot build: the taints that can block
        # allocation, so the per-claim check touches a (usually empty)
        # list instead of re-walking the device dict.
        self.blocking_taints = [
            t for t in device.get("taints") or []
            if t.get("effect") in ("NoSchedule", "NoExecute")
        ]
        attrs = device.get("attributes") or {}
        # Shared-device tenant slots (pkg/partition oversubscription):
        # an ``oversubscribeSlots`` int attribute > 1 lets up to that
        # many claims hold the device concurrently; everything else is
        # exclusive (1). The device's consumesCounters are published
        # PER SLOT, so the counter ledger stays exact.
        entry = attrs.get("oversubscribeSlots")
        slots = entry.get("int", 1) if isinstance(entry, dict) else 1
        try:
            self.slots = max(int(slots), 1)
        except (TypeError, ValueError):
            self.slots = 1
        part = attrs.get("partition")
        self.is_partition = bool(
            isinstance(part, dict) and part.get("bool"))
        # Expected power draw (watts) this device debits from its
        # node's power budget when allocated: the published rating,
        # else the live telemetry attribute, else (for whole devices
        # only -- a partition shares its parent chip's power, which
        # the chip-level attributes already account for) the
        # TPU_DRA_CHIP_POWER_W default. 0 = debits nothing.
        self.power_watts = _attr_int(attrs, ATTR_POWER_RATED)
        if self.power_watts <= 0:
            self.power_watts = _attr_int(attrs, ATTR_POWER_TELEMETRY)
        if self.power_watts <= 0 and not self.is_partition:
            self.power_watts = chip_power_default_env()
        # Telemetry-derived placement penalty (pkg/topology/score):
        # >0 on chips in an active anomaly episode or out of power/
        # thermal headroom -- the scheduler's candidate orderings sort
        # these last (pure preference, never exclusion). Precomputed
        # here so the per-claim fit touches an int, not taint lists.
        self.headroom_penalty = device_headroom_penalty(device)

    @property
    def name(self):
        return self.device["name"]

    @property
    def key(self):
        return (self.driver, self.pool, self.name)


def pool_key_of(slice_obj: dict) -> tuple[str, str]:
    """(driver, pool name) for one ResourceSlice."""
    spec = slice_obj.get("spec", {})
    return (spec.get("driver", ""),
            spec.get("pool", {}).get("name", ""))


class PoolSnapshot:
    """The allocation-relevant projection of ONE (driver, pool)'s
    ResourceSlices: newest-generation candidates with a per-node
    split, KEP-4815 counter seeds, the pool-scoped static-CEL memo,
    and the slice signature triples the incremental rebuild diffs on.

    Immutable after construction and shared BY IDENTITY across
    snapshot generations: a slice event rebuilds only the affected
    pool's PoolSnapshot, every untouched pool -- candidates, CEL
    memos, everything -- rides into the next merged view untouched
    (the mutation-isolation property tests/test_sched_delta.py pins).
    Mutating these internals outside pkg/schedcache.py is lint-fenced
    (TPUDRA009, pkg/analysis)."""

    __slots__ = ("driver", "pool", "generation", "slice_sigs",
                 "candidates", "by_node", "nodes", "counter_seeds",
                 "sel_cache", "node_power_caps")

    def __init__(self, driver: str, pool: str, slices: list[dict],
                 default_node: str | None = None):
        self.driver = driver
        self.pool = pool
        # Name-sorted so the build is a pure function of the slice SET
        # -- event-ordered delta rebuilds and listing-ordered cold
        # rebuilds must produce byte-identical candidate sequences.
        ordered = sorted(slices, key=lambda s: s.get(
            "metadata", {}).get("name", ""))
        self.slice_sigs = tuple(
            (s.get("metadata", {}).get("name", ""),
             s.get("metadata", {}).get("resourceVersion", ""),
             s.get("spec", {}).get("pool", {}).get("generation", 0))
            for s in ordered)
        gen = 0
        for s in ordered:
            gen = max(gen, s.get("spec", {}).get("pool", {}).get(
                "generation", 0))
        self.generation = gen
        self.candidates: list[Candidate] = []
        self.counter_seeds: list[list[dict]] = []
        for s in ordered:
            spec = s.get("spec", {})
            if spec.get("pool", {}).get("generation", 0) != gen:
                continue  # stale generation: invisible to allocation
            node = spec.get("nodeName") or default_node or ""
            if spec.get("sharedCounters"):
                self.counter_seeds.append(spec["sharedCounters"])
            for dev in spec.get("devices", []):
                self.candidates.append(
                    Candidate(driver, pool, node, dev))
        self.by_node: dict[str, list[Candidate]] = {}
        for c in self.candidates:
            self.by_node.setdefault(c.node, []).append(c)
        self.nodes = frozenset(self.by_node)
        # Per-node power cap (watts) from the ``powerCapWatts``
        # attribute the node plugin stamps on its devices (the NODE
        # cap, stamped identically on each -- max() tolerates a
        # mid-upgrade mix): the seed of the per-host power budget.
        self.node_power_caps: dict[str, int] = {}
        for c in self.candidates:
            cap = _attr_int(c.device.get("attributes") or {},
                            ATTR_POWER_CAP)
            if cap > 0:
                self.node_power_caps[c.node] = max(
                    self.node_power_caps.get(c.node, 0), cap)
        # (expression, device name) -> bool; pool-scoped so it shares
        # the PoolSnapshot's lifetime exactly.
        self.sel_cache: dict[tuple[str, str], bool] = {}


class InventorySnapshot:
    """The merged allocation view over per-pool sub-snapshots
    (:class:`PoolSnapshot`), built once per slice change:

    - ``candidates`` / ``by_key`` / ``by_node``: newest-generation
      devices, indexed for the per-node fit.
    - counter seeds for a fresh :class:`CounterLedger`.
    - ``cel_match``: memoized static-selector evaluation -- one CEL
      run per (expression, device) for the owning POOL sub-snapshot's
      lifetime (which spans merged-view generations for untouched
      pools), not per claim per pass.
    - ``order_cache``: the topology scorer's candidate-ordering memos,
      keyed ``(driver, pool, names, want)`` -- pure functions of one
      pool's inventory, so delta rebuilds carry untouched pools'
      entries forward and drop exactly the changed pools'.

    Two build paths share the result shape: the cold ``__init__``
    (O(slices), direct mode / first build) and :meth:`delta`
    (O(changes): only the dirtied pools re-project; untouched
    :class:`PoolSnapshot` objects merge by IDENTITY and the top-level
    indexes are pointer-copied, never content-copied)."""

    @staticmethod
    def signature_of(slices: list[dict]) -> tuple:
        return tuple(sorted(
            (s.get("metadata", {}).get("name", ""),
             s.get("metadata", {}).get("resourceVersion", ""),
             s.get("spec", {}).get("pool", {}).get("generation", 0))
            for s in slices
        ))

    def __init__(self, slices: list[dict], signature: tuple | None = None,
                 default_node: str | None = None):
        self._signature = (self.signature_of(slices)
                           if signature is None else signature)
        self.default_node = default_node
        # Build seq / delta lineage: stamped by the owning ClusterView
        # so consumers (AllocationState.retarget) can learn WHICH pools
        # changed between two snapshots they hold.
        self.build_seq: int | None = None
        self.delta_pools: frozenset = frozenset()
        buckets: dict[tuple[str, str], list[dict]] = {}
        for s in slices:
            buckets.setdefault(pool_key_of(s), []).append(s)
        self.pools: dict[tuple[str, str], PoolSnapshot] = {
            pk: PoolSnapshot(pk[0], pk[1], group, default_node)
            for pk, group in buckets.items()
        }
        self.order_cache: dict[tuple, list[str] | None] = {}
        self._sel_cache: dict[tuple, bool] = {}
        self._candidates: list[Candidate] | None = None
        self._index_pools()

    def _index_pools(self) -> None:
        """(Re)build the merged indexes from scratch for a cold build:
        deterministic pool-key order so cold and delta builds agree."""
        self.pool_generations = {
            pk: p.generation for pk, p in self.pools.items()}
        self.by_key: dict[tuple, Candidate] = {}
        self.by_node: dict[str, list[Candidate]] = {}
        self._pools_of_node: dict[str, frozenset] = {}
        pools_of_node: dict[str, set] = {}
        for pk in sorted(self.pools):
            for c in self.pools[pk].candidates:
                self.by_key[c.key] = c
            for node in self.pools[pk].nodes:
                pools_of_node.setdefault(node, set()).add(pk)
        for node, pks in pools_of_node.items():
            self._pools_of_node[node] = frozenset(pks)
            self.by_node[node] = self._merge_node(node, pks)

    def _merge_node(self, node: str, pks) -> list[Candidate]:
        """One node's merged candidate list. A single-pool node (the
        common node-local-pool case) SHARES the pool's list by
        identity -- delta rebuilds then copy only pointers."""
        if len(pks) == 1:
            (only,) = pks
            return self.pools[only].by_node[node]
        return [c for pk in sorted(pks)
                for c in self.pools[pk].by_node.get(node, ())]

    @property
    def signature(self) -> tuple:
        """Sorted per-slice (name, resourceVersion, generation)
        triples. Delta builds compute it LAZILY from the per-pool
        signature shards -- the event-mode fast path never needs it."""
        if self._signature is None:
            self._signature = tuple(sorted(
                t for p in self.pools.values() for t in p.slice_sigs))
        return self._signature

    @property
    def candidates(self) -> list[Candidate]:
        if self._candidates is None:
            self._candidates = [
                c for pk in sorted(self.pools)
                for c in self.pools[pk].candidates]
        return self._candidates

    @classmethod
    def delta(cls, prev: "InventorySnapshot",
              dirty_buckets: dict[tuple[str, str], list[dict]],
              default_node: str | None = None,
              on_pool_build: Callable | None = None
              ) -> "InventorySnapshot":
        """O(changes) rebuild: re-project ONLY the pools named in
        ``dirty_buckets`` (pool key -> that pool's current slices;
        empty list = pool gone) and merge with every other pool of
        ``prev`` by identity. Pools whose slice signature turns out
        unchanged are dropped from the delta (spurious dirtying);
        if nothing really changed, ``prev`` itself is returned.

        The merged indexes are pointer-copies of ``prev``'s with only
        the changed pools' entries spliced -- untouched pools' sub-
        snapshots (candidates, CEL memos, order memos) are NEVER
        copied, which is what keeps maintenance sublinear in fleet
        size (bench.py --sched-scale delta gate)."""
        pools = dict(prev.pools)
        rebuilt: dict[tuple[str, str], PoolSnapshot | None] = {}
        for pk, slices in dirty_buckets.items():
            old = pools.get(pk)
            if not slices:
                if old is None:
                    continue  # never existed: nothing to drop
                pools.pop(pk)
                rebuilt[pk] = None
                continue
            t0 = time.monotonic()
            new = PoolSnapshot(pk[0], pk[1], slices, default_node)
            built_s = time.monotonic() - t0
            if old is not None and old.slice_sigs == new.slice_sigs:
                continue  # spuriously dirtied: content unchanged
            if on_pool_build is not None:
                on_pool_build(pk, built_s)
            pools[pk] = new
            rebuilt[pk] = new
        if not rebuilt:
            return prev
        changed = frozenset(rebuilt)
        snap = cls.__new__(cls)
        snap.default_node = default_node
        snap.pools = pools
        snap.build_seq = None
        snap.delta_pools = changed
        snap._signature = None  # lazy: merged from per-pool shards
        snap._candidates = None
        snap._sel_cache = {}
        # Untouched pools keep their topology-order memos; changed
        # pools' (and legacy-shaped keys') entries drop.
        snap.order_cache = {
            k: v for k, v in prev.order_cache.items()
            if isinstance(k, tuple) and len(k) >= 2
            and (k[0], k[1]) in pools and (k[0], k[1]) not in changed}
        snap.pool_generations = dict(prev.pool_generations)
        snap.by_key = dict(prev.by_key)
        snap.by_node = dict(prev.by_node)
        snap._pools_of_node = dict(prev._pools_of_node)
        affected_nodes: set[str] = set()
        for pk, new in rebuilt.items():
            old = prev.pools.get(pk)
            if old is not None:
                for c in old.candidates:
                    snap.by_key.pop(c.key, None)
                affected_nodes |= old.nodes
            if new is not None:
                for c in new.candidates:
                    snap.by_key[c.key] = c
                affected_nodes |= new.nodes
                snap.pool_generations[pk] = new.generation
            else:
                snap.pool_generations.pop(pk, None)
        for node in affected_nodes:
            pks = {pk for pk in prev._pools_of_node.get(node, ())
                   if pk not in changed}
            pks |= {pk for pk in changed
                    if pk in pools and node in pools[pk].nodes}
            if not pks:
                snap.by_node.pop(node, None)
                snap._pools_of_node.pop(node, None)
            else:
                snap._pools_of_node[node] = frozenset(pks)
                snap.by_node[node] = snap._merge_node(node, pks)
        return snap

    def make_ledger(self) -> CounterLedger:
        ledger = CounterLedger()
        for pk in sorted(self.pools):
            for sets in self.pools[pk].counter_seeds:
                ledger.seed(pk[0], pk[1], sets)
        return ledger

    def power_cap_of(self, node: str) -> int:
        """The node's power budget in watts (the published
        ``powerCapWatts`` attribute, else the scheduler-side
        TPU_DRA_POWER_CAP_W default); 0 = uncapped. Computed from the
        per-pool shards on demand so the delta path maintains no extra
        merged index."""
        cap = 0
        for pk in self._pools_of_node.get(node, ()):
            pool = self.pools.get(pk)
            if pool is not None:
                cap = max(cap, pool.node_power_caps.get(node, 0))
        return cap if cap > 0 else power_cap_env()

    def cel_match(self, expression: str, prog: CelProgram,
                  cand: Candidate) -> bool:
        pool = self.pools.get((cand.driver, cand.pool))
        cache = pool.sel_cache if pool is not None else self._sel_cache
        key = (expression, cand.name)
        hit = cache.get(key)
        if hit is None:
            try:
                hit = bool(prog.matches_device(cand.device, cand.driver))
            except Exception:  # noqa: BLE001 - CEL eval boundary
                hit = False
            cache[key] = hit
        return hit

    # -- topology order memo (the mutation-fenced accessor pair) --------------

    def order_memo_get(self, key: tuple):
        """Cached topology candidate ordering, or the ``_MISS``
        sentinel (a cached None is a real answer: 'no usable
        coordinates')."""
        return self.order_cache.get(key, _ORDER_MISS)

    def order_memo_put(self, key: tuple,
                       ordered: list[str] | None) -> None:
        """The ONLY sanctioned external mutation path into the order
        memo (TPUDRA009 fences direct subscript writes to schedcache
        internals outside this module)."""
        self.order_cache[key] = ordered


_ORDER_MISS = object()


class NodeLockManager:
    """Per-node allocation locks for the sharded scheduler: disjoint
    nodes commit in parallel, same-node contenders serialize, and a
    gang claim spanning several hosts takes its whole lock set in one
    ordered acquisition (sorted node names) so two gangs overlapping on
    any node can never deadlock. Sits ABOVE the scheduler registry lock
    and the allocation-state lock in the documented hierarchy
    (docs/architecture.md "Sharded allocation locking"); commit kube
    I/O is sanctioned under node locks only."""

    def __init__(self):
        self._locks: dict[str, threading.Lock] = {}
        self._mu = threading.Lock()

    def _lock_for(self, node: str) -> threading.Lock:
        with self._mu:
            lock = self._locks.get(node)
            if lock is None:
                lock = self._locks[node] = threading.Lock()
            return lock

    @contextmanager
    def hold(self, nodes):
        """Acquire the locks for ``nodes`` in sorted order (the
        deadlock-freedom invariant the interleaving explorer and lint
        rule TPUDRA001 check)."""
        ordered = sorted(set(nodes))
        held = []
        try:
            for node in ordered:
                lock = self._lock_for(node)
                lock.acquire()
                held.append(lock)
            yield
        finally:
            for lock in reversed(held):
                lock.release()


def claim_like(name: str, devices: list[tuple[str, str, str]],
               namespace: str = "default", uid: str = "") -> dict:
    """Build the minimal ResourceClaim-shaped dict AllocationState
    consumes: ``devices`` is a list of (driver, pool, device) keys --
    the same tuples ``_alloc_keys`` extracts. The canonical seam for
    model checkers and tests that drive observe/try_commit/forget
    without a full apiserver object."""
    return {
        "metadata": {"name": name, "namespace": namespace,
                     **({"uid": uid} if uid else {})},
        "status": {"allocation": {"devices": {"results": [
            {"driver": d, "pool": p, "device": dev}
            for d, p, dev in devices
        ]}}},
    }


class AllocationState:
    """Allocated-device keys + debited counter budgets, incrementally
    maintained from ResourceClaim allocations.

    ``observe`` is idempotent per claim (keyed by uid, falling back to
    namespace/name): replaying the same allocation -- e.g. the watch
    event for a patch the scheduler itself just wrote -- is a no-op,
    and a changed allocation releases the previous devices first.

    Thread safety (scheduler scale-out): every mutation happens under
    the internal ``_alloc_lock`` so informer event threads and N sync
    workers can share one state. ``try_commit`` is the atomic
    check-and-reserve the optimistic commit-then-observe protocol pins
    on: a fit computed against (possibly stale) reads either reserves
    its devices atomically or reports a conflict for a re-fit, so two
    workers can never double-allocate a device or over-spend a counter
    budget. ``node_load`` is maintained incrementally so the per-claim
    node ordering no longer scans the whole allocated set.
    """

    # Node-ordering memo staleness bound: the least-loaded-first node
    # order re-sorts after ceil(nodes / REORDER_NODES_PER_STEP) load
    # mutations (or any snapshot change) -- EXACT per-commit spreading
    # on small fleets (threshold 1 below 256 nodes, the historical
    # behavior), amortized at scale where the per-claim O(n log n)
    # sort was the top 10k-node allocation hotspot. Pure placement
    # PREFERENCE: a stale order can only pick a slightly-more-loaded
    # node first, never misallocate.
    REORDER_NODES_PER_STEP = 256

    def __init__(self, snapshot: InventorySnapshot):
        self.snapshot = snapshot
        self.ledger = snapshot.make_ledger()
        # Keys at FULL capacity -- the set the fit probes. Exclusive
        # devices fill at one allocation; shared (oversubscribed
        # partition) devices fill at ``Candidate.slots`` concurrent
        # holders, tracked in _counts.
        self.allocated: set[tuple] = set()
        self._counts: dict[tuple, int] = {}
        self.node_load: dict[str, int] = {}
        # Per-node power debits (watts) from held allocations: the
        # spent half of the power budget try_commit judges against
        # InventorySnapshot.power_cap_of. Mutated ONLY through
        # power_debit/power_credit (lint rule TPUDRA015).
        self.power_used: dict[str, int] = {}
        self._claims: dict[str, frozenset] = {}
        self._alloc_lock = threading.Lock()
        self._node_order: list[str] | None = None
        self._node_order_drift = 0

    def _slots_of(self, key: tuple) -> int:
        cand = self.snapshot.by_key.get(key)
        return cand.slots if cand is not None else 1

    @staticmethod
    def claim_id(claim: dict) -> str:
        md = claim.get("metadata", {})
        return md.get("uid") or f"{md.get('namespace', 'default')}/" \
                                f"{md.get('name', '')}"

    @staticmethod
    def _alloc_keys(claim: dict) -> frozenset:
        alloc = claim.get("status", {}).get("allocation") or {}
        return frozenset(
            (r.get("driver", ""), r.get("pool", ""), r.get("device", ""))
            for r in alloc.get("devices", {}).get("results", [])
        )

    def rebuild(self, claims: list[dict]) -> None:
        with self._alloc_lock:
            self.ledger = self.snapshot.make_ledger()
            self.allocated = set()
            self._counts = {}
            self.node_load = {}
            self.power_used = {}
            self._claims = {}
            self._node_order = None
            for claim in claims:
                self._observe_locked(claim)

    # -- power budget (mutations fenced by lint rule TPUDRA015) ---------------

    def power_debit(self, node: str, watts: int) -> None:
        """Debit one device's expected draw from its node's budget.
        Caller holds ``_alloc_lock`` (called from the apply/retarget
        paths only -- the TPUDRA015 fence keeps random call sites from
        un-balancing the budget)."""
        if watts > 0 and node:
            self.power_used[node] = self.power_used.get(node, 0) + watts

    def power_credit(self, node: str, watts: int) -> None:
        """Undo a debit (release half; same discipline as
        ``power_debit``)."""
        if watts > 0 and node:
            left = self.power_used.get(node, 0) - watts
            if left > 0:
                self.power_used[node] = left
            else:
                self.power_used.pop(node, None)

    def power_snapshot(self) -> dict[str, int]:
        """Consistent copy of per-node power debits (watts) for a
        lock-free fit; try_commit re-judges before anything becomes
        visible."""
        with self._alloc_lock:
            return dict(self.power_used)

    def retarget(self, snapshot: InventorySnapshot,
                 changed_pools) -> None:
        """Re-point this state at a DELTA-built snapshot: only the
        ``changed_pools`` (driver, pool) keys differ from the current
        snapshot, so the O(claims) rebuild collapses to re-deriving
        exactly those pools' ledger seeds, node-load contributions and
        at-capacity memberships from the held allocations. Untouched
        pools' Candidate objects are IDENTICAL between the two
        snapshots, so every other piece of state is already right.
        Equivalent to ``rebuild`` over the same claim set (pinned by
        tests/test_sched_delta.py)."""
        changed = set(changed_pools)
        with self._alloc_lock:
            old_snap = self.snapshot
            self.snapshot = snapshot
            self._node_order = None
            if not changed:
                return
            # Reseed the changed pools' counter budgets...
            for lkey in [k for k in self.ledger._avail
                         if (k[0], k[1]) in changed]:
                del self.ledger._avail[lkey]
            for pk in changed:
                pool = snapshot.pools.get(pk)
                if pool is not None:
                    for sets in pool.counter_seeds:
                        self.ledger.seed(pk[0], pk[1], sets)
            # ...then re-apply the held allocations that touch them.
            for key, count in self._counts.items():
                pk = (key[0], key[1])
                if pk not in changed:
                    continue
                old_cand = old_snap.by_key.get(key)
                new_cand = snapshot.by_key.get(key)
                if old_cand is not None:
                    left = self.node_load.get(old_cand.node, 0) - count
                    if left > 0:
                        self.node_load[old_cand.node] = left
                    else:
                        self.node_load.pop(old_cand.node, None)
                    # Power draw re-derives from the NEW candidate's
                    # attributes below: a telemetry/rating attribute
                    # change is exactly the event that dirtied this
                    # pool, so any debit/credit drift heals here.
                    self.power_credit(old_cand.node,
                                      old_cand.power_watts * count)
                if new_cand is not None:
                    consumes = new_cand.device.get("consumesCounters")
                    for _ in range(count):
                        self.ledger.debit(new_cand.driver, new_cand.pool,
                                          consumes)
                    self.node_load[new_cand.node] = \
                        self.node_load.get(new_cand.node, 0) + count
                    self.power_debit(new_cand.node,
                                     new_cand.power_watts * count)
                slots = new_cand.slots if new_cand is not None else 1
                if count >= slots:
                    self.allocated.add(key)
                else:
                    self.allocated.discard(key)

    def ordered_nodes(self) -> list[str]:
        """Every node with published candidates, least-loaded first
        (name tiebreak), memoized until ``max(1, nodes //
        REORDER_NODES_PER_STEP)`` load mutations accumulate or the
        snapshot changes. Callers must treat the returned list as
        read-only (it is shared across workers)."""
        with self._alloc_lock:
            order = self._node_order
            threshold = max(
                1, len(self.snapshot.by_node) //
                self.REORDER_NODES_PER_STEP)
            if order is None or self._node_order_drift >= threshold:
                load = self.node_load
                order = sorted(self.snapshot.by_node,
                               key=lambda n: (load.get(n, 0), n))
                self._node_order = order
                self._node_order_drift = 0
            return order

    def observe(self, claim: dict) -> bool:
        """Fold one claim's current allocation in. Returns True when
        the state changed."""
        with self._alloc_lock:
            return self._observe_locked(claim)

    def _observe_locked(self, claim: dict) -> bool:
        cid = self.claim_id(claim)
        keys = self._alloc_keys(claim)
        old = self._claims.get(cid, frozenset())
        if keys == old:
            return False
        self._release_locked(old)
        self._apply_locked(cid, keys)
        return True

    def _apply_locked(self, cid: str, keys: frozenset) -> None:
        for key in keys:
            count = self._counts.get(key, 0) + 1
            self._counts[key] = count
            if count >= self._slots_of(key):
                self.allocated.add(key)
            cand = self.snapshot.by_key.get(key)
            if cand is not None:
                self.ledger.debit(cand.driver, cand.pool,
                                  cand.device.get("consumesCounters"))
                self.node_load[cand.node] = \
                    self.node_load.get(cand.node, 0) + 1
                self.power_debit(cand.node, cand.power_watts)
                self._node_order_drift += 1
        if keys:
            self._claims[cid] = keys
        else:
            self._claims.pop(cid, None)

    def forget(self, claim: dict) -> bool:
        """Drop a deleted claim; its devices return to the free pool."""
        with self._alloc_lock:
            cid = self.claim_id(claim)
            old = self._claims.pop(cid, None)
            if not old:
                return False
            self._release_locked(old)
            return True

    def try_commit(self, claim: dict) -> bool:
        """Atomically reserve one claim's planned allocation: every
        device key must still have a free slot (exclusive devices: not
        allocated at all; shared partition devices: fewer than
        ``slots`` holders) and every counter budget must still fit,
        judged and applied under one lock. Returns False on conflict
        (the caller re-fits against fresh state); replaying a claim's
        own reservation returns True (idempotent). A reserve whose
        kube patch subsequently fails is undone via ``forget``, so a
        failed write never leaks a debit (commit-then-observe)."""
        cid = self.claim_id(claim)
        keys = self._alloc_keys(claim)
        with self._alloc_lock:
            prior = self._claims.get(cid)
            if prior == keys:
                return True  # idempotent replay of our own reservation
            if prior is not None:
                # The claim was freshly read as unallocated, so a prior
                # entry is stale (a deallocated claim's ghost from the
                # commit-log replay): release it and re-judge. The work
                # queue runs each key on at most one worker at a time
                # (its running-set -- true even with work stealing), so
                # this can never drop another worker's in-flight
                # reservation.
                self._release_locked(prior)
                self._claims.pop(cid, None)
            debited: list[Candidate] = []
            power_want: dict[str, int] = {}
            ok = True
            for key in keys:
                if key in self.allocated:
                    ok = False
                    break
                cand = self.snapshot.by_key.get(key)
                if cand is None:
                    continue
                consumes = cand.device.get("consumesCounters")
                if consumes and not self.ledger.fits(
                        cand.driver, cand.pool, consumes):
                    ok = False
                    break
                # Power budget (2501.17752): the claim's summed draw
                # per node must fit under the node cap on top of what
                # is already debited -- judged cumulatively so a
                # multi-device claim can't pass N individual checks
                # that together blow the rack budget.
                if cand.power_watts > 0:
                    want = power_want.get(cand.node, 0) + \
                        cand.power_watts
                    cap = self.snapshot.power_cap_of(cand.node)
                    if cap > 0 and \
                            self.power_used.get(cand.node, 0) + want \
                            > cap:
                        ok = False
                        break
                    power_want[cand.node] = want
                # Debit as we go so multi-device claims can't pass N
                # individual fits that overspend one shared counter.
                self.ledger.debit(cand.driver, cand.pool, consumes)
                debited.append(cand)
            if not ok:
                for cand in debited:
                    self.ledger.credit(cand.driver, cand.pool,
                                       cand.device.get("consumesCounters"))
                return False
            for cand in debited:
                # _apply_locked re-debits; restore balance first.
                self.ledger.credit(cand.driver, cand.pool,
                                   cand.device.get("consumesCounters"))
            self._apply_locked(cid, keys)
            return True

    def ledger_snapshot(self) -> "CounterLedger":
        """Consistent copy of the counter ledger for a lock-free fit."""
        with self._alloc_lock:
            copy = CounterLedger()
            copy._avail = {k: dict(v) for k, v in self.ledger._avail.items()}
            return copy

    def load_view(self) -> dict[str, int]:
        """Consistent copy of the per-node allocated-device counts."""
        with self._alloc_lock:
            return dict(self.node_load)

    def slot_counts(self) -> dict[tuple, int]:
        """Consistent copy of per-device-key holder counts (shared
        partition devices count every co-tenant): the fleet
        aggregator's partition-slot-occupancy read."""
        with self._alloc_lock:
            return dict(self._counts)

    def _release_locked(self, keys: frozenset) -> None:
        for key in keys:
            count = self._counts.get(key, 0) - 1
            if count > 0:
                self._counts[key] = count
            else:
                self._counts.pop(key, None)
            if count < self._slots_of(key):
                self.allocated.discard(key)
            cand = self.snapshot.by_key.get(key)
            if cand is not None:
                self.ledger.credit(cand.driver, cand.pool,
                                   cand.device.get("consumesCounters"))
                self.power_credit(cand.node, cand.power_watts)
                self._node_order_drift += 1
                left = self.node_load.get(cand.node, 0) - 1
                if left > 0:
                    self.node_load[cand.node] = left
                else:
                    self.node_load.pop(cand.node, None)


# Objects (claims / pods) opt into a scheduling domain with this
# annotation; unannotated objects belong to the default domain.
DOMAIN_ANNOTATION = "resource.tpu.dra/domain"
# Cross-domain claim spillover (pkg/scheduler._maybe_spill): a claim
# pinned into an exhausted domain re-homes to a sibling domain instead
# of pending forever. The move annotates INTENT so operators (and the
# claim's eventual return path) can see it was displaced:
#   spilled-from: the ORIGINAL domain (first hop wins; stable across
#                 multi-hop spills),
#   spillover-hops: hop count, capped by TPU_DRA_SPILLOVER_MAX_HOPS,
#   spillover: "false" on a claim opts it out entirely.
SPILLOVER_ANNOTATION = "resource.tpu.dra/spillover"
SPILLED_FROM_ANNOTATION = "resource.tpu.dra/spilled-from"
SPILLOVER_HOPS_ANNOTATION = "resource.tpu.dra/spillover-hops"


class SchedulingDomain:
    """A partitioned scheduling domain (scheduler-per-pool sharding).

    Operators scale the control plane horizontally by running one
    scheduler instance per domain: each instance leader-elects on its
    own per-domain Lease (``lease_name``), restricts its inventory
    snapshot to the pools matching ``pools`` (exact names or
    ``fnmatch`` globs), and consumes only the dirty keys of claims /
    pods annotated ``resource.tpu.dra/domain: <name>``. Exactly one
    domain should be ``default=True`` (or one scheduler run with no
    domain at all): it owns unannotated objects plus the cluster-wide
    controllers (DaemonSet/Job sync, recovery), which must not run in
    every domain."""

    def __init__(self, name: str, pools=(), default: bool = False,
                 siblings: "list[SchedulingDomain] | None" = None):
        self.name = name
        self.pools = [p for p in pools if p]
        self.default = default
        # Spillover targets, in operator preference order: sibling
        # domains a pinned claim may re-home to when THIS domain's
        # pools are exhausted (pkg/scheduler._maybe_spill ranks them
        # by migration-cost score; order is the tiebreak prior).
        self.siblings: list[SchedulingDomain] = list(siblings or ())

    @property
    def lease_name(self) -> str:
        return f"tpu-dra-scheduler-{self.name}"

    def owns_pool(self, pool: str, node: str) -> bool:
        """POOL names only (node-local pools are named after their
        node, so that already covers the common case); matching node
        names too would let one slice silently satisfy two domains'
        globs and overlap their snapshots -- nothing validates domain
        disjointness, so the contract stays narrow."""
        if not self.pools:
            return True
        from fnmatch import fnmatch  # noqa: PLC0415

        return any(fnmatch(pool, pat) for pat in self.pools)

    def owns_object(self, obj: dict) -> bool:
        """Claim/pod routing: the domain annotation wins; unannotated
        objects belong to the default domain."""
        ann = (obj.get("metadata", {}).get("annotations") or {}).get(
            DOMAIN_ANNOTATION, "")
        if ann:
            return ann == self.name
        return self.default

    @classmethod
    def parse_siblings(cls, spec: str) -> "list[SchedulingDomain]":
        """``name=glob|glob;name2=glob`` -> sibling domains, in the
        operator's preference order. Malformed entries are skipped
        (a bad sibling must not take the scheduler down) -- including
        entries WITHOUT pool globs: an empty pool list means
        match-everything in owns_pool, which would count the whole
        cluster (the exhausted origin included) as the sibling's spill
        capacity."""
        siblings = []
        for entry in (spec or "").split(";"):
            entry = entry.strip()
            if not entry:
                continue
            name, _, globs = entry.partition("=")
            name = name.strip()
            pools = [g.strip() for g in globs.split("|") if g.strip()]
            if not name or not pools:
                logger.warning(
                    "skipping malformed spillover sibling entry %r "
                    "(want name=poolglob[|glob...])", entry)
                continue
            siblings.append(cls(name, pools=pools))
        return siblings

    @classmethod
    def from_env(cls, env=None) -> "SchedulingDomain | None":
        import os  # noqa: PLC0415

        env = env if env is not None else os.environ
        name = env.get("TPU_DRA_SCHED_DOMAIN", "")
        if not name:
            return None
        pools = [p.strip() for p in env.get(
            "TPU_DRA_SCHED_DOMAIN_POOLS", "").split(",") if p.strip()]
        default = env.get("TPU_DRA_SCHED_DOMAIN_DEFAULT", "") in (
            "1", "true", "True")
        siblings = cls.parse_siblings(env.get(
            "TPU_DRA_SCHED_DOMAIN_SIBLINGS", ""))
        return cls(name, pools=pools, default=default,
                   siblings=siblings)


# (group, version, resource, kind) for every resource the scheduler's
# sync paths read. TPUDRA009 (pkg/analysis) enforces that reads of
# these inside pkg/scheduler.py go through this view.
WATCHED_RESOURCES: tuple[tuple[str, str, str, str], ...] = (
    ("", "v1", "pods", "Pod"),
    ("", "v1", "nodes", "Node"),
    ("apps", "v1", "daemonsets", "DaemonSet"),
    ("batch", "v1", "jobs", "Job"),
    ("resource.k8s.io", "v1", "resourceclaims", "ResourceClaim"),
    ("resource.k8s.io", "v1", "resourceslices", "ResourceSlice"),
    ("resource.k8s.io", "v1", "deviceclasses", "DeviceClass"),
    ("resource.k8s.io", "v1", "resourceclaimtemplates",
     "ResourceClaimTemplate"),
    (CD_GROUP, CD_VERSION, "computedomains", "ComputeDomain"),
    # The serving autoscaler's desired-layout CRD (pkg/autoscale):
    # cluster-scoped, watched so re-plans reach the controller's
    # confirm stage (and pending tenants their retry) without polling.
    (CD_GROUP, CD_VERSION, "partitionsets", "PartitionSet"),
)


class ClusterView:
    """One read surface for scheduler sync paths.

    Direct mode (default): every accessor falls through to the kube
    client, preserving the one-shot ``sync_once()`` semantics unit
    tests rely on (KubeErrors propagate so fail-closed call sites keep
    failing closed). Event mode (``start()``): every watched resource
    gets an informer; accessors become pure cache reads and
    ``on_event(resource, ev_type, obj)`` fires per object change so
    the scheduler can maintain its dirty set.

    The inventory snapshot is cached in BOTH modes and rebuilt only
    when the slice signature changes (any slice create/update/delete,
    including pool-generation bumps)."""

    def __init__(self, kube, on_event: Callable | None = None,
                 on_relist: Callable[[str], None] | None = None,
                 resync_period: float = 300.0,
                 default_node: str | None = None,
                 pool_filter: Callable[[str, str], bool] | None = None,
                 on_snapshot_build: Callable[[float], None] | None = None,
                 on_snapshot_delta: Callable | None = None,
                 on_relist_backoff: Callable | None = None):
        self.kube = kube
        self._on_event = on_event
        self._on_relist = on_relist
        self._resync_period = resync_period
        self._default_node = default_node
        # Scheduling-domain partitioning: pool_filter(pool, node) False
        # makes a slice invisible to this scheduler's snapshot (the
        # per-pool domain sharding surface).
        self._pool_filter = pool_filter
        self._on_snapshot_build = on_snapshot_build
        # on_snapshot_delta(pool_label, seconds): one observation per
        # per-pool sub-snapshot rebuilt by the delta path
        # (tpu_dra_sched_snapshot_delta_seconds{pool}).
        self._on_snapshot_delta = on_snapshot_delta
        self._on_relist_backoff = on_relist_backoff
        self._informers: dict[str, Informer] = {}
        self._relist_coord = None
        self._snapshot: InventorySnapshot | None = None
        self._snapshot_lock = threading.Lock()
        # Bumped on EVERY slice event/invalidation; snapshot() rereads
        # until its listing is provably not older than the latest bump,
        # so a rebuild racing an event-thread generation bump can never
        # install (and serve to a commit) a stale-generation snapshot.
        # In event mode it also powers the O(1) snapshot fast path: a
        # cached snapshot built at the current generation is returned
        # without relisting or recomputing the signature.
        self._slice_gen = 0
        self._snapshot_gen = -1
        # Per-pool slice buckets, maintained INCREMENTALLY from slice
        # events (and re-anchored at every full build): the delta
        # rebuild reads exactly the dirty pools' slices from here --
        # zero listing, zero grouping of the other 9,999 pools.
        # _dirty_pools None = tracking lost (unusable event payload);
        # the next snapshot() falls back to a full build.
        self._slices_by_pool: dict[tuple[str, str], dict[str, dict]] = {}
        self._pool_of_slice: dict[str, tuple[str, str]] = {}
        self._dirty_pools: set[tuple[str, str]] | None = set()
        # Delta lineage: every installed snapshot gets a build seq and
        # the log records which pools each build changed (None = full
        # rebuild, unknown delta), so AllocationState holders can
        # retarget in O(changed pools) instead of rebuilding O(claims).
        self._build_seq = 0
        self._delta_log: deque = deque(maxlen=512)
        self._cd_windows: dict[str, list[str]] | None = None
        # Bumped on every ComputeDomain event (single informer watch
        # thread writes it): cd_windows() builds that raced an event
        # serve their listing but never install it.
        self._cd_gen = 0
        self._started = False

    # -- lifecycle ------------------------------------------------------------

    @property
    def event_driven(self) -> bool:
        return self._started

    def start(self) -> "ClusterView":
        if self._started:
            return self
        self._started = True
        # One relist coordinator for all nine informers: a restart
        # storm's simultaneous relists drain priority-ordered
        # (slices/claims first -- the allocation-critical state) under
        # a concurrency cap with per-resource jittered backoff, instead
        # of thundering-herding the apiserver. Startup itself lists in
        # the same priority order.
        self._relist_coord = RelistCoordinator(
            on_backoff=self._on_relist_backoff)
        ordered = sorted(WATCHED_RESOURCES,
                         key=lambda e: RELIST_PRIORITY.get(e[2], 9))
        for group, version, resource, kind in ordered:
            inf = Informer(self.kube, group, version, resource, kind=kind,
                           resync_period=self._resync_period,
                           on_relist=self._relist_hook(resource),
                           coordinator=self._relist_coord)
            # The LOCAL hook (slice buckets, CD windows) always runs;
            # the external on_event feed is optional.
            inf.add_event_hook(self._event_hook(resource))
            self._informers[resource] = inf
            inf.start()
        return self

    def stop(self) -> None:
        for inf in self._informers.values():
            inf.stop()

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        deadline = timeout
        return all(inf.wait_for_sync(deadline)
                   for inf in self._informers.values())

    def _event_hook(self, resource: str):
        def hook(ev_type: str, obj: dict, _r=resource):
            self._on_local_event(_r, ev_type, obj)
            if self._on_event is not None:
                self._on_event(_r, ev_type, obj)
        return hook

    def _relist_hook(self, resource: str):
        def hook(_r=resource):
            if self._on_relist is not None:
                self._on_relist(_r)
        return hook

    def _on_local_event(self, resource: str, ev_type: str,
                        obj: dict) -> None:
        if resource == "computedomains":
            self._on_cd_event(ev_type, obj)
        elif resource == "resourceslices":
            # The informer applied the change to its cache BEFORE
            # firing this hook, so any slice listing taken after this
            # bump observes it.
            self._on_slice_event(ev_type, obj)

    def _on_cd_event(self, ev_type: str, obj: dict) -> None:
        """SCOPED CD-window maintenance: one ComputeDomain's event
        updates exactly its own uid's entry, so the N-1 unrelated
        domains' window memos (and the pools their gangs target)
        survive -- the cache used to be nuked wholesale on any CD
        event, costing a relist per pending channel claim across every
        pool. ``_cd_gen`` always advances so a cd_windows() build
        racing this event discards its (possibly pre-event) result
        instead of caching it -- with per-uid maintenance there is no
        later global invalidation to heal a stale install."""
        self._cd_gen += 1
        cached = self._cd_windows
        if cached is None:
            return  # never built: the next cd_windows() builds fresh
        md = obj.get("metadata", {})
        uid = md.get("uid")
        if not uid:
            self._cd_windows = None  # unusable payload: full refresh
            return
        if ev_type == "DELETED":
            cached.pop(uid, None)
        else:
            ann = (md.get("annotations") or {}).get(
                PREFERRED_NODES_ANNOTATION, "")
            cached[uid] = [n for n in ann.split(",") if n]

    def _on_slice_event(self, ev_type: str, obj: dict) -> None:
        """Incremental per-pool slice bucket + dirty-pool upkeep (the
        delta rebuild's feed). Slices a domain's pool_filter excludes
        never dirty this view at all -- other domains' slice churn no
        longer costs this scheduler a rebuild."""
        md = obj.get("metadata", {})
        name = md.get("name", "")
        pk = pool_key_of(obj)
        visible = self._pool_filter is None or self._pool_filter(
            pk[1], obj.get("spec", {}).get("nodeName", ""))
        with self._snapshot_lock:
            if not name:
                # No identity to track: fall back to a full rebuild.
                self._slice_gen += 1
                self._dirty_pools = None
                return
            prev_pk = self._pool_of_slice.get(name)
            if prev_pk is None and not visible:
                return  # filtered and never seen: invisible churn
            self._slice_gen += 1
            if prev_pk is not None and prev_pk != pk:
                # Pool (or driver) rename: retire the old residency.
                bucket = self._slices_by_pool.get(prev_pk)
                if bucket is not None:
                    bucket.pop(name, None)
                    if not bucket:
                        self._slices_by_pool.pop(prev_pk, None)
                if self._dirty_pools is not None:
                    self._dirty_pools.add(prev_pk)
            if ev_type == "DELETED" or not visible:
                self._pool_of_slice.pop(name, None)
                bucket = self._slices_by_pool.get(pk)
                if bucket is not None:
                    bucket.pop(name, None)
                    if not bucket:
                        self._slices_by_pool.pop(pk, None)
            else:
                self._pool_of_slice[name] = pk
                self._slices_by_pool.setdefault(pk, {})[name] = obj
            if self._dirty_pools is not None and (
                    visible or prev_pk is not None):
                self._dirty_pools.add(pk)

    # -- per-pass bookkeeping -------------------------------------------------

    def begin_pass(self) -> None:
        """Reset per-pass memos that event mode invalidates by event
        (direct mode has no events, so a full pass starts fresh)."""
        if not self._started:
            self._cd_windows = None

    # -- reads ----------------------------------------------------------------

    def _list(self, group: str, version: str, resource: str) -> list[dict]:
        inf = self._informers.get(resource)
        if inf is not None:
            return inf.list()
        return self.kube.list(group, version, resource)

    def pods(self) -> list[dict]:
        return self._list("", "v1", "pods")

    def nodes(self) -> list[dict]:
        return self._list("", "v1", "nodes")

    def daemonsets(self) -> list[dict]:
        return self._list("apps", "v1", "daemonsets")

    def jobs(self) -> list[dict]:
        return self._list("batch", "v1", "jobs")

    def claims(self) -> list[dict]:
        return self._list(*RESOURCE, "resourceclaims")

    def slices(self) -> list[dict]:
        return self._list(*RESOURCE, "resourceslices")

    def device_classes(self) -> list[dict]:
        return self._list(*RESOURCE, "deviceclasses")

    def partition_sets(self) -> list[dict]:
        return self._list(CD_GROUP, CD_VERSION, "partitionsets")

    def get_pod(self, name: str, namespace: str = "default") -> dict:
        inf = self._informers.get("pods")
        if inf is not None:
            obj = inf.get(name, namespace)
            if obj is None:
                raise NotFoundError(f"pods/{name}")
            return obj
        return self.kube.get("", "v1", "pods", name, namespace=namespace)

    def get_claim(self, name: str, namespace: str = "default") -> dict:
        inf = self._informers.get("resourceclaims")
        if inf is not None:
            obj = inf.get(name, namespace)
            if obj is None:
                raise NotFoundError(f"resourceclaims/{name}")
            return obj
        return self.kube.get(*RESOURCE, "resourceclaims", name,
                             namespace=namespace)

    def get_template(self, name: str, namespace: str = "default") -> dict:
        inf = self._informers.get("resourceclaimtemplates")
        if inf is not None:
            obj = inf.get(name, namespace)
            if obj is None:
                raise NotFoundError(f"resourceclaimtemplates/{name}")
            return obj
        return self.kube.get(*RESOURCE, "resourceclaimtemplates", name,
                             namespace=namespace)

    # -- indexed snapshot -----------------------------------------------------

    def _filtered_slices(self) -> list[dict]:
        slices = self.slices()
        if self._pool_filter is None:
            return slices
        return [
            s for s in slices
            if self._pool_filter(
                s.get("spec", {}).get("pool", {}).get("name", ""),
                s.get("spec", {}).get("nodeName", ""))
        ]

    # Bounded retries for the list-vs-event race below: a cluster
    # churning slices faster than we can list is pathological; after
    # this many laps the freshest listing we have wins (still at least
    # as new as every bump observed before the first lap).
    _SNAPSHOT_RACE_RETRIES = 10

    def snapshot(self) -> InventorySnapshot:
        """The current inventory snapshot, rebuilt only when any slice
        changed (tracked via (name, resourceVersion, generation)).

        Rebuilds are race-checked against ``_slice_gen``: a worker
        whose listing predates a concurrent slice event (generation
        bump) re-lists instead of installing -- and handing a commit --
        a stale-generation snapshot that could clobber a newer one.

        Event mode gets an O(1) fast path off the same counter: slice
        events are the only thing that can change the listing, so a
        snapshot built at the current generation is returned without
        relisting or recomputing the O(slices) signature -- at 1000
        nodes that check used to dominate every allocation batch.

        Between the fast path and the full rebuild sits the DELTA
        path: with per-pool dirty tracking intact, only the dirtied
        pools' sub-snapshots rebuild and merge into the served view
        (O(changes), the 10k-node maintenance contract)."""
        if self._started:
            with self._snapshot_lock:
                if self._snapshot is not None and \
                        self._snapshot_gen == self._slice_gen:
                    return self._snapshot
                if self._snapshot is not None and \
                        self._snapshot_gen >= 0 and \
                        self._dirty_pools is not None:
                    return self._snapshot_delta_locked()
        for _ in range(self._SNAPSHOT_RACE_RETRIES):
            with self._snapshot_lock:
                gen0 = self._slice_gen
            slices = self._filtered_slices()
            sig = InventorySnapshot.signature_of(slices)
            with self._snapshot_lock:
                if self._snapshot is not None and \
                        self._snapshot.signature == sig:
                    # The listing provably covers every event up to
                    # gen0 (read before the list); never stamp newer.
                    self._snapshot_gen = max(self._snapshot_gen, gen0)
                    return self._snapshot
                if self._slice_gen != gen0:
                    continue  # raced a slice event: our listing may be stale
                t0 = time.monotonic()
                with tracing.span("sched.snapshot_build",
                                  attrs={"slices": len(slices)}):
                    self._snapshot = InventorySnapshot(
                        slices, signature=sig,
                        default_node=self._default_node)
                self._install_full_locked(self._snapshot, slices)
                self._snapshot_gen = gen0
                snap = self._snapshot
            if self._on_snapshot_build is not None:
                self._on_snapshot_build(time.monotonic() - t0)
            return snap
        # Persistent churn: accept the freshest listing we can get
        # (and force the next call to re-verify).
        slices = self._filtered_slices()
        sig = InventorySnapshot.signature_of(slices)
        with self._snapshot_lock:
            if self._snapshot is None or self._snapshot.signature != sig:
                self._snapshot = InventorySnapshot(
                    slices, signature=sig, default_node=self._default_node)
                self._install_full_locked(self._snapshot, slices)
            self._snapshot_gen = -1
            return self._snapshot

    def _install_full_locked(self, snap: InventorySnapshot,
                             slices: list[dict]) -> None:
        """Bookkeeping for a freshly built FULL snapshot (caller holds
        the lock and has verified the listing's generation): stamp the
        build seq, log it as an everything-may-have-changed build, and
        re-anchor the per-pool buckets + dirty tracking off the
        authoritative listing."""
        self._build_seq += 1
        snap.build_seq = self._build_seq
        self._delta_log.append((self._build_seq, None))
        if not self._started:
            return
        self._slices_by_pool = {}
        self._pool_of_slice = {}
        for s in slices:
            name = s.get("metadata", {}).get("name", "")
            if not name:
                continue
            pk = pool_key_of(s)
            self._pool_of_slice[name] = pk
            self._slices_by_pool.setdefault(pk, {})[name] = s
        self._dirty_pools = set()

    def _snapshot_delta_locked(self) -> InventorySnapshot:
        """Delta rebuild under the snapshot lock: O(dirty pools), the
        event threads that would mutate the buckets are excluded by
        the same lock. Spuriously dirtied pools (content unchanged)
        fall out inside InventorySnapshot.delta; a no-op delta keeps
        the previous snapshot object (and its identity-based
        consumers) entirely."""
        gen0 = self._slice_gen
        dirty = self._dirty_pools
        self._dirty_pools = set()
        buckets = {
            pk: list(self._slices_by_pool.get(pk, {}).values())
            for pk in dirty
        }
        new = InventorySnapshot.delta(
            self._snapshot, buckets, default_node=self._default_node,
            on_pool_build=self._pool_build_hook)
        if new is not self._snapshot:
            self._build_seq += 1
            new.build_seq = self._build_seq
            self._delta_log.append((self._build_seq, new.delta_pools))
            self._snapshot = new
        self._snapshot_gen = gen0
        return self._snapshot

    def _pool_build_hook(self, pk: tuple[str, str],
                         seconds: float) -> None:
        if self._on_snapshot_delta is not None:
            try:
                self._on_snapshot_delta(f"{pk[0]}/{pk[1]}", seconds)
            except Exception:  # noqa: BLE001 - metrics hook
                logger.exception("snapshot delta hook failed")

    def changed_pools_between(self, old: InventorySnapshot | None,
                              new: InventorySnapshot | None
                              ) -> set | None:
        """The pool keys that changed between two snapshots this view
        installed, or None when that cannot be answered from the delta
        log (either snapshot unstamped, a full rebuild in the window,
        or the log aged past ``old``) -- the caller then falls back to
        a full state rebuild."""
        if old is new:
            return set()
        old_seq = getattr(old, "build_seq", None) if old else None
        new_seq = getattr(new, "build_seq", None) if new else None
        if old_seq is None or new_seq is None or new_seq < old_seq:
            return None
        out: set = set()
        with self._snapshot_lock:
            if self._delta_log and self._delta_log[0][0] > old_seq + 1:
                return None  # log no longer covers the window
            for seq, pools in self._delta_log:
                if old_seq < seq <= new_seq:
                    if pools is None:
                        return None  # a full rebuild: unknown delta
                    out |= pools
        return out

    def invalidate_snapshot(self) -> None:
        with self._snapshot_lock:
            self._slice_gen += 1
            self._snapshot = None

    # -- ComputeDomain windows ------------------------------------------------

    def cd_windows(self) -> dict[str, list[str]]:
        """uid -> preferred-node window for every ComputeDomain.
        Cached until a CD event (event mode) / the next pass (direct
        mode); a transient list failure caches the empty answer so N
        pending channel claims never mean N failing lists."""
        cached = self._cd_windows
        if cached is not None:
            return cached
        gen0 = self._cd_gen
        try:
            cds = self._list(CD_GROUP, CD_VERSION, "computedomains")
        except KubeError:
            if self._cd_gen == gen0:
                self._cd_windows = {}
            return {}
        windows: dict[str, list[str]] = {}
        for cd in cds:
            md = cd.get("metadata", {})
            uid = md.get("uid")
            ann = (md.get("annotations") or {}).get(
                PREFERRED_NODES_ANNOTATION, "")
            if uid:
                windows[uid] = [n for n in ann.split(",") if n]
        if self._cd_gen == gen0:
            # No event raced the build: safe to install. A raced
            # build serves its listing uncached; the next call
            # re-lists and sees the event's effect (per-uid
            # maintenance has no later global heal, so a stale
            # install would live forever).
            self._cd_windows = windows
        return windows
