"""Indexed allocation snapshots + informer-backed cluster view.

The scheduler stand-in used to re-derive the world on every 0.25s pass:
re-list every watched resource, rebuild every candidate device list,
re-evaluate every CEL selector per claim. This module is the
incremental-state backbone that replaces that:

- ``InventorySnapshot``: the device inventory (candidates, per-node
  index, KEP-4815 counter seeds, static CEL selector evaluations, the
  topology scorer's ordering memos) built ONCE per ResourceSlice
  change and shared across claims and sync passes. The snapshot
  signature covers every slice's (name, resourceVersion, pool
  generation): any slice write -- including a pool-generation bump --
  invalidates it.
- ``AllocationState``: the allocated-device set and the debited
  counter ledger, maintained INCREMENTALLY from ResourceClaim events
  (observe/forget) instead of being rebuilt per claim per pass.
- ``ClusterView``: one read surface for the scheduler's sync paths.
  Event-driven mode backs it with per-resource informers (list+watch
  caches, pkg/informer.py) so a sync pass performs zero kube reads;
  direct mode (unit tests, one-shot sync) falls through to the kube
  client. Scheduler sync code must read through this view -- lint rule
  TPUDRA009 (pkg/analysis) forbids raw ``kube.list`` of watched
  resources inside pkg/scheduler.py.

Reference: controller-runtime's informer-indexed reconcilers and the
structured-parameters DRA plugin's allocator snapshot (see PAPERS.md);
the reference driver consumes CRs exclusively through informer caches.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable

from .cel import CelProgram, Quantity, compile_expression
from .informer import Informer
from .kubeclient import KubeError, NotFoundError

logger = logging.getLogger(__name__)

RESOURCE = ("resource.k8s.io", "v1")

# ComputeDomain CRD coordinates (kept literal: importing the
# computedomain package here would cycle through the plugin stack).
CD_GROUP = "resource.tpu.dra"
CD_VERSION = "v1beta1"
PREFERRED_NODES_ANNOTATION = "resource.tpu.dra/preferredNodes"


def tolerates(taint: dict, tolerations: list[dict]) -> bool:
    for tol in tolerations or []:
        if tol.get("effect") and tol["effect"] != taint.get("effect"):
            continue
        op = tol.get("operator", "Equal")
        if op == "Exists":
            if not tol.get("key") or tol["key"] == taint.get("key"):
                return True
        elif tol.get("key") == taint.get("key") and \
                tol.get("value", "") == taint.get("value", ""):
            return True
    return False


class CompiledSelectors:
    """Expression -> CelProgram cache; a selector that fails to compile
    permanently matches nothing (and is logged once), like a CEL
    compile error surfaced in the scheduler.

    The cache is shared process-wide (class-level, lock-guarded) and
    keyed by source text: a scheduler instantiated per sync pass still
    reuses every previously compiled selector. cel.compile_expression
    additionally memoizes the parsed AST, so even a fresh cache entry
    skips the lex+parse for text seen anywhere else in the process."""

    _shared: dict[str, CelProgram | None] = {}
    _shared_lock = threading.Lock()
    _MAX = 4096  # selectors are operator-authored; this is a leak bound

    def __init__(self):
        self._cache = self._shared

    def get(self, expression: str) -> CelProgram | None:
        with self._shared_lock:
            if expression in self._cache:
                return self._cache[expression]
        try:
            prog = compile_expression(expression)
        except Exception as e:  # noqa: BLE001 - compile boundary
            logger.error("selector does not compile (%s): %s",
                         e, expression)
            prog = None
        with self._shared_lock:
            if len(self._cache) >= self._MAX:
                self._cache.clear()
            self._cache[expression] = prog
        return prog


class CounterLedger:
    """Available KEP-4815 counters per (driver, pool, counterSet),
    seeded from sharedCounters and debited by consumesCounters."""

    def __init__(self):
        self._avail: dict[tuple, dict[str, int]] = {}

    def seed(self, driver: str, pool: str, counter_sets: list[dict]):
        for cs in counter_sets or []:
            key = (driver, pool, cs.get("name", ""))
            if key in self._avail:
                continue
            self._avail[key] = {
                name: Quantity.parse(val.get("value", "0")).milli
                for name, val in (cs.get("counters") or {}).items()
            }

    def _iter_demand(self, driver, pool, consumes):
        for block in consumes or []:
            key = (driver, pool, block.get("counterSet", ""))
            for name, val in (block.get("counters") or {}).items():
                yield key, name, Quantity.parse(
                    val.get("value", "0")).milli

    def fits(self, driver: str, pool: str, consumes: list[dict]) -> bool:
        for key, name, milli in self._iter_demand(driver, pool, consumes):
            have = self._avail.get(key, {}).get(name)
            if have is None or have < milli:
                return False
        return True

    def debit(self, driver: str, pool: str, consumes: list[dict]):
        for key, name, milli in self._iter_demand(driver, pool, consumes):
            if key in self._avail and name in self._avail[key]:
                self._avail[key][name] -= milli

    def credit(self, driver: str, pool: str, consumes: list[dict]):
        """Undo a debit (the backtracking allocator un-picks devices)."""
        for key, name, milli in self._iter_demand(driver, pool, consumes):
            if key in self._avail and name in self._avail[key]:
                self._avail[key][name] += milli


class Candidate:
    __slots__ = ("driver", "pool", "node", "device", "blocking_taints")

    def __init__(self, driver, pool, node, device):
        self.driver = driver
        self.pool = pool
        self.node = node
        self.device = device
        # Pre-extracted at snapshot build: the taints that can block
        # allocation, so the per-claim check touches a (usually empty)
        # list instead of re-walking the device dict.
        self.blocking_taints = [
            t for t in device.get("taints") or []
            if t.get("effect") in ("NoSchedule", "NoExecute")
        ]

    @property
    def name(self):
        return self.device["name"]

    @property
    def key(self):
        return (self.driver, self.pool, self.name)


class InventorySnapshot:
    """The allocation-relevant projection of the published
    ResourceSlices, built once per slice change:

    - ``candidates`` / ``by_key`` / ``by_node``: newest-generation
      devices, indexed for the per-node fit.
    - counter seeds for a fresh :class:`CounterLedger`.
    - ``cel_match``: memoized static-selector evaluation -- one CEL
      run per (expression, device) for the snapshot's LIFETIME, not
      per claim per pass.
    - ``order_cache``: the topology scorer's candidate-ordering memos
      (moved here from the scheduler's per-pass cache; they are pure
      functions of the inventory, so they live exactly as long as it
      does and invalidate on any slice write / generation bump).
    """

    @staticmethod
    def signature_of(slices: list[dict]) -> tuple:
        return tuple(sorted(
            (s.get("metadata", {}).get("name", ""),
             s.get("metadata", {}).get("resourceVersion", ""),
             s.get("spec", {}).get("pool", {}).get("generation", 0))
            for s in slices
        ))

    def __init__(self, slices: list[dict], signature: tuple | None = None,
                 default_node: str | None = None):
        self.signature = (self.signature_of(slices)
                          if signature is None else signature)
        newest: dict[tuple, int] = {}
        for s in slices:
            spec = s.get("spec", {})
            pool = spec.get("pool", {})
            key = (spec.get("driver", ""), pool.get("name", ""))
            newest[key] = max(newest.get(key, 0),
                              pool.get("generation", 0))
        self.pool_generations = newest
        self.candidates: list[Candidate] = []
        self._counter_seeds: list[tuple[str, str, list[dict]]] = []
        for s in slices:
            spec = s.get("spec", {})
            pool = spec.get("pool", {})
            driver = spec.get("driver", "")
            pool_name = pool.get("name", "")
            if pool.get("generation", 0) != newest[(driver, pool_name)]:
                continue  # stale generation: invisible to allocation
            node = spec.get("nodeName") or default_node or ""
            if spec.get("sharedCounters"):
                self._counter_seeds.append(
                    (driver, pool_name, spec["sharedCounters"]))
            for dev in spec.get("devices", []):
                self.candidates.append(
                    Candidate(driver, pool_name, node, dev))
        self.by_key: dict[tuple, Candidate] = {
            c.key: c for c in self.candidates}
        self.by_node: dict[str, list[Candidate]] = {}
        for c in self.candidates:
            self.by_node.setdefault(c.node, []).append(c)
        self.order_cache: dict[tuple, list[str] | None] = {}
        self._sel_cache: dict[tuple[str, tuple], bool] = {}

    def make_ledger(self) -> CounterLedger:
        ledger = CounterLedger()
        for driver, pool, sets in self._counter_seeds:
            ledger.seed(driver, pool, sets)
        return ledger

    def cel_match(self, expression: str, prog: CelProgram,
                  cand: Candidate) -> bool:
        key = (expression, cand.key)
        hit = self._sel_cache.get(key)
        if hit is None:
            try:
                hit = bool(prog.matches_device(cand.device, cand.driver))
            except Exception:  # noqa: BLE001 - CEL eval boundary
                hit = False
            self._sel_cache[key] = hit
        return hit


class AllocationState:
    """Allocated-device keys + debited counter budgets, incrementally
    maintained from ResourceClaim allocations.

    ``observe`` is idempotent per claim (keyed by uid, falling back to
    namespace/name): replaying the same allocation -- e.g. the watch
    event for a patch the scheduler itself just wrote -- is a no-op,
    and a changed allocation releases the previous devices first.
    """

    def __init__(self, snapshot: InventorySnapshot):
        self.snapshot = snapshot
        self.ledger = snapshot.make_ledger()
        self.allocated: set[tuple] = set()
        self._claims: dict[str, frozenset] = {}

    @staticmethod
    def claim_id(claim: dict) -> str:
        md = claim.get("metadata", {})
        return md.get("uid") or f"{md.get('namespace', 'default')}/" \
                                f"{md.get('name', '')}"

    @staticmethod
    def _alloc_keys(claim: dict) -> frozenset:
        alloc = claim.get("status", {}).get("allocation") or {}
        return frozenset(
            (r.get("driver", ""), r.get("pool", ""), r.get("device", ""))
            for r in alloc.get("devices", {}).get("results", [])
        )

    def rebuild(self, claims: list[dict]) -> None:
        self.ledger = self.snapshot.make_ledger()
        self.allocated = set()
        self._claims = {}
        for claim in claims:
            self.observe(claim)

    def observe(self, claim: dict) -> bool:
        """Fold one claim's current allocation in. Returns True when
        the state changed."""
        cid = self.claim_id(claim)
        keys = self._alloc_keys(claim)
        old = self._claims.get(cid, frozenset())
        if keys == old:
            return False
        self._release(old)
        for key in keys:
            self.allocated.add(key)
            cand = self.snapshot.by_key.get(key)
            if cand is not None:
                self.ledger.debit(cand.driver, cand.pool,
                                  cand.device.get("consumesCounters"))
        if keys:
            self._claims[cid] = keys
        else:
            self._claims.pop(cid, None)
        return True

    def forget(self, claim: dict) -> bool:
        """Drop a deleted claim; its devices return to the free pool."""
        cid = self.claim_id(claim)
        old = self._claims.pop(cid, None)
        if not old:
            return False
        self._release(old)
        return True

    def _release(self, keys: frozenset) -> None:
        for key in keys:
            self.allocated.discard(key)
            cand = self.snapshot.by_key.get(key)
            if cand is not None:
                self.ledger.credit(cand.driver, cand.pool,
                                   cand.device.get("consumesCounters"))


# (group, version, resource, kind) for every resource the scheduler's
# sync paths read. TPUDRA009 (pkg/analysis) enforces that reads of
# these inside pkg/scheduler.py go through this view.
WATCHED_RESOURCES: tuple[tuple[str, str, str, str], ...] = (
    ("", "v1", "pods", "Pod"),
    ("", "v1", "nodes", "Node"),
    ("apps", "v1", "daemonsets", "DaemonSet"),
    ("batch", "v1", "jobs", "Job"),
    ("resource.k8s.io", "v1", "resourceclaims", "ResourceClaim"),
    ("resource.k8s.io", "v1", "resourceslices", "ResourceSlice"),
    ("resource.k8s.io", "v1", "deviceclasses", "DeviceClass"),
    ("resource.k8s.io", "v1", "resourceclaimtemplates",
     "ResourceClaimTemplate"),
    (CD_GROUP, CD_VERSION, "computedomains", "ComputeDomain"),
)


class ClusterView:
    """One read surface for scheduler sync paths.

    Direct mode (default): every accessor falls through to the kube
    client, preserving the one-shot ``sync_once()`` semantics unit
    tests rely on (KubeErrors propagate so fail-closed call sites keep
    failing closed). Event mode (``start()``): every watched resource
    gets an informer; accessors become pure cache reads and
    ``on_event(resource, ev_type, obj)`` fires per object change so
    the scheduler can maintain its dirty set.

    The inventory snapshot is cached in BOTH modes and rebuilt only
    when the slice signature changes (any slice create/update/delete,
    including pool-generation bumps)."""

    def __init__(self, kube, on_event: Callable | None = None,
                 on_relist: Callable[[str], None] | None = None,
                 resync_period: float = 300.0,
                 default_node: str | None = None):
        self.kube = kube
        self._on_event = on_event
        self._on_relist = on_relist
        self._resync_period = resync_period
        self._default_node = default_node
        self._informers: dict[str, Informer] = {}
        self._snapshot: InventorySnapshot | None = None
        self._snapshot_lock = threading.Lock()
        self._cd_windows: dict[str, list[str]] | None = None
        self._started = False

    # -- lifecycle ------------------------------------------------------------

    @property
    def event_driven(self) -> bool:
        return self._started

    def start(self) -> "ClusterView":
        if self._started:
            return self
        self._started = True
        for group, version, resource, kind in WATCHED_RESOURCES:
            inf = Informer(self.kube, group, version, resource, kind=kind,
                           resync_period=self._resync_period,
                           on_relist=self._relist_hook(resource))
            if self._on_event is not None:
                inf.add_event_hook(self._event_hook(resource))
            self._informers[resource] = inf
            inf.start()
        return self

    def stop(self) -> None:
        for inf in self._informers.values():
            inf.stop()

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        deadline = timeout
        return all(inf.wait_for_sync(deadline)
                   for inf in self._informers.values())

    def _event_hook(self, resource: str):
        def hook(ev_type: str, obj: dict, _r=resource):
            self._on_local_event(_r, ev_type, obj)
            if self._on_event is not None:
                self._on_event(_r, ev_type, obj)
        return hook

    def _relist_hook(self, resource: str):
        def hook(_r=resource):
            if self._on_relist is not None:
                self._on_relist(_r)
        return hook

    def _on_local_event(self, resource: str, ev_type: str,
                        obj: dict) -> None:
        if resource == "computedomains":
            self._cd_windows = None

    # -- per-pass bookkeeping -------------------------------------------------

    def begin_pass(self) -> None:
        """Reset per-pass memos that event mode invalidates by event
        (direct mode has no events, so a full pass starts fresh)."""
        if not self._started:
            self._cd_windows = None

    # -- reads ----------------------------------------------------------------

    def _list(self, group: str, version: str, resource: str) -> list[dict]:
        inf = self._informers.get(resource)
        if inf is not None:
            return inf.list()
        return self.kube.list(group, version, resource)

    def pods(self) -> list[dict]:
        return self._list("", "v1", "pods")

    def nodes(self) -> list[dict]:
        return self._list("", "v1", "nodes")

    def daemonsets(self) -> list[dict]:
        return self._list("apps", "v1", "daemonsets")

    def jobs(self) -> list[dict]:
        return self._list("batch", "v1", "jobs")

    def claims(self) -> list[dict]:
        return self._list(*RESOURCE, "resourceclaims")

    def slices(self) -> list[dict]:
        return self._list(*RESOURCE, "resourceslices")

    def device_classes(self) -> list[dict]:
        return self._list(*RESOURCE, "deviceclasses")

    def get_claim(self, name: str, namespace: str = "default") -> dict:
        inf = self._informers.get("resourceclaims")
        if inf is not None:
            obj = inf.get(name, namespace)
            if obj is None:
                raise NotFoundError(f"resourceclaims/{name}")
            return obj
        return self.kube.get(*RESOURCE, "resourceclaims", name,
                             namespace=namespace)

    def get_template(self, name: str, namespace: str = "default") -> dict:
        inf = self._informers.get("resourceclaimtemplates")
        if inf is not None:
            obj = inf.get(name, namespace)
            if obj is None:
                raise NotFoundError(f"resourceclaimtemplates/{name}")
            return obj
        return self.kube.get(*RESOURCE, "resourceclaimtemplates", name,
                             namespace=namespace)

    # -- indexed snapshot -----------------------------------------------------

    def snapshot(self) -> InventorySnapshot:
        """The current inventory snapshot, rebuilt only when any slice
        changed (tracked via (name, resourceVersion, generation))."""
        slices = self.slices()
        sig = InventorySnapshot.signature_of(slices)
        with self._snapshot_lock:
            if self._snapshot is None or self._snapshot.signature != sig:
                self._snapshot = InventorySnapshot(
                    slices, signature=sig,
                    default_node=self._default_node)
            return self._snapshot

    def invalidate_snapshot(self) -> None:
        with self._snapshot_lock:
            self._snapshot = None

    # -- ComputeDomain windows ------------------------------------------------

    def cd_windows(self) -> dict[str, list[str]]:
        """uid -> preferred-node window for every ComputeDomain.
        Cached until a CD event (event mode) / the next pass (direct
        mode); a transient list failure caches the empty answer so N
        pending channel claims never mean N failing lists."""
        cached = self._cd_windows
        if cached is not None:
            return cached
        try:
            cds = self._list(CD_GROUP, CD_VERSION, "computedomains")
        except KubeError:
            self._cd_windows = {}
            return self._cd_windows
        windows: dict[str, list[str]] = {}
        for cd in cds:
            md = cd.get("metadata", {})
            uid = md.get("uid")
            ann = (md.get("annotations") or {}).get(
                PREFERRED_NODES_ANNOTATION, "")
            if uid:
                windows[uid] = [n for n in ann.split(",") if n]
        self._cd_windows = windows
        return windows
