"""Indexed allocation snapshots + informer-backed cluster view.

The scheduler stand-in used to re-derive the world on every 0.25s pass:
re-list every watched resource, rebuild every candidate device list,
re-evaluate every CEL selector per claim. This module is the
incremental-state backbone that replaces that:

- ``InventorySnapshot``: the device inventory (candidates, per-node
  index, KEP-4815 counter seeds, static CEL selector evaluations, the
  topology scorer's ordering memos) built ONCE per ResourceSlice
  change and shared across claims and sync passes. The snapshot
  signature covers every slice's (name, resourceVersion, pool
  generation): any slice write -- including a pool-generation bump --
  invalidates it.
- ``AllocationState``: the allocated-device set and the debited
  counter ledger, maintained INCREMENTALLY from ResourceClaim events
  (observe/forget) instead of being rebuilt per claim per pass.
- ``ClusterView``: one read surface for the scheduler's sync paths.
  Event-driven mode backs it with per-resource informers (list+watch
  caches, pkg/informer.py) so a sync pass performs zero kube reads;
  direct mode (unit tests, one-shot sync) falls through to the kube
  client. Scheduler sync code must read through this view -- lint rule
  TPUDRA009 (pkg/analysis) forbids raw ``kube.list`` of watched
  resources inside pkg/scheduler.py.

Reference: controller-runtime's informer-indexed reconcilers and the
structured-parameters DRA plugin's allocator snapshot (see PAPERS.md);
the reference driver consumes CRs exclusively through informer caches.
"""

from __future__ import annotations

import logging
import threading
import time
from contextlib import contextmanager
from typing import Callable

from . import tracing
from .cel import CelProgram, Quantity, compile_expression
from .informer import Informer
from .kubeclient import KubeError, NotFoundError

logger = logging.getLogger(__name__)

RESOURCE = ("resource.k8s.io", "v1")

# ComputeDomain CRD coordinates (kept literal: importing the
# computedomain package here would cycle through the plugin stack).
CD_GROUP = "resource.tpu.dra"
CD_VERSION = "v1beta1"
PREFERRED_NODES_ANNOTATION = "resource.tpu.dra/preferredNodes"


def tolerates(taint: dict, tolerations: list[dict]) -> bool:
    for tol in tolerations or []:
        if tol.get("effect") and tol["effect"] != taint.get("effect"):
            continue
        op = tol.get("operator", "Equal")
        if op == "Exists":
            if not tol.get("key") or tol["key"] == taint.get("key"):
                return True
        elif tol.get("key") == taint.get("key") and \
                tol.get("value", "") == taint.get("value", ""):
            return True
    return False


class CompiledSelectors:
    """Expression -> CelProgram cache; a selector that fails to compile
    permanently matches nothing (and is logged once), like a CEL
    compile error surfaced in the scheduler.

    The cache is shared process-wide (class-level, lock-guarded) and
    keyed by source text: a scheduler instantiated per sync pass still
    reuses every previously compiled selector. cel.compile_expression
    additionally memoizes the parsed AST, so even a fresh cache entry
    skips the lex+parse for text seen anywhere else in the process."""

    _shared: dict[str, CelProgram | None] = {}
    _shared_lock = threading.Lock()
    _MAX = 4096  # selectors are operator-authored; this is a leak bound

    def __init__(self):
        self._cache = self._shared

    def get(self, expression: str) -> CelProgram | None:
        with self._shared_lock:
            if expression in self._cache:
                return self._cache[expression]
        try:
            prog = compile_expression(expression)
        except Exception as e:  # noqa: BLE001 - compile boundary
            logger.error("selector does not compile (%s): %s",
                         e, expression)
            prog = None
        with self._shared_lock:
            if len(self._cache) >= self._MAX:
                self._cache.clear()
            self._cache[expression] = prog
        return prog


class CounterLedger:
    """Available KEP-4815 counters per (driver, pool, counterSet),
    seeded from sharedCounters and debited by consumesCounters."""

    def __init__(self):
        self._avail: dict[tuple, dict[str, int]] = {}

    def seed(self, driver: str, pool: str, counter_sets: list[dict]):
        for cs in counter_sets or []:
            key = (driver, pool, cs.get("name", ""))
            if key in self._avail:
                continue
            self._avail[key] = {
                name: Quantity.parse(val.get("value", "0")).milli
                for name, val in (cs.get("counters") or {}).items()
            }

    def _iter_demand(self, driver, pool, consumes):
        for block in consumes or []:
            key = (driver, pool, block.get("counterSet", ""))
            for name, val in (block.get("counters") or {}).items():
                yield key, name, Quantity.parse(
                    val.get("value", "0")).milli

    def fits(self, driver: str, pool: str, consumes: list[dict]) -> bool:
        for key, name, milli in self._iter_demand(driver, pool, consumes):
            have = self._avail.get(key, {}).get(name)
            if have is None or have < milli:
                return False
        return True

    def debit(self, driver: str, pool: str, consumes: list[dict]):
        for key, name, milli in self._iter_demand(driver, pool, consumes):
            if key in self._avail and name in self._avail[key]:
                self._avail[key][name] -= milli

    def credit(self, driver: str, pool: str, consumes: list[dict]):
        """Undo a debit (the backtracking allocator un-picks devices)."""
        for key, name, milli in self._iter_demand(driver, pool, consumes):
            if key in self._avail and name in self._avail[key]:
                self._avail[key][name] += milli


class Candidate:
    __slots__ = ("driver", "pool", "node", "device", "blocking_taints",
                 "slots")

    def __init__(self, driver, pool, node, device):
        self.driver = driver
        self.pool = pool
        self.node = node
        self.device = device
        # Pre-extracted at snapshot build: the taints that can block
        # allocation, so the per-claim check touches a (usually empty)
        # list instead of re-walking the device dict.
        self.blocking_taints = [
            t for t in device.get("taints") or []
            if t.get("effect") in ("NoSchedule", "NoExecute")
        ]
        # Shared-device tenant slots (pkg/partition oversubscription):
        # an ``oversubscribeSlots`` int attribute > 1 lets up to that
        # many claims hold the device concurrently; everything else is
        # exclusive (1). The device's consumesCounters are published
        # PER SLOT, so the counter ledger stays exact.
        entry = (device.get("attributes") or {}).get(
            "oversubscribeSlots")
        slots = entry.get("int", 1) if isinstance(entry, dict) else 1
        try:
            self.slots = max(int(slots), 1)
        except (TypeError, ValueError):
            self.slots = 1

    @property
    def name(self):
        return self.device["name"]

    @property
    def key(self):
        return (self.driver, self.pool, self.name)


class InventorySnapshot:
    """The allocation-relevant projection of the published
    ResourceSlices, built once per slice change:

    - ``candidates`` / ``by_key`` / ``by_node``: newest-generation
      devices, indexed for the per-node fit.
    - counter seeds for a fresh :class:`CounterLedger`.
    - ``cel_match``: memoized static-selector evaluation -- one CEL
      run per (expression, device) for the snapshot's LIFETIME, not
      per claim per pass.
    - ``order_cache``: the topology scorer's candidate-ordering memos
      (moved here from the scheduler's per-pass cache; they are pure
      functions of the inventory, so they live exactly as long as it
      does and invalidate on any slice write / generation bump).
    """

    @staticmethod
    def signature_of(slices: list[dict]) -> tuple:
        return tuple(sorted(
            (s.get("metadata", {}).get("name", ""),
             s.get("metadata", {}).get("resourceVersion", ""),
             s.get("spec", {}).get("pool", {}).get("generation", 0))
            for s in slices
        ))

    def __init__(self, slices: list[dict], signature: tuple | None = None,
                 default_node: str | None = None):
        self.signature = (self.signature_of(slices)
                          if signature is None else signature)
        newest: dict[tuple, int] = {}
        for s in slices:
            spec = s.get("spec", {})
            pool = spec.get("pool", {})
            key = (spec.get("driver", ""), pool.get("name", ""))
            newest[key] = max(newest.get(key, 0),
                              pool.get("generation", 0))
        self.pool_generations = newest
        self.candidates: list[Candidate] = []
        self._counter_seeds: list[tuple[str, str, list[dict]]] = []
        for s in slices:
            spec = s.get("spec", {})
            pool = spec.get("pool", {})
            driver = spec.get("driver", "")
            pool_name = pool.get("name", "")
            if pool.get("generation", 0) != newest[(driver, pool_name)]:
                continue  # stale generation: invisible to allocation
            node = spec.get("nodeName") or default_node or ""
            if spec.get("sharedCounters"):
                self._counter_seeds.append(
                    (driver, pool_name, spec["sharedCounters"]))
            for dev in spec.get("devices", []):
                self.candidates.append(
                    Candidate(driver, pool_name, node, dev))
        self.by_key: dict[tuple, Candidate] = {
            c.key: c for c in self.candidates}
        self.by_node: dict[str, list[Candidate]] = {}
        for c in self.candidates:
            self.by_node.setdefault(c.node, []).append(c)
        self.order_cache: dict[tuple, list[str] | None] = {}
        self._sel_cache: dict[tuple[str, tuple], bool] = {}

    def make_ledger(self) -> CounterLedger:
        ledger = CounterLedger()
        for driver, pool, sets in self._counter_seeds:
            ledger.seed(driver, pool, sets)
        return ledger

    def cel_match(self, expression: str, prog: CelProgram,
                  cand: Candidate) -> bool:
        key = (expression, cand.key)
        hit = self._sel_cache.get(key)
        if hit is None:
            try:
                hit = bool(prog.matches_device(cand.device, cand.driver))
            except Exception:  # noqa: BLE001 - CEL eval boundary
                hit = False
            self._sel_cache[key] = hit
        return hit


class NodeLockManager:
    """Per-node allocation locks for the sharded scheduler: disjoint
    nodes commit in parallel, same-node contenders serialize, and a
    gang claim spanning several hosts takes its whole lock set in one
    ordered acquisition (sorted node names) so two gangs overlapping on
    any node can never deadlock. Sits ABOVE the scheduler registry lock
    and the allocation-state lock in the documented hierarchy
    (docs/architecture.md "Sharded allocation locking"); commit kube
    I/O is sanctioned under node locks only."""

    def __init__(self):
        self._locks: dict[str, threading.Lock] = {}
        self._mu = threading.Lock()

    def _lock_for(self, node: str) -> threading.Lock:
        with self._mu:
            lock = self._locks.get(node)
            if lock is None:
                lock = self._locks[node] = threading.Lock()
            return lock

    @contextmanager
    def hold(self, nodes):
        """Acquire the locks for ``nodes`` in sorted order (the
        deadlock-freedom invariant the interleaving explorer and lint
        rule TPUDRA001 check)."""
        ordered = sorted(set(nodes))
        held = []
        try:
            for node in ordered:
                lock = self._lock_for(node)
                lock.acquire()
                held.append(lock)
            yield
        finally:
            for lock in reversed(held):
                lock.release()


class AllocationState:
    """Allocated-device keys + debited counter budgets, incrementally
    maintained from ResourceClaim allocations.

    ``observe`` is idempotent per claim (keyed by uid, falling back to
    namespace/name): replaying the same allocation -- e.g. the watch
    event for a patch the scheduler itself just wrote -- is a no-op,
    and a changed allocation releases the previous devices first.

    Thread safety (scheduler scale-out): every mutation happens under
    the internal ``_alloc_lock`` so informer event threads and N sync
    workers can share one state. ``try_commit`` is the atomic
    check-and-reserve the optimistic commit-then-observe protocol pins
    on: a fit computed against (possibly stale) reads either reserves
    its devices atomically or reports a conflict for a re-fit, so two
    workers can never double-allocate a device or over-spend a counter
    budget. ``node_load`` is maintained incrementally so the per-claim
    node ordering no longer scans the whole allocated set.
    """

    def __init__(self, snapshot: InventorySnapshot):
        self.snapshot = snapshot
        self.ledger = snapshot.make_ledger()
        # Keys at FULL capacity -- the set the fit probes. Exclusive
        # devices fill at one allocation; shared (oversubscribed
        # partition) devices fill at ``Candidate.slots`` concurrent
        # holders, tracked in _counts.
        self.allocated: set[tuple] = set()
        self._counts: dict[tuple, int] = {}
        self.node_load: dict[str, int] = {}
        self._claims: dict[str, frozenset] = {}
        self._alloc_lock = threading.Lock()

    def _slots_of(self, key: tuple) -> int:
        cand = self.snapshot.by_key.get(key)
        return cand.slots if cand is not None else 1

    @staticmethod
    def claim_id(claim: dict) -> str:
        md = claim.get("metadata", {})
        return md.get("uid") or f"{md.get('namespace', 'default')}/" \
                                f"{md.get('name', '')}"

    @staticmethod
    def _alloc_keys(claim: dict) -> frozenset:
        alloc = claim.get("status", {}).get("allocation") or {}
        return frozenset(
            (r.get("driver", ""), r.get("pool", ""), r.get("device", ""))
            for r in alloc.get("devices", {}).get("results", [])
        )

    def rebuild(self, claims: list[dict]) -> None:
        with self._alloc_lock:
            self.ledger = self.snapshot.make_ledger()
            self.allocated = set()
            self._counts = {}
            self.node_load = {}
            self._claims = {}
            for claim in claims:
                self._observe_locked(claim)

    def observe(self, claim: dict) -> bool:
        """Fold one claim's current allocation in. Returns True when
        the state changed."""
        with self._alloc_lock:
            return self._observe_locked(claim)

    def _observe_locked(self, claim: dict) -> bool:
        cid = self.claim_id(claim)
        keys = self._alloc_keys(claim)
        old = self._claims.get(cid, frozenset())
        if keys == old:
            return False
        self._release_locked(old)
        self._apply_locked(cid, keys)
        return True

    def _apply_locked(self, cid: str, keys: frozenset) -> None:
        for key in keys:
            count = self._counts.get(key, 0) + 1
            self._counts[key] = count
            if count >= self._slots_of(key):
                self.allocated.add(key)
            cand = self.snapshot.by_key.get(key)
            if cand is not None:
                self.ledger.debit(cand.driver, cand.pool,
                                  cand.device.get("consumesCounters"))
                self.node_load[cand.node] = \
                    self.node_load.get(cand.node, 0) + 1
        if keys:
            self._claims[cid] = keys
        else:
            self._claims.pop(cid, None)

    def forget(self, claim: dict) -> bool:
        """Drop a deleted claim; its devices return to the free pool."""
        with self._alloc_lock:
            cid = self.claim_id(claim)
            old = self._claims.pop(cid, None)
            if not old:
                return False
            self._release_locked(old)
            return True

    def try_commit(self, claim: dict) -> bool:
        """Atomically reserve one claim's planned allocation: every
        device key must still have a free slot (exclusive devices: not
        allocated at all; shared partition devices: fewer than
        ``slots`` holders) and every counter budget must still fit,
        judged and applied under one lock. Returns False on conflict
        (the caller re-fits against fresh state); replaying a claim's
        own reservation returns True (idempotent). A reserve whose
        kube patch subsequently fails is undone via ``forget``, so a
        failed write never leaks a debit (commit-then-observe)."""
        cid = self.claim_id(claim)
        keys = self._alloc_keys(claim)
        with self._alloc_lock:
            prior = self._claims.get(cid)
            if prior == keys:
                return True  # idempotent replay of our own reservation
            if prior is not None:
                # The claim was freshly read as unallocated, so a prior
                # entry is stale (a deallocated claim's ghost from the
                # commit-log replay): release it and re-judge. The work
                # queue runs each key on at most one worker at a time
                # (its running-set -- true even with work stealing), so
                # this can never drop another worker's in-flight
                # reservation.
                self._release_locked(prior)
                self._claims.pop(cid, None)
            debited: list[Candidate] = []
            ok = True
            for key in keys:
                if key in self.allocated:
                    ok = False
                    break
                cand = self.snapshot.by_key.get(key)
                if cand is None:
                    continue
                consumes = cand.device.get("consumesCounters")
                if consumes and not self.ledger.fits(
                        cand.driver, cand.pool, consumes):
                    ok = False
                    break
                # Debit as we go so multi-device claims can't pass N
                # individual fits that overspend one shared counter.
                self.ledger.debit(cand.driver, cand.pool, consumes)
                debited.append(cand)
            if not ok:
                for cand in debited:
                    self.ledger.credit(cand.driver, cand.pool,
                                       cand.device.get("consumesCounters"))
                return False
            for cand in debited:
                # _apply_locked re-debits; restore balance first.
                self.ledger.credit(cand.driver, cand.pool,
                                   cand.device.get("consumesCounters"))
            self._apply_locked(cid, keys)
            return True

    def ledger_snapshot(self) -> "CounterLedger":
        """Consistent copy of the counter ledger for a lock-free fit."""
        with self._alloc_lock:
            copy = CounterLedger()
            copy._avail = {k: dict(v) for k, v in self.ledger._avail.items()}
            return copy

    def load_view(self) -> dict[str, int]:
        """Consistent copy of the per-node allocated-device counts."""
        with self._alloc_lock:
            return dict(self.node_load)

    def _release_locked(self, keys: frozenset) -> None:
        for key in keys:
            count = self._counts.get(key, 0) - 1
            if count > 0:
                self._counts[key] = count
            else:
                self._counts.pop(key, None)
            if count < self._slots_of(key):
                self.allocated.discard(key)
            cand = self.snapshot.by_key.get(key)
            if cand is not None:
                self.ledger.credit(cand.driver, cand.pool,
                                   cand.device.get("consumesCounters"))
                left = self.node_load.get(cand.node, 0) - 1
                if left > 0:
                    self.node_load[cand.node] = left
                else:
                    self.node_load.pop(cand.node, None)


# Objects (claims / pods) opt into a scheduling domain with this
# annotation; unannotated objects belong to the default domain.
DOMAIN_ANNOTATION = "resource.tpu.dra/domain"


class SchedulingDomain:
    """A partitioned scheduling domain (scheduler-per-pool sharding).

    Operators scale the control plane horizontally by running one
    scheduler instance per domain: each instance leader-elects on its
    own per-domain Lease (``lease_name``), restricts its inventory
    snapshot to the pools matching ``pools`` (exact names or
    ``fnmatch`` globs), and consumes only the dirty keys of claims /
    pods annotated ``resource.tpu.dra/domain: <name>``. Exactly one
    domain should be ``default=True`` (or one scheduler run with no
    domain at all): it owns unannotated objects plus the cluster-wide
    controllers (DaemonSet/Job sync, recovery), which must not run in
    every domain."""

    def __init__(self, name: str, pools=(), default: bool = False):
        self.name = name
        self.pools = [p for p in pools if p]
        self.default = default

    @property
    def lease_name(self) -> str:
        return f"tpu-dra-scheduler-{self.name}"

    def owns_pool(self, pool: str, node: str) -> bool:
        """POOL names only (node-local pools are named after their
        node, so that already covers the common case); matching node
        names too would let one slice silently satisfy two domains'
        globs and overlap their snapshots -- nothing validates domain
        disjointness, so the contract stays narrow."""
        if not self.pools:
            return True
        from fnmatch import fnmatch  # noqa: PLC0415

        return any(fnmatch(pool, pat) for pat in self.pools)

    def owns_object(self, obj: dict) -> bool:
        """Claim/pod routing: the domain annotation wins; unannotated
        objects belong to the default domain."""
        ann = (obj.get("metadata", {}).get("annotations") or {}).get(
            DOMAIN_ANNOTATION, "")
        if ann:
            return ann == self.name
        return self.default

    @classmethod
    def from_env(cls, env=None) -> "SchedulingDomain | None":
        import os  # noqa: PLC0415

        env = env if env is not None else os.environ
        name = env.get("TPU_DRA_SCHED_DOMAIN", "")
        if not name:
            return None
        pools = [p.strip() for p in env.get(
            "TPU_DRA_SCHED_DOMAIN_POOLS", "").split(",") if p.strip()]
        default = env.get("TPU_DRA_SCHED_DOMAIN_DEFAULT", "") in (
            "1", "true", "True")
        return cls(name, pools=pools, default=default)


# (group, version, resource, kind) for every resource the scheduler's
# sync paths read. TPUDRA009 (pkg/analysis) enforces that reads of
# these inside pkg/scheduler.py go through this view.
WATCHED_RESOURCES: tuple[tuple[str, str, str, str], ...] = (
    ("", "v1", "pods", "Pod"),
    ("", "v1", "nodes", "Node"),
    ("apps", "v1", "daemonsets", "DaemonSet"),
    ("batch", "v1", "jobs", "Job"),
    ("resource.k8s.io", "v1", "resourceclaims", "ResourceClaim"),
    ("resource.k8s.io", "v1", "resourceslices", "ResourceSlice"),
    ("resource.k8s.io", "v1", "deviceclasses", "DeviceClass"),
    ("resource.k8s.io", "v1", "resourceclaimtemplates",
     "ResourceClaimTemplate"),
    (CD_GROUP, CD_VERSION, "computedomains", "ComputeDomain"),
)


class ClusterView:
    """One read surface for scheduler sync paths.

    Direct mode (default): every accessor falls through to the kube
    client, preserving the one-shot ``sync_once()`` semantics unit
    tests rely on (KubeErrors propagate so fail-closed call sites keep
    failing closed). Event mode (``start()``): every watched resource
    gets an informer; accessors become pure cache reads and
    ``on_event(resource, ev_type, obj)`` fires per object change so
    the scheduler can maintain its dirty set.

    The inventory snapshot is cached in BOTH modes and rebuilt only
    when the slice signature changes (any slice create/update/delete,
    including pool-generation bumps)."""

    def __init__(self, kube, on_event: Callable | None = None,
                 on_relist: Callable[[str], None] | None = None,
                 resync_period: float = 300.0,
                 default_node: str | None = None,
                 pool_filter: Callable[[str, str], bool] | None = None,
                 on_snapshot_build: Callable[[float], None] | None = None):
        self.kube = kube
        self._on_event = on_event
        self._on_relist = on_relist
        self._resync_period = resync_period
        self._default_node = default_node
        # Scheduling-domain partitioning: pool_filter(pool, node) False
        # makes a slice invisible to this scheduler's snapshot (the
        # per-pool domain sharding surface).
        self._pool_filter = pool_filter
        self._on_snapshot_build = on_snapshot_build
        self._informers: dict[str, Informer] = {}
        self._snapshot: InventorySnapshot | None = None
        self._snapshot_lock = threading.Lock()
        # Bumped on EVERY slice event/invalidation; snapshot() rereads
        # until its listing is provably not older than the latest bump,
        # so a rebuild racing an event-thread generation bump can never
        # install (and serve to a commit) a stale-generation snapshot.
        # In event mode it also powers the O(1) snapshot fast path: a
        # cached snapshot built at the current generation is returned
        # without relisting or recomputing the signature.
        self._slice_gen = 0
        self._snapshot_gen = -1
        self._cd_windows: dict[str, list[str]] | None = None
        self._started = False

    # -- lifecycle ------------------------------------------------------------

    @property
    def event_driven(self) -> bool:
        return self._started

    def start(self) -> "ClusterView":
        if self._started:
            return self
        self._started = True
        for group, version, resource, kind in WATCHED_RESOURCES:
            inf = Informer(self.kube, group, version, resource, kind=kind,
                           resync_period=self._resync_period,
                           on_relist=self._relist_hook(resource))
            if self._on_event is not None:
                inf.add_event_hook(self._event_hook(resource))
            self._informers[resource] = inf
            inf.start()
        return self

    def stop(self) -> None:
        for inf in self._informers.values():
            inf.stop()

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        deadline = timeout
        return all(inf.wait_for_sync(deadline)
                   for inf in self._informers.values())

    def _event_hook(self, resource: str):
        def hook(ev_type: str, obj: dict, _r=resource):
            self._on_local_event(_r, ev_type, obj)
            if self._on_event is not None:
                self._on_event(_r, ev_type, obj)
        return hook

    def _relist_hook(self, resource: str):
        def hook(_r=resource):
            if self._on_relist is not None:
                self._on_relist(_r)
        return hook

    def _on_local_event(self, resource: str, ev_type: str,
                        obj: dict) -> None:
        if resource == "computedomains":
            self._cd_windows = None
        elif resource == "resourceslices":
            # The informer applied the change to its cache BEFORE
            # firing this hook, so any slice listing taken after this
            # bump observes it.
            with self._snapshot_lock:
                self._slice_gen += 1

    # -- per-pass bookkeeping -------------------------------------------------

    def begin_pass(self) -> None:
        """Reset per-pass memos that event mode invalidates by event
        (direct mode has no events, so a full pass starts fresh)."""
        if not self._started:
            self._cd_windows = None

    # -- reads ----------------------------------------------------------------

    def _list(self, group: str, version: str, resource: str) -> list[dict]:
        inf = self._informers.get(resource)
        if inf is not None:
            return inf.list()
        return self.kube.list(group, version, resource)

    def pods(self) -> list[dict]:
        return self._list("", "v1", "pods")

    def nodes(self) -> list[dict]:
        return self._list("", "v1", "nodes")

    def daemonsets(self) -> list[dict]:
        return self._list("apps", "v1", "daemonsets")

    def jobs(self) -> list[dict]:
        return self._list("batch", "v1", "jobs")

    def claims(self) -> list[dict]:
        return self._list(*RESOURCE, "resourceclaims")

    def slices(self) -> list[dict]:
        return self._list(*RESOURCE, "resourceslices")

    def device_classes(self) -> list[dict]:
        return self._list(*RESOURCE, "deviceclasses")

    def get_pod(self, name: str, namespace: str = "default") -> dict:
        inf = self._informers.get("pods")
        if inf is not None:
            obj = inf.get(name, namespace)
            if obj is None:
                raise NotFoundError(f"pods/{name}")
            return obj
        return self.kube.get("", "v1", "pods", name, namespace=namespace)

    def get_claim(self, name: str, namespace: str = "default") -> dict:
        inf = self._informers.get("resourceclaims")
        if inf is not None:
            obj = inf.get(name, namespace)
            if obj is None:
                raise NotFoundError(f"resourceclaims/{name}")
            return obj
        return self.kube.get(*RESOURCE, "resourceclaims", name,
                             namespace=namespace)

    def get_template(self, name: str, namespace: str = "default") -> dict:
        inf = self._informers.get("resourceclaimtemplates")
        if inf is not None:
            obj = inf.get(name, namespace)
            if obj is None:
                raise NotFoundError(f"resourceclaimtemplates/{name}")
            return obj
        return self.kube.get(*RESOURCE, "resourceclaimtemplates", name,
                             namespace=namespace)

    # -- indexed snapshot -----------------------------------------------------

    def _filtered_slices(self) -> list[dict]:
        slices = self.slices()
        if self._pool_filter is None:
            return slices
        return [
            s for s in slices
            if self._pool_filter(
                s.get("spec", {}).get("pool", {}).get("name", ""),
                s.get("spec", {}).get("nodeName", ""))
        ]

    # Bounded retries for the list-vs-event race below: a cluster
    # churning slices faster than we can list is pathological; after
    # this many laps the freshest listing we have wins (still at least
    # as new as every bump observed before the first lap).
    _SNAPSHOT_RACE_RETRIES = 10

    def snapshot(self) -> InventorySnapshot:
        """The current inventory snapshot, rebuilt only when any slice
        changed (tracked via (name, resourceVersion, generation)).

        Rebuilds are race-checked against ``_slice_gen``: a worker
        whose listing predates a concurrent slice event (generation
        bump) re-lists instead of installing -- and handing a commit --
        a stale-generation snapshot that could clobber a newer one.

        Event mode gets an O(1) fast path off the same counter: slice
        events are the only thing that can change the listing, so a
        snapshot built at the current generation is returned without
        relisting or recomputing the O(slices) signature -- at 1000
        nodes that check used to dominate every allocation batch."""
        if self._started:
            with self._snapshot_lock:
                if self._snapshot is not None and \
                        self._snapshot_gen == self._slice_gen:
                    return self._snapshot
        for _ in range(self._SNAPSHOT_RACE_RETRIES):
            with self._snapshot_lock:
                gen0 = self._slice_gen
            slices = self._filtered_slices()
            sig = InventorySnapshot.signature_of(slices)
            with self._snapshot_lock:
                if self._snapshot is not None and \
                        self._snapshot.signature == sig:
                    # The listing provably covers every event up to
                    # gen0 (read before the list); never stamp newer.
                    self._snapshot_gen = max(self._snapshot_gen, gen0)
                    return self._snapshot
                if self._slice_gen != gen0:
                    continue  # raced a slice event: our listing may be stale
                t0 = time.monotonic()
                with tracing.span("sched.snapshot_build",
                                  attrs={"slices": len(slices)}):
                    self._snapshot = InventorySnapshot(
                        slices, signature=sig,
                        default_node=self._default_node)
                self._snapshot_gen = gen0
                snap = self._snapshot
            if self._on_snapshot_build is not None:
                self._on_snapshot_build(time.monotonic() - t0)
            return snap
        # Persistent churn: accept the freshest listing we can get
        # (and force the next call to re-verify).
        slices = self._filtered_slices()
        sig = InventorySnapshot.signature_of(slices)
        with self._snapshot_lock:
            if self._snapshot is None or self._snapshot.signature != sig:
                self._snapshot = InventorySnapshot(
                    slices, signature=sig, default_node=self._default_node)
            self._snapshot_gen = -1
            return self._snapshot

    def invalidate_snapshot(self) -> None:
        with self._snapshot_lock:
            self._slice_gen += 1
            self._snapshot = None

    # -- ComputeDomain windows ------------------------------------------------

    def cd_windows(self) -> dict[str, list[str]]:
        """uid -> preferred-node window for every ComputeDomain.
        Cached until a CD event (event mode) / the next pass (direct
        mode); a transient list failure caches the empty answer so N
        pending channel claims never mean N failing lists."""
        cached = self._cd_windows
        if cached is not None:
            return cached
        try:
            cds = self._list(CD_GROUP, CD_VERSION, "computedomains")
        except KubeError:
            self._cd_windows = {}
            return self._cd_windows
        windows: dict[str, list[str]] = {}
        for cd in cds:
            md = cd.get("metadata", {})
            uid = md.get("uid")
            ann = (md.get("annotations") or {}).get(
                PREFERRED_NODES_ANNOTATION, "")
            if uid:
                windows[uid] = [n for n in ann.split(",") if n]
        self._cd_windows = windows
        return windows
