"""Shared filesystem helpers."""

from __future__ import annotations

import json
import os


def write_json_atomic(path: str, obj, fsync: bool = False) -> None:
    """tmp-write + rename so a crash mid-write never leaves truncated
    JSON behind (the checkpoint/registry persistence pattern)."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(obj, f)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, path)
