"""Shared filesystem helpers."""

from __future__ import annotations

import json
import os


def write_json_atomic(path: str, obj, fsync: bool = False) -> None:
    """tmp-write + rename so a crash mid-write never leaves truncated
    JSON behind (the checkpoint/registry persistence pattern)."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(obj, f)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, path)


def stat_signature(path: str) -> tuple[int, int, int] | None:
    """(mtime_ns, size, inode) identity of a file for stat-validated
    parse caches, or None when absent. The inode catches same-size
    same-mtime cross-process rewrites: every atomic write lands via
    os.replace of a fresh tmp inode."""
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (st.st_mtime_ns, st.st_size, st.st_ino)
