"""Active defragmentation: the frag-drift-triggered migration
controller that converges churned fleets back to large free sub-tori.

The placement engine (pkg/topology + scheduler ordering) only
*prevents* fragmentation at allocation time; under sustained claim
churn the fleet still decays until large gangs pend behind scattered
free chips. This module closes the loop the ROADMAP names: it watches
the per-pool fragmentation time-series the FleetAggregator already
keeps (pkg/fleetstate), and when a pool's ``fragmentation_score``
crosses ``TPU_DRA_DEFRAG_TRIGGER`` -- with a pending large-shape
demand signal, or steadily for ``TPU_DRA_DEFRAG_SUSTAIN_S`` -- it
plans claim moves multi-objectively (the 2502.01909 framing):

- **frag recovered**: the largest-free-shape delta of a simulated
  re-pack (``pkg/topology/sim.plan_repack``) -- the biggest sub-torus
  that can be carved free by relocating squatting claims;
- **migration cost**: chips moved + claim uptime
  (``pkg/recovery.age_cost`` -- young claims move before long-running
  training gangs) -- greedy cheapest-first;
- **gang disruption**: healthy ComputeDomain companions disturbed per
  move, weighted like the eviction planner's disruption term.

Execution reuses the PR 6 eviction pipeline stage for stage: drain
(evict bound consumer pods, drop reservations) -> deallocate -> the
event-driven scheduler re-places, steered by a
``resource.tpu.dra/defrag-target`` placement hint honored by
``_fit_on_node`` ordering while the controller's device reservations
veto every OTHER claim off the carve and the move targets. Each move
is one durable record under the ``defrag`` TransitionPolicy
(pkg/analysis/statemachine), so a controller crash at any fault point
(``defrag.sync``/``plan``/``drain``/``dealloc``) resumes idempotently.

Priority classes fall out of the same plan/execute machinery: a claim
annotated ``resource.tpu.dra/priority`` is only ever displaced on
behalf of STRICTLY higher-priority pending demand, and claims
annotated ``resource.tpu.dra/defrag-opt-out`` are never moved at all.

Operator surface: docs/operations.md "Defragmentation runbook"
(trigger/budget/priority knob matrix, pausing via
``TPU_DRA_DEFRAG_PAUSE``), ``tpu_dra_defrag_*`` metrics
(pkg/metrics.DefragMetrics), per-move flight-recorder entries.
"""

from __future__ import annotations

import logging
import threading
import time

from . import positive_float_env
from . import faults, flightrecorder
from .analysis.statemachine import (
    DEFRAG_DEALLOCATED,
    DEFRAG_DRAINING,
    DEFRAG_PLANNED,
    DEFRAG_POLICY,
)
from .kubeclient import ConflictError, KubeError, NotFoundError
from .recovery import (
    AGE_WEIGHT,
    DISRUPTION_WEIGHT,
    age_cost,
    allocation_nodes,
    claim_gang_id,
    clear_allocation,
    coop_cost_multiplier,
    drain_claim,
)
from .topology import TorusGrid
from .topology.score import largest_free_shape
from .topology.sim import plan_repack

logger = logging.getLogger(__name__)

RESOURCE = ("resource.k8s.io", "v1")

#: Placement hint the controller stamps on a moving claim:
#: ``<node>|<dev1>,<dev2>``. The scheduler's ``_fit_on_node`` orders
#: the hinted devices first (and ``_candidate_nodes`` probes the
#: hinted node first) -- pure preference, never a constraint, so a
#: stale hint can only cost placement quality.
DEFRAG_TARGET_ANNOTATION = "resource.tpu.dra/defrag-target"
#: Claims carrying this annotation (any value but "false") are
#: protected: the planner never selects them as move victims.
OPT_OUT_ANNOTATION = "resource.tpu.dra/defrag-opt-out"
#: Integer priority class. An annotated claim is only displaced on
#: behalf of pending demand with STRICTLY higher priority; an
#: unannotated claim belongs to the default (freely movable) tier.
PRIORITY_ANNOTATION = "resource.tpu.dra/priority"

# Operator knobs (docs/operations.md "Defragmentation runbook").
DEFRAG_TRIGGER = positive_float_env(
    "TPU_DRA_DEFRAG_TRIGGER", default=0.25, floor=0.0)
#: Hysteresis release: a triggered pool stays a defrag target until
#: its frag falls back here (must be < trigger to actually hysterese).
DEFRAG_RELEASE = positive_float_env(
    "TPU_DRA_DEFRAG_RELEASE", default=0.15, floor=0.0)
DEFRAG_SUSTAIN_S = positive_float_env(
    "TPU_DRA_DEFRAG_SUSTAIN_S", default=120.0, floor=0.0)
DEFRAG_MAX_CONCURRENT = int(positive_float_env(
    "TPU_DRA_DEFRAG_MAX_CONCURRENT", default=2, floor=1))
DEFRAG_DEADLINE_S = positive_float_env(
    "TPU_DRA_DEFRAG_DEADLINE_S", default=300.0, floor=0.01)
#: Per-window migration budget: at most this percentage of a pool's
#: LIVE claims may be planned into one defrag window.
DEFRAG_BUDGET_PCT = positive_float_env(
    "TPU_DRA_DEFRAG_BUDGET_PCT", default=15.0, floor=0.0)
#: Quiet period after a window completes before the pool is
#: re-planned (lets the fleet rings catch up with the moves).
DEFRAG_COOLDOWN_S = positive_float_env(
    "TPU_DRA_DEFRAG_COOLDOWN_S", default=60.0, floor=0.0)
#: Pause switch: "1"/"true" stops NEW plan windows; in-flight moves
#: still advance to completion (never park a half-moved claim).
PAUSE_ENV = "TPU_DRA_DEFRAG_PAUSE"


def _meta(obj: dict) -> dict:
    return obj.get("metadata", {})


def claim_priority(claim: dict) -> int | None:
    """The claim's priority class, or None when unannotated (the
    default, freely-movable tier). A malformed annotation fails
    CLOSED: the user clearly meant to protect the claim, so it gets
    an unbeatable priority instead of silently demoting to the
    movable tier."""
    raw = (_meta(claim).get("annotations") or {}).get(
        PRIORITY_ANNOTATION)
    if raw is None:
        return None
    try:
        return int(raw)
    except (TypeError, ValueError):
        logger.warning(
            "claim %s/%s: unparseable %s annotation %r; treating the "
            "claim as unmovable",
            _meta(claim).get("namespace", "default"),
            _meta(claim).get("name"), PRIORITY_ANNOTATION, raw)
        import sys  # noqa: PLC0415 - cold error path

        return sys.maxsize


def demand_priority_of(claim: dict) -> int:
    """Priority a PENDING claim wields as preemption power. The
    asymmetric twin of :func:`claim_priority`: here a malformed
    annotation fails closed to ZERO power (a typo must never let a
    pending claim displace protected workloads), while on the victim
    side the same typo fails closed to unmovable."""
    raw = (_meta(claim).get("annotations") or {}).get(
        PRIORITY_ANNOTATION)
    try:
        return int(raw) if raw is not None else 0
    except (TypeError, ValueError):
        return 0


def claim_opted_out(claim: dict) -> bool:
    raw = (_meta(claim).get("annotations") or {}).get(
        OPT_OUT_ANNOTATION)
    return raw is not None and raw not in ("false", "False", "0")


def claim_device_demand(claim: dict) -> int:
    """Chips one claim requests (All-mode counts 1) -- the pending
    large-shape demand signal's magnitude."""
    total = 0
    for req in claim.get("spec", {}).get("devices", {}).get(
            "requests", []):
        exactly = req.get("exactly") or req
        if exactly.get("allocationMode", "ExactCount") == "All":
            total += 1
        else:
            try:
                total += max(int(exactly.get("count", 1)), 1)
            except (TypeError, ValueError):
                total += 1
    return max(total, 1)


def parse_target_hint(value: str) -> tuple[str, list[str]] | None:
    """``"node-3|chip-1,chip-2"`` -> ("node-3", ["chip-1", "chip-2"]);
    None for anything malformed."""
    if not value or "|" not in value:
        return None
    node, _, names = value.partition("|")
    devices = [n for n in names.split(",") if n]
    if not node or not devices:
        return None
    return node, devices


class DefragController:
    """Plans and drives frag-recovery claim migrations; designed to
    ride the event-driven scheduler loop (``attach_defrag``) or be
    driven directly (``sync_once``) by tests and the defrag bench."""

    #: Meta device name carrying a move record's plan payload in its
    #: ``live`` dict (target devices, carve devices, window id, gain).
    _META_DEVICE = "defrag"

    def __init__(self, kube, root: str, fleet=None, metrics=None,
                 trigger: float = DEFRAG_TRIGGER,
                 release: float = DEFRAG_RELEASE,
                 sustain_s: float = DEFRAG_SUSTAIN_S,
                 max_concurrent: int = DEFRAG_MAX_CONCURRENT,
                 deadline_s: float = DEFRAG_DEADLINE_S,
                 budget_pct: float = DEFRAG_BUDGET_PCT,
                 cooldown_s: float = DEFRAG_COOLDOWN_S,
                 disruption_weight: float = DISRUPTION_WEIGHT,
                 age_weight: float = AGE_WEIGHT):
        # Function-local import like pkg/recovery: pkg -> kubeletplugin
        # stays a one-way street for non-driver users of pkg.
        from ..kubeletplugin.checkpoint import (  # noqa: PLC0415
            CheckpointManager,
        )

        self.kube = kube
        self.fleet = fleet  # pkg/fleetstate.FleetAggregator | None
        self.metrics = metrics  # pkg.metrics.DefragMetrics | None
        self.trigger = trigger
        self.release = min(release, trigger)
        self.sustain_s = sustain_s
        self.max_concurrent = max(1, int(max_concurrent))
        self.deadline_s = deadline_s
        self.budget_pct = budget_pct
        self.cooldown_s = cooldown_s
        self.disruption_weight = disruption_weight
        self.age_weight = age_weight
        # Durable move records under the defrag TransitionPolicy: the
        # idempotent-resume anchor (see module docstring).
        self._checkpoint = CheckpointManager(
            root, transition_policy=DEFRAG_POLICY)
        self._lock = threading.Lock()
        # Device reservations derived from the durable records: device
        # key -> moving claim uid (its planned target), or None (a
        # carve cell held free for the forming shape). The scheduler's
        # fit vetoes every OTHER claim off these devices.
        self._reservations: dict[tuple[str, str, str],
                                 str | None] = {}
        # (driver, pool) -> wall clock before which no new window may
        # be planned there (post-window cooldown).
        self._cooldown_until: dict[tuple[str, str], float] = {}
        # Windows with at least one aborted move: their projected gain
        # was not fully realized, so window close skips the
        # frag-recovered credit (the next pass re-measures reality).
        self._aborted_windows: set[str] = set()
        # Optional informer-backed read surface
        # (pkg/schedcache.ClusterView), set by attach_defrag.
        self.view = None
        self.flight = flightrecorder.default()
        self.last_sync: dict = {}
        with self._lock:
            self._rebuild_reservations_locked()
            self._active_count = len(self._checkpoint.get().claims)

    # -- scheduler surface ----------------------------------------------------

    def busy(self) -> bool:
        """True while any move record is in flight; the scheduler
        gates per-claim-event defrag enqueues on this."""
        with self._lock:
            return self._active_count > 0

    def active_moves(self) -> dict[str, str]:
        """uid -> move state of every in-flight record."""
        return {uid: rec.state
                for uid, rec in self._checkpoint.get().claims.items()}

    def reservations(self) -> dict[tuple[str, str, str], str | None]:
        """Device key -> reserved-for uid (None = carve cell, held
        free for the forming shape). Cheap cached read for the
        scheduler's per-claim fit."""
        with self._lock:
            return self._reservations

    @staticmethod
    def paused() -> bool:
        import os  # noqa: PLC0415 - env read on a cold path

        return os.environ.get(PAUSE_ENV, "") in ("1", "true", "True")

    # -- reads ----------------------------------------------------------------

    def _list_slices(self) -> list[dict]:
        if self.view is not None:
            return self.view.slices()
        return self.kube.list(*RESOURCE, "resourceslices")

    def _list_claims(self) -> list[dict]:
        if self.view is not None:
            return self.view.claims()
        return self.kube.list(*RESOURCE, "resourceclaims")

    def _pods(self) -> list[dict]:
        try:
            if self.view is not None:
                return self.view.pods()
            return self.kube.list("", "v1", "pods")
        except KubeError:
            return []

    # -- sync -----------------------------------------------------------------

    def sync_once(self) -> dict:
        """One advance -> detect -> plan pass. Every stage is
        idempotent; a crash anywhere resumes from the durable
        records."""
        faults.fault_point("defrag.sync")
        counts = {"advanced": 0, "completed": 0, "aborted": 0,
                  "planned": 0, "windows": 0}
        try:
            claims = self._list_claims()
            slices = self._list_slices()
        except KubeError:
            logger.warning("defrag sync: inventory list failed; "
                           "retrying next pass")
            return counts
        self._advance(claims, counts)
        if not self.paused():
            self._detect_and_plan(claims, slices, counts)
        active = len(self._checkpoint.get().claims)
        with self._lock:
            self._active_count = active
        if self.metrics is not None:
            self.metrics.active_moves.set(active)
        self.last_sync = counts
        return counts

    # -- trigger + planning ---------------------------------------------------

    def _detect_and_plan(self, claims: list[dict], slices: list[dict],
                         counts: dict) -> None:
        if self.fleet is None:
            return
        if self._checkpoint.get().claims:
            return  # one window at a time: finish the moves first
        pending = [c for c in claims
                   if not c.get("status", {}).get("allocation")
                   and not _meta(c).get("deletionTimestamp")]
        signal = self.fleet.frag_signal(
            self.trigger, self.release, self.sustain_s,
            demand=self._demand_pools(pending))
        now = time.time()
        fired = [(key, sig) for key, sig in signal.items()
                 if sig["fire"]
                 and now >= self._cooldown_until.get(key, 0.0)]
        # Worst pool first: one window at a time keeps the blast
        # radius (and the reservation set) small and inspectable.
        fired.sort(key=lambda t: (-t[1]["fragmentation_score"], t[0]))
        for key, sig in fired:
            if self._plan_pool(key, claims, slices, pending, counts):
                counts["windows"] += 1
                break
            # No feasible carve (everything protected, or no gain
            # inside the budget): cool the pool down rather than
            # re-running the full what-if sweep every pass until its
            # occupancy actually changes.
            self._cooldown_until[key] = now + self.cooldown_s

    def _demand_pools(self, pending: list[dict]) -> set:
        """Pools whose pending demand cannot fit their largest free
        shape RIGHT NOW (the fire-immediately signal). Pending claims
        are not pool-bound, so unsatisfiable demand lights up every
        armed pool -- whichever defragments first absorbs it."""
        if self.fleet is None or not pending:
            return set()
        demand = max((claim_device_demand(c) for c in pending),
                     default=0)
        out = set()
        snap = self.fleet.snapshot()
        for label, entry in (snap.get("pools") or {}).items():
            point = entry.get("current") or {}
            largest = point.get("largest_free_shape")
            if largest is not None and demand > largest:
                driver, _, pool = label.partition("/")
                out.add((driver, pool))
        return out

    def _demand_priority(self, pending: list[dict],
                         largest_chips: int) -> int | None:
        """Highest priority among pending claims too big for the
        pool's current largest free shape; None when no such demand
        (a sustained-frag window acts for fleet health, not on any
        claim's behalf)."""
        prios = [demand_priority_of(c) for c in pending
                 if claim_device_demand(c) > largest_chips]
        return max(prios) if prios else None

    def _pool_model(self, key: tuple[str, str], slices: list[dict],
                    claims: list[dict]):
        """Grid + occupancy of one pool: (grid, free cells, claim uid
        -> cells, uid -> claim, coord -> node, coord -> device name).
        Claims that cannot be modeled (devices outside the pool,
        uncoordinated devices) still occupy their cells but are never
        movable."""
        driver, pool = key
        mine = [s for s in slices
                if s.get("spec", {}).get("driver") == driver
                and s.get("spec", {}).get("pool", {}).get(
                    "name") == pool]
        if not mine:
            return None
        gen = max(s["spec"].get("pool", {}).get("generation", 0)
                  for s in mine)
        devices, node_of_name = [], {}
        for s in sorted(mine, key=lambda s: _meta(s).get("name", "")):
            spec = s.get("spec", {})
            if spec.get("pool", {}).get("generation", 0) != gen:
                continue
            for dev in spec.get("devices", []) or []:
                devices.append(dev)
                node_of_name[dev.get("name", "")] = spec.get(
                    "nodeName") or ""
        grid = TorusGrid.from_devices(devices)
        if not grid.coords:
            return None
        node_of = {c: node_of_name.get(n, "")
                   for n, c in grid.coords.items()}
        name_of = {c: n for n, c in grid.coords.items()}
        allocations: dict[str, set] = {}
        by_uid: dict[str, dict] = {}
        unmodelable: set[str] = set()
        taken: set = set()
        for claim in claims:
            alloc = claim.get("status", {}).get("allocation")
            uid = _meta(claim).get("uid", "")
            if not alloc or not uid:
                continue
            results = alloc.get("devices", {}).get("results", [])
            cells = set()
            foreign = False
            for r in results:
                if (r.get("driver", ""), r.get("pool", "")) != key:
                    foreign = True
                    continue
                coord = grid.coords.get(r.get("device", ""))
                if coord is None:
                    unmodelable.add(uid)
                else:
                    cells.add(coord)
            if not cells:
                continue
            if foreign or len(cells) != len(results):
                unmodelable.add(uid)
            allocations[uid] = cells
            by_uid[uid] = claim
            taken |= cells
        free = set(node_of) - taken
        return (grid, free, allocations, by_uid, unmodelable, node_of,
                name_of)

    def _plan_pool(self, key: tuple[str, str], claims: list[dict],
                   slices: list[dict], pending: list[dict],
                   counts: dict) -> bool:
        """Simulated re-pack of one triggered pool; admits the
        cheapest feasible carve as a window of durable move records.
        Returns True when a window was planned."""
        faults.fault_point("defrag.plan")
        if self.budget_pct <= 0:
            return False  # budget exhausted/disabled: no new windows
        model = self._pool_model(key, slices, claims)
        if model is None:
            return False
        (grid, free, allocations, by_uid, unmodelable, node_of,
         name_of) = model
        _, largest_now = largest_free_shape(grid, free)
        demand_priority = self._demand_priority(pending, largest_now)
        gangs: dict[str, list[str]] = {}
        for uid, claim in by_uid.items():
            gang = claim_gang_id(claim)
            if gang:
                gangs.setdefault(gang, []).append(uid)

        def movable(uid: str) -> bool:
            claim = by_uid.get(uid)
            if claim is None or uid in unmodelable:
                return False
            if claim_opted_out(claim):
                return False
            prio = claim_priority(claim)
            if prio is None:
                return True  # default tier: movable for fleet health
            # Priority-annotated claims are only displaced on behalf
            # of STRICTLY higher-priority pending demand.
            return demand_priority is not None and \
                demand_priority > prio

        def companions(uid: str) -> int:
            gang = claim_gang_id(by_uid[uid]) if uid in by_uid else None
            return len(gangs.get(gang, [uid])) - 1 if gang else 0

        now = time.time()

        def cost_fn(uids: tuple) -> float:
            chips = sum(len(allocations[u]) for u in uids)
            disruption = sum(companions(u) for u in uids)
            aged = age_cost([by_uid[u] for u in uids],
                            self.age_weight, now=now)
            # Cooperative tier (pkg/migration contract): victims that
            # checkpoint on demand are far cheaper to displace, so the
            # repack prefers them over cold-restart claims of equal
            # size and age.
            coop = coop_cost_multiplier([by_uid[u] for u in uids])
            return (chips + self.disruption_weight * disruption
                    + aged) * coop

        budget = max(1, int(len(allocations) * self.budget_pct / 100))
        plan = plan_repack(grid, free, allocations, movable=movable,
                           cost_fn=cost_fn, max_moves=budget,
                           node_of=node_of)
        if plan is None or plan.chips_after <= plan.chips_before:
            return False
        driver, pool = key
        window = f"{driver}/{pool}@{int(now * 1000)}"
        carve = sorted(name_of[c] for c in plan.goal_cells
                       if c in name_of)
        gain = plan.chips_after - plan.chips_before
        logger.warning(
            "defrag window %s: carving %s (%d chips, largest free "
            "%d -> %d) by moving %d claim(s) [budget %d of %d live]",
            window, "x".join(map(str, plan.goal_shape)),
            len(plan.goal_cells), plan.chips_before, plan.chips_after,
            len(plan.moves), budget, len(allocations))
        for move in plan.moves:
            target_names = [name_of[c] for c in move.target
                            if c in name_of]
            target_nodes = {node_of.get(c, "") for c in move.target}
            self._write_record(
                by_uid[move.claim], DEFRAG_PLANNED, live={
                    "plannedAt": now,
                    "window": window,
                    "driver": driver,
                    "pool": pool,
                    "node": next(iter(target_nodes), ""),
                    "target": sorted(target_names),
                    "carve": carve,
                    "gain": gain,
                    "cost": round(cost_fn((move.claim,)), 3),
                })
            counts["planned"] += 1
        with self._lock:
            self._active_count = max(self._active_count, 1)
            self._rebuild_reservations_locked()
        if self.metrics is not None:
            self.metrics.plans.inc()
        return True

    # -- durable records ------------------------------------------------------

    def _write_record(self, claim: dict, state: str,
                      live: dict | None = None, prev=None) -> None:
        from ..kubeletplugin.checkpoint import (  # noqa: PLC0415
            CheckpointedClaim,
            CheckpointedDevice,
        )

        uid = _meta(claim).get("uid", "")
        if prev is not None:
            live = dict(prev.devices[0].live or {}) \
                if prev.devices else {}
        self._checkpoint.update_claim(uid, CheckpointedClaim(
            uid=uid,
            namespace=_meta(claim).get("namespace", "default"),
            name=_meta(claim).get("name", ""),
            state=state,
            devices=[CheckpointedDevice(
                canonical_name=self._META_DEVICE,
                kind=self._META_DEVICE, live=live or {})],
        ))
        self.flight.record(
            uid, "defrag",
            alias=(f"{_meta(claim).get('namespace', 'default')}/"
                   f"{_meta(claim).get('name', '')}"),
            state=state, window=(live or {}).get("window", ""))

    @staticmethod
    def _record_meta(rec) -> dict:
        return (rec.devices[0].live or {}) if rec.devices else {}

    def _retire_record(self, uid: str) -> None:
        self._checkpoint.update_claim(uid, None)
        with self._lock:
            self._rebuild_reservations_locked()

    def _rebuild_reservations_locked(self) -> None:
        """Reservations are a pure function of the durable records, so
        a restarted controller re-derives exactly the veto set its
        predecessor held."""
        out: dict[tuple[str, str, str], str | None] = {}
        for uid, rec in self._checkpoint.get().claims.items():
            meta = self._record_meta(rec)
            driver = meta.get("driver", "")
            pool = meta.get("pool", "")
            for name in meta.get("carve") or []:
                out.setdefault((driver, pool, name), None)
            for name in meta.get("target") or []:
                out[(driver, pool, name)] = uid
        self._reservations = out

    # -- staged advance -------------------------------------------------------

    def _advance(self, claims: list[dict], counts: dict) -> None:
        records = self._checkpoint.get().claims
        if not records:
            return
        by_uid = {_meta(c).get("uid", ""): c for c in claims}
        pods = None
        in_flight = sum(1 for rec in records.values()
                        if rec.state != DEFRAG_PLANNED)
        # Cheapest-first admission under the concurrency cap; records
        # beyond the cap stay durably Planned (their reservations
        # already protect the carve).
        ordered = sorted(
            records.items(),
            key=lambda kv: (self._record_meta(kv[1]).get("cost", 0.0),
                            kv[0]))
        now = time.time()
        for uid, rec in ordered:
            claim = by_uid.get(uid)
            if claim is None or _meta(claim).get("deletionTimestamp"):
                # The claim is gone: the move is moot.
                self._abort(uid, rec, claim, counts, reason="gone")
                continue
            # Deadline applies to EVERY stage, or a record wedged in
            # Planned/Draining (e.g. a perpetually conflicting patch)
            # would pin its reservations -- and block new windows --
            # forever. Planned records time out on the window's plan
            # clock (nothing was disrupted yet, so the abort is
            # free); admitted records on their admission clock.
            meta = self._record_meta(rec)
            clock = float(meta.get("startedAt")
                          or meta.get("plannedAt", 0.0))
            if clock and now - clock > self.deadline_s:
                self._abort(uid, rec, claim, counts,
                            reason="deadline")
                continue
            if rec.state == DEFRAG_PLANNED:
                if in_flight >= self.max_concurrent:
                    continue
                if pods is None:
                    pods = self._pods()
                if self._drain(uid, rec, claim, pods):
                    in_flight += 1
                    counts["advanced"] += 1
            elif rec.state == DEFRAG_DRAINING:
                self._deallocate(uid, rec, claim)
                counts["advanced"] += 1
            elif rec.state == DEFRAG_DEALLOCATED:
                self._try_retire(uid, rec, claim, counts)

    def _drain(self, uid: str, rec, claim: dict,
               pods: list[dict]) -> bool:
        """Stamp the placement hint, then the shared drain stage.
        Returns False when nothing was admitted (the hint patch was
        refused), so the caller's concurrency slot stays free."""
        faults.fault_point("defrag.drain")
        meta = dict(self._record_meta(rec))
        hint = f"{meta.get('node', '')}|" + ",".join(
            meta.get("target") or [])
        try:
            self.kube.patch(
                *RESOURCE, "resourceclaims", _meta(claim)["name"],
                {"metadata": {"annotations": {
                    DEFRAG_TARGET_ANNOTATION: hint}}},
                namespace=_meta(claim).get("namespace", "default"))
        except (NotFoundError, ConflictError):
            return False  # re-examined next pass
        drain_claim(self.kube, claim, pods)
        # The move-deadline clock starts at ADMISSION, not plan time:
        # a move queued behind max_concurrent must get its full
        # re-placement budget once drained, or a slow window's tail
        # would be disrupted only to abort immediately.
        meta.setdefault("startedAt", time.time())
        self._write_record(claim, DEFRAG_DRAINING, live=meta)
        return True

    def _deallocate(self, uid: str, rec, claim: dict) -> None:
        faults.fault_point("defrag.dealloc")
        if not clear_allocation(self.kube, claim):
            return  # re-examined next pass
        self._write_record(claim, DEFRAG_DEALLOCATED, prev=rec)
        logger.warning(
            "defrag: deallocated claim %s/%s (uid %s); awaiting "
            "re-placement onto %s",
            _meta(claim).get("namespace", "default"),
            _meta(claim).get("name"), uid,
            self._record_meta(rec).get("target"))

    def _try_retire(self, uid: str, rec, claim: dict,
                    counts: dict) -> None:
        meta = self._record_meta(rec)
        if claim.get("status", {}).get("allocation"):
            self._clear_hint(claim)
            self._retire_record(uid)
            counts["completed"] += 1
            planned_at = float(meta.get("plannedAt", 0.0))
            if self.metrics is not None:
                self.metrics.moves.inc()
                if planned_at:
                    self.metrics.move_seconds.observe(
                        max(time.time() - planned_at, 0.0))
            self.flight.record(uid, "defrag", state="Moved",
                               nodes=sorted(allocation_nodes(claim)))
            logger.warning("defrag: claim %s re-placed on %s", uid,
                           sorted(allocation_nodes(claim)))
            self._maybe_close_window(meta, counts)
        # Not yet re-placed: the caller's per-record deadline check
        # (top of _advance) aborts the move when the budget runs out.

    def _abort(self, uid: str, rec, claim: dict | None, counts: dict,
               reason: str) -> None:
        """Abandon a move cleanly: the claim (if it still exists)
        stays pending and schedulable with its hint cleared -- never
        parked mid-move."""
        if claim is not None:
            self._clear_hint(claim)
        meta = self._record_meta(rec)
        self._retire_record(uid)
        counts["aborted"] += 1
        if self.metrics is not None:
            self.metrics.aborted.inc()
        self.flight.record(uid, "defrag", state="Aborted",
                           reason=reason)
        logger.warning("defrag: move of claim %s aborted (%s)", uid,
                       reason)
        # An aborted window still cools the pool down -- re-planning
        # immediately would replay the same failure -- and forfeits
        # its frag-recovered credit (the carve did not fully form).
        self._aborted_windows.add(meta.get("window", ""))
        self._cooldown_until[(meta.get("driver", ""),
                              meta.get("pool", ""))] = \
            time.time() + self.cooldown_s
        # If this was the window's LAST record, close it here too --
        # the completed path's close never runs for a window whose
        # final move aborts, and the aborted-window marker must not
        # accumulate forever.
        self._maybe_close_window(meta, counts)

    def _clear_hint(self, claim: dict) -> None:
        # Unconditional idempotent merge-null: gating on the cached
        # claim copy could skip the clear when the informer view lags
        # our own _drain patch, leaving a stale hint to reorder every
        # future re-placement of this claim.
        try:
            self.kube.patch(
                *RESOURCE, "resourceclaims", _meta(claim)["name"],
                {"metadata": {"annotations": {
                    DEFRAG_TARGET_ANNOTATION: None}}},
                namespace=_meta(claim).get("namespace", "default"))
        except (NotFoundError, ConflictError):
            pass

    def _maybe_close_window(self, meta: dict, counts: dict) -> None:
        """The LAST move of a window retiring closes the window:
        credit the frag-recovered counter once and start the pool's
        cooldown."""
        window = meta.get("window", "")
        still_open = any(
            self._record_meta(rec).get("window") == window
            for rec in self._checkpoint.get().claims.values())
        if still_open:
            return
        key = (meta.get("driver", ""), meta.get("pool", ""))
        self._cooldown_until[key] = time.time() + self.cooldown_s
        gain = int(meta.get("gain", 0) or 0)
        if window in self._aborted_windows:
            self._aborted_windows.discard(window)
            gain = 0  # partially-executed carve: no credit claimed
        if self.metrics is not None and gain > 0:
            self.metrics.frag_recovered.inc(gain)
        logger.warning(
            "defrag window %s complete: %d chip(s) of largest-free-"
            "shape recovered in pool %s/%s", window, gain, *key)
