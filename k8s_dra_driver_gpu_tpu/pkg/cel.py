"""CEL-subset evaluator for DRA device selectors.

The scheduler side of DRA evaluates each ``selectors[].cel.expression``
against a ``device`` variable (KEP-4381; upstream
k8s.io/dynamic-resource-allocation/cel/compile.go builds the real env).
The reference driver never evaluates CEL itself -- it only *emits*
devices and lets kube-scheduler match them -- but proving our published
slices against our shipped selectors requires a scheduler, and a
scheduler requires an evaluator. This implements the grammar that DRA
selectors actually use:

- literals: strings, ints, floats, booleans
- ``device.driver``, ``device.attributes["<driver>"].<name>`` (and
  index form), ``device.capacity["<driver>"].<name>``
- ``"name" in device.attributes["<driver>"]``
- ``!``, ``&&``, ``||`` with CEL's error-absorption semantics
  (``false && error == false``, ``true || error == true``)
- comparisons ``== != < <= > >=``
- ``quantity("1Gi")`` and quantity methods ``compareTo``,
  ``isGreaterThan``, ``isLessThan``, ``asInteger``
- string methods ``matches``, ``startsWith``, ``endsWith``,
  ``contains``

Attribute values arrive in DRA's typed-union wire form
(``{"string": s} | {"int": n} | {"bool": b} | {"version": v}``) and are
unwrapped to CEL scalars, mirroring the real env's attribute binding.

Anything outside the subset raises ``CelParseError`` at compile time --
loud, so a selector we cannot faithfully evaluate is a test failure,
not a silent mismatch.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from functools import lru_cache


class CelParseError(Exception):
    """The expression is outside the supported CEL subset."""


class CelEvalError(Exception):
    """Runtime evaluation error (missing key, type mismatch).

    Real CEL propagates errors unless absorbed by && / ||; the DRA
    scheduler treats an errored selector as "device does not match".
    """


# -- quantities ---------------------------------------------------------------

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<digits>[0-9]+(?:\.[0-9]+)?)"
    r"(?P<suffix>Ki|Mi|Gi|Ti|Pi|Ei|k|M|G|T|P|E|m|)$")

_SUFFIX = {
    "": 1, "k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12,
    "P": 10**15, "E": 10**18,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40,
    "Pi": 2**50, "Ei": 2**60,
}


@dataclass(frozen=True)
class Quantity:
    """A k8s resource.Quantity scaled to milli-units internally so the
    ``m`` suffix and decimal forms compare exactly."""

    milli: int

    @classmethod
    def parse(cls, s: str) -> "Quantity":
        s = str(s).strip()
        # Scientific notation (129e6) used by canonical quantities.
        m = re.match(r"^([+-]?[0-9]+(?:\.[0-9]+)?)e([0-9]+)$", s)
        if m:
            return cls(milli=int(float(m.group(1)) * 10**int(m.group(2))
                                 * 1000))
        m = _QUANTITY_RE.match(s)
        if not m:
            raise CelEvalError(f"unparseable quantity {s!r}")
        sign = -1 if m.group("sign") == "-" else 1
        digits = m.group("digits")
        suffix = m.group("suffix")
        if suffix == "m":
            if "." in digits:
                raise CelEvalError(f"fractional milli quantity {s!r}")
            return cls(milli=sign * int(digits))
        scale = _SUFFIX[suffix]
        value = float(digits) if "." in digits else int(digits)
        return cls(milli=int(sign * value * scale * 1000))

    def compare_to(self, other: "Quantity") -> int:
        return (self.milli > other.milli) - (self.milli < other.milli)

    def as_integer(self) -> int:
        if self.milli % 1000:
            raise CelEvalError("asInteger() on fractional quantity")
        return self.milli // 1000


# -- lexer --------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<string>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*')
  | (?P<float>[0-9]+\.[0-9]+)
  | (?P<int>[0-9]+)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op>&&|\|\||==|!=|<=|>=|[!<>()\[\].,])
""", re.VERBOSE)

_KEYWORDS = {"true": True, "false": False}


def _lex(src: str) -> list[tuple[str, object]]:
    out: list[tuple[str, object]] = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if not m:
            raise CelParseError(f"bad character at {pos}: {src[pos:pos+10]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        text = m.group()
        if kind == "string":
            body = text[1:-1]
            out.append(("string", re.sub(r"\\(.)", r"\1", body)))
        elif kind == "float":
            out.append(("number", float(text)))
        elif kind == "int":
            out.append(("number", int(text)))
        elif kind == "ident":
            if text in _KEYWORDS:
                out.append(("bool", _KEYWORDS[text]))
            elif text == "in":
                out.append(("op", "in"))
            else:
                out.append(("ident", text))
        else:
            out.append(("op", text))
    out.append(("eof", None))
    return out


# -- parser (precedence climbing) --------------------------------------------

# AST nodes: ("lit", v) ("var", name) ("member", obj, name)
# ("index", obj, key) ("call", obj_or_None, name, args)
# ("not", e) ("and", l, r) ("or", l, r) ("cmp", op, l, r) ("in", l, r)


class _Parser:
    def __init__(self, tokens: list[tuple[str, object]]):
        self.toks = tokens
        self.i = 0

    def peek(self):
        return self.toks[self.i]

    def next(self):
        tok = self.toks[self.i]
        self.i += 1
        return tok

    def expect_op(self, op: str):
        kind, val = self.next()
        if kind != "op" or val != op:
            raise CelParseError(f"expected {op!r}, got {val!r}")

    def parse(self):
        e = self.parse_or()
        if self.peek()[0] != "eof":
            raise CelParseError(f"trailing tokens at {self.peek()!r}")
        return e

    def parse_or(self):
        left = self.parse_and()
        while self.peek() == ("op", "||"):
            self.next()
            left = ("or", left, self.parse_and())
        return left

    def parse_and(self):
        left = self.parse_cmp()
        while self.peek() == ("op", "&&"):
            self.next()
            left = ("and", left, self.parse_cmp())
        return left

    _CMP = {"==", "!=", "<", "<=", ">", ">="}

    def parse_cmp(self):
        left = self.parse_unary()
        kind, val = self.peek()
        if kind == "op" and val in self._CMP:
            self.next()
            return ("cmp", val, left, self.parse_unary())
        if kind == "op" and val == "in":
            self.next()
            return ("in", left, self.parse_unary())
        return left

    def parse_unary(self):
        if self.peek() == ("op", "!"):
            self.next()
            return ("not", self.parse_unary())
        return self.parse_postfix()

    def parse_postfix(self):
        e = self.parse_primary()
        while True:
            kind, val = self.peek()
            if (kind, val) == ("op", "."):
                self.next()
                nkind, name = self.next()
                if nkind != "ident":
                    raise CelParseError(f"expected member name, got {name!r}")
                if self.peek() == ("op", "("):
                    e = ("call", e, name, self.parse_args())
                else:
                    e = ("member", e, name)
            elif (kind, val) == ("op", "["):
                self.next()
                key = self.parse_or()
                self.expect_op("]")
                e = ("index", e, key)
            else:
                return e

    def parse_args(self):
        self.expect_op("(")
        args = []
        if self.peek() != ("op", ")"):
            args.append(self.parse_or())
            while self.peek() == ("op", ","):
                self.next()
                args.append(self.parse_or())
        self.expect_op(")")
        return args

    def parse_primary(self):
        kind, val = self.next()
        if kind in ("string", "number", "bool"):
            return ("lit", val)
        if kind == "ident":
            if self.peek() == ("op", "("):
                return ("call", None, val, self.parse_args())
            return ("var", val)
        if (kind, val) == ("op", "("):
            e = self.parse_or()
            self.expect_op(")")
            return e
        raise CelParseError(f"unexpected token {val!r}")


# -- evaluation ---------------------------------------------------------------


_UNION_KEYS = {"string", "int", "bool", "version", "value"}


@dataclass(frozen=True)
class SemVer:
    """A version-typed attribute: compares by semver components, not
    lexicographically (matching the real DRA CEL env's semver type)."""

    raw: str

    @property
    def key(self):
        core = self.raw.split("+", 1)[0]
        core, _, pre = core.partition("-")
        nums = tuple(int(p) for p in core.split(".") if p.isdigit())
        # A pre-release sorts before the release itself (semver 11).
        return (nums, 0 if pre else 1, pre)

    def compare_to(self, other: "SemVer") -> int:
        return (self.key > other.key) - (self.key < other.key)


def _unwrap_attr(value):
    """DRA typed-union attribute value -> CEL scalar; intermediate maps
    (attributes, capacity, per-driver maps) pass through unchanged.

    A union is exactly one key from the wire schema with a SCALAR
    payload -- both conditions matter, or a per-driver map containing a
    single attribute literally named "version"/"string"/... would be
    misread as a union and collapse the whole map."""
    if isinstance(value, dict) and len(value) == 1:
        key, v = next(iter(value.items()))
        if key in _UNION_KEYS and isinstance(v, (str, int, float, bool)):
            if key == "version":
                return SemVer(str(v))
            if key == "int":
                return int(v)
            if key == "value":
                return Quantity.parse(str(v))
            return v
    return value


class _Eval:
    def __init__(self, env: dict):
        self.env = env

    def run(self, node):
        op = node[0]
        return getattr(self, "_" + op)(node)

    def _lit(self, n):
        return n[1]

    def _var(self, n):
        if n[1] not in self.env:
            raise CelEvalError(f"unknown variable {n[1]!r}")
        return self.env[n[1]]

    def _member(self, n):
        obj = self.run(n[1])
        if isinstance(obj, dict):
            if n[2] not in obj:
                raise CelEvalError(f"no such key {n[2]!r}")
            return _unwrap_attr(obj[n[2]])
        raise CelEvalError(f"member access on {type(obj).__name__}")

    def _index(self, n):
        obj = self.run(n[1])
        key = self.run(n[2])
        if isinstance(obj, dict):
            if key not in obj:
                raise CelEvalError(f"no such key {key!r}")
            return _unwrap_attr(obj[key])
        raise CelEvalError(f"index on {type(obj).__name__}")

    def _not(self, n):
        v = self.run(n[1])
        if not isinstance(v, bool):
            raise CelEvalError("! on non-bool")
        return not v

    def _and(self, n):
        # CEL error absorption: false on either side wins.
        try:
            left = self.run(n[1])
        except CelEvalError:
            left = None
        if left is False:
            return False
        right = self.run(n[2])
        if right is False:
            return False
        if left is None:
            raise CelEvalError("errored && non-false")
        if not isinstance(left, bool) or not isinstance(right, bool):
            raise CelEvalError("&& on non-bool")
        return left and right

    def _or(self, n):
        try:
            left = self.run(n[1])
        except CelEvalError:
            left = None
        if left is True:
            return True
        right = self.run(n[2])
        if right is True:
            return True
        if left is None:
            raise CelEvalError("errored || non-true")
        if not isinstance(left, bool) or not isinstance(right, bool):
            raise CelEvalError("|| on non-bool")
        return left or right

    def _in(self, n):
        key = self.run(n[1])
        obj = self.run(n[2])
        if isinstance(obj, (dict, list)):
            return key in obj
        raise CelEvalError(f"'in' on {type(obj).__name__}")

    def _cmp(self, n):
        _, op, ln, rn = n
        left, right = self.run(ln), self.run(rn)
        if isinstance(left, SemVer) or isinstance(right, SemVer):
            if isinstance(left, str):
                left = SemVer(left)
            if isinstance(right, str):
                right = SemVer(right)
            if not (isinstance(left, SemVer) and isinstance(right, SemVer)):
                raise CelEvalError("version compared to non-version")
            c = left.compare_to(right)
            return {"==": c == 0, "!=": c != 0, "<": c < 0,
                    "<=": c <= 0, ">": c > 0, ">=": c >= 0}[op]
        if isinstance(left, Quantity) or isinstance(right, Quantity):
            raise CelEvalError("quantities compare via compareTo()")
        if isinstance(left, bool) != isinstance(right, bool):
            raise CelEvalError("bool compared to non-bool")
        num = (int, float)
        if not (isinstance(left, num) and isinstance(right, num)):
            if type(left) is not type(right):
                # CEL: comparing different types is an error, not False.
                raise CelEvalError(
                    f"type mismatch {type(left).__name__} {op} "
                    f"{type(right).__name__}")
        try:
            return {
                "==": left == right, "!=": left != right,
                "<": left < right, "<=": left <= right,
                ">": left > right, ">=": left >= right,
            }[op]
        except TypeError as e:  # e.g. < on bools
            raise CelEvalError(str(e)) from e

    def _call(self, n):
        _, obj_node, name, arg_nodes = n
        args = [self.run(a) for a in arg_nodes]
        if obj_node is None:
            if name == "quantity" and len(args) == 1:
                return Quantity.parse(args[0])
            if name == "semver" and len(args) == 1:
                return SemVer(str(args[0]))
            raise CelEvalError(f"unknown function {name}()")
        obj = self.run(obj_node)
        if isinstance(obj, SemVer):
            if name == "compareTo" and len(args) == 1:
                other = args[0]
                if isinstance(other, str):
                    other = SemVer(other)
                if not isinstance(other, SemVer):
                    raise CelEvalError("compareTo non-version")
                return obj.compare_to(other)
        if isinstance(obj, Quantity):
            if name == "compareTo" and len(args) == 1:
                return obj.compare_to(_as_quantity(args[0]))
            if name == "isGreaterThan" and len(args) == 1:
                return obj.compare_to(_as_quantity(args[0])) > 0
            if name == "isLessThan" and len(args) == 1:
                return obj.compare_to(_as_quantity(args[0])) < 0
            if name == "asInteger" and not args:
                return obj.as_integer()
        if isinstance(obj, str):
            if name == "matches" and len(args) == 1:
                return re.search(args[0], obj) is not None
            if name == "startsWith" and len(args) == 1:
                return obj.startswith(args[0])
            if name == "endsWith" and len(args) == 1:
                return obj.endswith(args[0])
            if name == "contains" and len(args) == 1:
                return args[0] in obj
        raise CelEvalError(
            f"unsupported method .{name}() on {type(obj).__name__}")


def _as_quantity(v) -> Quantity:
    if isinstance(v, Quantity):
        return v
    if isinstance(v, (int, str)):
        return Quantity.parse(str(v))
    raise CelEvalError(f"not a quantity: {v!r}")


@lru_cache(maxsize=1024)
def _compile_ast(expression: str):
    """Memoized lex+parse keyed by source text. Selector expressions
    repeat across candidate devices within a scheduling pass AND across
    passes (the same DeviceClass/request selectors are evaluated for
    every device every sync), so the AST is compiled once per distinct
    source string. ASTs are immutable tuples -- safe to share across
    threads and CelProgram instances. Parse failures are NOT cached
    (lru_cache does not memoize exceptions); callers that want negative
    caching layer it on top (scheduler._CompiledSelectors does)."""
    return _Parser(_lex(expression)).parse()


class CelProgram:
    """A compiled selector expression, reusable across devices."""

    def __init__(self, expression: str):
        self.expression = expression
        self._ast = _compile_ast(expression)

    def evaluate(self, env: dict):
        return _Eval(env).run(self._ast)

    def matches_device(self, device: dict, driver: str, pool: str = "",
                       node: str = "") -> bool:
        """Evaluate against a published ResourceSlice device entry.

        Builds the same ``device`` variable the scheduler binds
        (driver/attributes/capacity keyed by the owning driver name).
        Errors mean "does not match", as in the real scheduler.
        """
        env = {"device": {
            "driver": driver,
            "attributes": {driver: dict(device.get("attributes", {}))},
            "capacity": {driver: {
                name: (val if isinstance(val, dict) else {"value": val})
                for name, val in device.get("capacity", {}).items()
            }},
        }}
        try:
            result = self.evaluate(env)
        except CelEvalError:
            return False
        if not isinstance(result, bool):
            return False
        return result


def compile_expression(expression: str) -> CelProgram:
    return CelProgram(expression)
