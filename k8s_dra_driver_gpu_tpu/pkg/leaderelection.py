"""Lease-based leader election for controller HA.

Reference: cmd/compute-domain-controller/main.go:277-377 -- k8s Lease
(coordination.k8s.io/v1) leader election with ReleaseOnCancel, 30s lease
/ 10s renew / 2s retry (upstream defaults).
"""

from __future__ import annotations

import logging
import threading
import time
from datetime import datetime, timezone

from . import json_copy
from .kubeclient import ConflictError, NotFoundError

logger = logging.getLogger(__name__)

LEASE_DURATION_S = 30
RENEW_PERIOD_S = 10
RETRY_PERIOD_S = 2


def _now() -> str:
    return datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%fZ")


class LeaderElector:
    def __init__(
        self,
        kube,
        lease_name: str,
        namespace: str,
        identity: str,
        lease_duration: float = LEASE_DURATION_S,
        renew_period: float = RENEW_PERIOD_S,
        retry_period: float = RETRY_PERIOD_S,
    ):
        self.kube = kube
        self.lease_name = lease_name
        self.namespace = namespace
        self.identity = identity
        self.lease_duration = lease_duration
        self.renew_period = renew_period
        self.retry_period = retry_period
        self.is_leader = False
        # Lease expiry is judged from when THIS process last observed the
        # lease record change (client-go semantics), never by comparing
        # the remote renewTime against the local wall clock -- clock skew
        # between replicas must not open a dual-leader window.
        self._observed_record: tuple[str, str] | None = None
        self._observed_at: float = 0.0

    # -- lease CRUD -------------------------------------------------------------

    def _lease_obj(self) -> dict:
        return {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"name": self.lease_name,
                         "namespace": self.namespace},
            "spec": {
                "holderIdentity": self.identity,
                "leaseDurationSeconds": int(self.lease_duration),
                "acquireTime": _now(),
                "renewTime": _now(),
            },
        }

    def try_acquire_or_renew(self) -> bool:
        """Never raises: any API failure reads as 'did not get the lease',
        so a transient apiserver error makes the leader step down rather
        than split-brain (the renew loop treats False as lost)."""
        try:
            return self._try_acquire_or_renew()
        except Exception:  # noqa: BLE001 - lease RPC boundary
            logger.exception("lease operation failed")
            return False

    def _try_acquire_or_renew(self) -> bool:
        try:
            lease = self.kube.get("coordination.k8s.io", "v1", "leases",
                                  self.lease_name, namespace=self.namespace)
        except NotFoundError:
            try:
                self.kube.create("coordination.k8s.io", "v1", "leases",
                                 self._lease_obj(), namespace=self.namespace)
                return True
            except ConflictError:
                return False
        spec = lease.get("spec", {})
        holder = spec.get("holderIdentity", "")
        record = (holder, spec.get("renewTime", ""))
        now = time.monotonic()
        if record != self._observed_record:
            # Fresh activity: restart the local expiry clock.
            self._observed_record = record
            self._observed_at = now
        expired = now - self._observed_at > self.lease_duration
        # An empty holder means the previous leader released on cancel.
        if holder and holder != self.identity and not expired:
            return False
        # Mutate a deep copy, never the fetched object (TPUDRA006);
        # setdefault also re-attaches the spec -- the old
        # `lease.get("spec", {})` silently DROPPED the holder write for
        # a lease that had no spec at all.
        lease = json_copy(lease)
        spec = lease.setdefault("spec", {})
        spec["holderIdentity"] = self.identity
        spec["renewTime"] = _now()
        if holder != self.identity:
            spec["acquireTime"] = _now()
        try:
            self.kube.update("coordination.k8s.io", "v1", "leases",
                             self.lease_name, lease,
                             namespace=self.namespace)
            return True
        except (ConflictError, NotFoundError):
            return False

    def release(self) -> None:
        """ReleaseOnCancel: zero the holder so a peer takes over fast."""
        try:
            lease = self.kube.get("coordination.k8s.io", "v1", "leases",
                                  self.lease_name, namespace=self.namespace)
        except NotFoundError:
            return
        if lease.get("spec", {}).get("holderIdentity") != self.identity:
            return
        lease = json_copy(lease)
        lease["spec"]["holderIdentity"] = ""
        try:
            self.kube.update("coordination.k8s.io", "v1", "leases",
                             self.lease_name, lease,
                             namespace=self.namespace)
        except (ConflictError, NotFoundError):
            pass

    # -- loop ---------------------------------------------------------------------

    def run(self, lead_fn, stop: threading.Event) -> None:
        """Block until stop; call lead_fn() (blocking) while leading."""
        while not stop.is_set():
            if self.try_acquire_or_renew():
                self.is_leader = True
                logger.info("%s acquired lease %s", self.identity,
                            self.lease_name)
                renew_stop = threading.Event()

                def renew_loop():
                    while not renew_stop.wait(self.renew_period):
                        if not self.try_acquire_or_renew():
                            logger.warning("lost lease %s", self.lease_name)
                            self.is_leader = False
                            stop.set()
                            return

                t = threading.Thread(target=renew_loop, daemon=True)
                t.start()
                try:
                    lead_fn()
                finally:
                    renew_stop.set()
                    t.join(timeout=2)
                    self.release()
                    self.is_leader = False
                return
            stop.wait(self.retry_period)
