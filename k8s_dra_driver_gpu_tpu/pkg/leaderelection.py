"""Lease-based leader election for controller HA.

Reference: cmd/compute-domain-controller/main.go:277-377 -- k8s Lease
(coordination.k8s.io/v1) leader election with ReleaseOnCancel, 30s lease
/ 10s renew / 2s retry (upstream defaults).
"""

from __future__ import annotations

import logging
import threading
import time
from datetime import datetime, timezone

from . import json_copy
from .kubeclient import ConflictError, NotFoundError

logger = logging.getLogger(__name__)

LEASE_DURATION_S = 30
RENEW_PERIOD_S = 10
RETRY_PERIOD_S = 2


def _now() -> str:
    return datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%fZ")


class LeaderElector:
    def __init__(
        self,
        kube,
        lease_name: str,
        namespace: str,
        identity: str,
        lease_duration: float = LEASE_DURATION_S,
        renew_period: float = RENEW_PERIOD_S,
        retry_period: float = RETRY_PERIOD_S,
    ):
        self.kube = self._lease_client(kube, renew_period)
        self.lease_name = lease_name
        self.namespace = namespace
        self.identity = identity
        self.lease_duration = lease_duration
        self.renew_period = renew_period
        self.retry_period = retry_period
        self.is_leader = False
        # Lease expiry is judged from when THIS process last observed the
        # lease record change (client-go semantics), never by comparing
        # the remote renewTime against the local wall clock -- clock skew
        # between replicas must not open a dual-leader window.
        self._observed_record: tuple[str, str] | None = None
        self._observed_at: float = 0.0

    @staticmethod
    def _lease_client(kube, renew_period: float):
        """Rebuild a RetryingKubeClient with a deadline BOUNDED by the
        renew period. Lease RPCs are latency-critical liveness signals:
        a renew parked inside a 30s retry budget while the server-side
        lease expires at 30s hands a peer the lease while this process
        still believes it leads (dual leader). One quick attempt +
        short retries per renew tick is the client-go shape; the renew
        LOOP is the retry mechanism. Non-wrapped clients pass through
        unchanged."""
        policy = getattr(kube, "policy", None)
        inner = getattr(kube, "kube", None)
        if policy is None or inner is None:
            return kube
        import dataclasses  # noqa: PLC0415

        from .retry import RetryingKubeClient  # noqa: PLC0415

        deadline = max(1.0, min(policy.deadline_s, renew_period * 0.8))
        return RetryingKubeClient(
            inner,
            policy=dataclasses.replace(
                policy, deadline_s=deadline,
                attempt_timeout_s=min(policy.attempt_timeout_s, deadline)),
            breaker=kube.breaker, metrics=kube.metrics)

    # -- lease CRUD -------------------------------------------------------------

    def _lease_obj(self) -> dict:
        return {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"name": self.lease_name,
                         "namespace": self.namespace},
            "spec": {
                "holderIdentity": self.identity,
                "leaseDurationSeconds": int(self.lease_duration),
                "acquireTime": _now(),
                "renewTime": _now(),
            },
        }

    def try_acquire_or_renew(self) -> bool:
        """Never raises: any API failure reads as 'did not get the
        lease'. The renew loop distinguishes LOST (another holder owns
        it -- step down now) from ERROR (apiserver blip -- tolerated up
        to the lease duration, because our lease stays valid on the
        server for that long) via _renew_once."""
        return self._renew_once() == "ok"

    def _renew_once(self) -> str:
        """'ok' | 'lost' | 'error' -- the tri-state the renew loop's
        step-down policy needs. A transient apiserver error must NOT
        read the same as a peer seizing the lease: stepping down on the
        first blip turns every apiserver hiccup into a leadership churn,
        while ignoring a real loss splits the brain."""
        try:
            return "ok" if self._try_acquire_or_renew() else "lost"
        except Exception:  # noqa: BLE001 - lease RPC boundary
            logger.exception("lease operation failed")
            return "error"

    def _try_acquire_or_renew(self) -> bool:
        try:
            lease = self.kube.get("coordination.k8s.io", "v1", "leases",
                                  self.lease_name, namespace=self.namespace)
        except NotFoundError:
            try:
                self.kube.create("coordination.k8s.io", "v1", "leases",
                                 self._lease_obj(), namespace=self.namespace)
                return True
            except ConflictError:
                return False
        spec = lease.get("spec", {})
        holder = spec.get("holderIdentity", "")
        record = (holder, spec.get("renewTime", ""))
        now = time.monotonic()
        if record != self._observed_record:
            # Fresh activity: restart the local expiry clock.
            self._observed_record = record
            self._observed_at = now
        expired = now - self._observed_at > self.lease_duration
        # An empty holder means the previous leader released on cancel.
        if holder and holder != self.identity and not expired:
            return False
        # Mutate a deep copy, never the fetched object (TPUDRA006);
        # setdefault also re-attaches the spec -- the old
        # `lease.get("spec", {})` silently DROPPED the holder write for
        # a lease that had no spec at all.
        lease = json_copy(lease)
        spec = lease.setdefault("spec", {})
        spec["holderIdentity"] = self.identity
        spec["renewTime"] = _now()
        if holder != self.identity:
            spec["acquireTime"] = _now()
        try:
            self.kube.update("coordination.k8s.io", "v1", "leases",
                             self.lease_name, lease,
                             namespace=self.namespace)
            return True
        except (ConflictError, NotFoundError):
            return False

    def release(self) -> None:
        """ReleaseOnCancel: zero the holder so a peer takes over fast.
        Genuinely best-effort -- the error-budget step-down path calls
        this precisely when the apiserver is unreachable, and a raise
        here would turn a clean step-down into a crash (the lease then
        simply expires server-side)."""
        try:
            lease = self.kube.get("coordination.k8s.io", "v1", "leases",
                                  self.lease_name, namespace=self.namespace)
            if lease.get("spec", {}).get("holderIdentity") != self.identity:
                return
            lease = json_copy(lease)
            lease["spec"]["holderIdentity"] = ""
            self.kube.update("coordination.k8s.io", "v1", "leases",
                             self.lease_name, lease,
                             namespace=self.namespace)
        except (ConflictError, NotFoundError):
            pass
        except Exception:  # noqa: BLE001 - lease RPC boundary
            logger.exception("lease release failed (will expire "
                             "server-side)")

    # -- loop ---------------------------------------------------------------------

    def run(self, lead_fn, stop: threading.Event,
            on_stopped_leading=None) -> None:
        """Block until stop; call lead_fn() (blocking) while leading.

        Renew-failure policy (the zombie-holder fix): a DEFINITIVE loss
        (another identity holds a live lease) steps down immediately; a
        transient renew ERROR (apiserver blip) is tolerated while our
        server-side lease is still within its duration -- the lease
        protects us from challengers for exactly that long -- and only
        REPEATED errors past that budget force a clean step-down. Either
        way ``on_stopped_leading`` fires EXACTLY ONCE per leadership
        term (never on a normal external stop before leading ends it),
        ``stop`` is set, and the lease is released (best effort)."""
        while not stop.is_set():
            if self.try_acquire_or_renew():
                self.is_leader = True
                logger.info("%s acquired lease %s", self.identity,
                            self.lease_name)
                renew_stop = threading.Event()
                fired = threading.Lock()
                fired_once = [False]

                def stopped_leading(reason: str) -> None:
                    """Idempotent step-down: exactly one caller -- the
                    renew loop or the run() finally -- gets to fire the
                    callback and flip the flags."""
                    with fired:
                        if fired_once[0]:
                            return
                        fired_once[0] = True
                    logger.warning("stepping down from lease %s: %s",
                                   self.lease_name, reason)
                    self.is_leader = False
                    if on_stopped_leading is not None:
                        try:
                            on_stopped_leading()
                        except Exception:  # noqa: BLE001 - consumer hook
                            logger.exception("on_stopped_leading failed")
                    stop.set()

                def renew_loop():
                    # The error budget is anchored at the LAST
                    # SUCCESSFUL renew: that is when the server-side
                    # lease clock restarted, so it bounds how long we
                    # may claim leadership through an outage -- wall
                    # time spent BLOCKED inside a failing renew call
                    # counts against it (anchoring at the first failed
                    # *return* would not).
                    last_ok = time.monotonic()
                    while not renew_stop.wait(self.renew_period):
                        result = self._renew_once()
                        now = time.monotonic()
                        if result == "ok":
                            last_ok = now
                            continue
                        if result == "lost":
                            stopped_leading("lease lost to another holder")
                            return
                        # Transient error: our lease stays valid
                        # server-side for lease_duration from the last
                        # successful renew -- keep leading inside that
                        # window (minus one renew period of margin)
                        # instead of churning on one blip.
                        budget = max(
                            self.lease_duration - self.renew_period, 0.0)
                        if now - last_ok >= budget:
                            stopped_leading(
                                f"renew failing for {now - last_ok:.1f}s"
                                " (lease may have expired server-side)")
                            return
                        logger.warning(
                            "lease %s renew error; retaining leadership "
                            "%.1fs more before stepping down",
                            self.lease_name,
                            budget - (now - last_ok))

                t = threading.Thread(target=renew_loop, daemon=True,
                                     name=f"lease-renew-{self.lease_name}")
                t.start()
                try:
                    lead_fn()
                finally:
                    renew_stop.set()
                    t.join(timeout=2)
                    # Normal exit path (external stop): no step-down
                    # callback fired yet and none is due -- leading
                    # ended because lead_fn returned, not because the
                    # lease was lost. Mark the term closed so a renew
                    # race can't fire the callback after release.
                    with fired:
                        fired_once[0] = True
                    self.release()
                    self.is_leader = False
                return
            stop.wait(self.retry_period)
