"""A DRA-aware scheduler + resourceclaim controller stand-in.

The reference never ships this logic -- it relies on the real
kube-scheduler's DRA plugin and kube-controller-manager's resourceclaim
controller (vendored under k8s.io/dynamic-resource-allocation). Our
first-contact tier has no kubelet or scheduler binaries available, so
this module implements the two control-plane behaviors the e2e tier
needs, faithfully enough that the REAL driver binaries cannot tell the
difference:

1. **Claim generation** (kcm resourceclaim controller): a pod whose
   ``spec.resourceClaims[]`` entry names a ``resourceClaimTemplateName``
   gets a generated ResourceClaim (owner-ref'd to the pod) and a
   ``status.resourceClaimStatuses`` mapping.
2. **Allocation** (kube-scheduler DRA plugin, structured parameters
   KEP-4381): for each unallocated claim, walk published
   ResourceSlices at their newest pool generation, filter devices
   through DeviceClass + request CEL selectors (pkg/cel.py), skip
   devices already allocated or tainted NoSchedule/NoExecute (unless
   tolerated), enforce KEP-4815 shared-counter budgets so partitioned
   devices can never over-commit their parent, then write
   ``status.allocation`` (results + config + nodeSelector) and reserve
   the claim for its consumer pods.
3. **Binding**: pods whose claims are all allocated get
   ``spec.nodeName`` patched to the (single) node the allocation pins.

Two execution modes share the same sync logic:

- **Polled** (``run(interval)``): the historical full-resync loop --
  every pass re-reads the world. Kept as the compatibility mode and as
  the low-frequency safety resync.
- **Event-driven** (``start_event_driven()``): informers
  (pkg/schedcache.ClusterView) feed per-object events into a keyed
  workqueue (pkg/workqueue); ``sync`` work degrades to draining dirty
  keys -- O(changes), not O(cluster) per tick -- with a low-frequency
  full resync as the safety net. Inventory state is served from an
  indexed snapshot rebuilt only when a ResourceSlice actually changes.

Used by the executable e2e tier (TPU_DRA_E2E=fake) and runnable as a
standalone control-plane binary:

    python -m k8s_dra_driver_gpu_tpu.pkg.scheduler --kube-api http://...
"""

from __future__ import annotations

import argparse
import logging
import os
import threading
import time
import uuid

from . import fleetstate, flightrecorder, tracing
from .defrag import (
    DEFRAG_TARGET_ANNOTATION,
    claim_device_demand as _defrag_claim_demand,
    parse_target_hint as _parse_defrag_hint,
)
from .events import emit_warning_event
from .featuregates import (
    TOPOLOGY_AWARE_PLACEMENT,
    FeatureGateError,
    FeatureGates,
)
from .kubeclient import ConflictError, KubeError, NotFoundError
from .schedcache import (
    DOMAIN_ANNOTATION,
    SPILLED_FROM_ANNOTATION,
    SPILLOVER_ANNOTATION,
    SPILLOVER_HOPS_ANNOTATION,
    AllocationState,
    Candidate as _Candidate,
    ClusterView,
    CompiledSelectors as _CompiledSelectors,
    CounterLedger as _CounterLedger,
    InventorySnapshot,
    NodeLockManager,
    SchedulingDomain,
    _ORDER_MISS,
    pool_key_of,
    tolerates as _tolerates,
)
from .topology import TorusGrid, largest_free_shape
from .topology.score import frag_from_largest
from .topology import order_candidates as topo_order_candidates
from .topology import set_compactness

logger = logging.getLogger(__name__)

RESOURCE = ("resource.k8s.io", "v1")

# Safety-net full-resync period for the event-driven mode: dirty keys
# carry the steady state, this only catches watch gaps and software
# bugs. Override with TPU_DRA_SCHED_RESYNC (seconds).
DEFAULT_RESYNC_S = 30.0

# Sync-queue worker count (event mode). 1 = the historical serialized
# drain; N > 1 shards claim/pod keys over N-1 data workers plus one
# dedicated control-key worker (full resync, inventory, recovery --
# which therefore can never starve behind a claim flood). Override
# with --sched-workers / TPU_DRA_SCHED_WORKERS.
DEFAULT_SCHED_WORKERS = 1

# Max dirty claim keys drained against ONE inventory snapshot /
# device-class read (amortizes snapshot signature checks and static-CEL
# memo warmup across a burst). Override with TPU_DRA_SCHED_BATCH.
DEFAULT_SCHED_BATCH = 8

# Dirty-key kinds handled by the dedicated control worker (shard 0).
_CTL_KINDS = frozenset((
    "full", "pending", "inventory", "daemonsets", "jobs", "recovery",
    "defrag", "autoscale", "migration", "pods-rescan",
))


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _meta(obj):
    return obj.get("metadata", {})


# Deep-copy discipline for API objects lives in one place now
# (pkg.json_copy); re-exported here for the existing import sites.
from . import json_copy  # noqa: E402,F401


class _FitBudgetExceeded(Exception):
    """The bounded constraint DFS ran out of states (see MAX_FIT_STEPS)."""


class DraScheduler:
    """Single-pass-capable scheduler; call sync_once(), run(), or
    start_event_driven()."""

    def __init__(self, kube, default_node: str | None = None,
                 gates: FeatureGates | None = None, metrics=None,
                 sched_metrics=None, resync_period: float | None = None,
                 workers: int | None = None, batch_max: int | None = None,
                 domain: SchedulingDomain | None = None,
                 fleet_metrics=None):
        self.kube = kube
        self.default_node = default_node
        self._selectors = _CompiledSelectors()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if workers is None:
            workers = _env_int("TPU_DRA_SCHED_WORKERS",
                               DEFAULT_SCHED_WORKERS)
        self.sched_workers = max(1, workers)
        if batch_max is None:
            batch_max = _env_int("TPU_DRA_SCHED_BATCH",
                                 DEFAULT_SCHED_BATCH)
        self.batch_max = max(1, batch_max)
        # Partitioned scheduling domain (scheduler-per-pool sharding):
        # None = this instance owns everything (the historical shape).
        self.domain = domain if domain is not None \
            else SchedulingDomain.from_env()
        # Cluster-wide controllers (DaemonSet/Job sync, recovery) run
        # in exactly one domain; non-default domain instances only
        # allocate/bind their own claims and pods.
        self._cluster_controllers = (self.domain is None
                                     or self.domain.default)
        if gates is None:
            try:
                gates = FeatureGates.from_env()
            except FeatureGateError:
                # A malformed FEATURE_GATES env must not kill the
                # control plane; defaults are the safe fallback.
                logger.exception("FEATURE_GATES unparseable; using defaults")
                gates = FeatureGates()
        self.gates = gates
        # ICI topology-aware device picking (pkg/topology). Off = the
        # historical first-fit order, which also remains the automatic
        # fallback whenever devices publish no usable coordinates.
        self._topology = gates.is_enabled(TOPOLOGY_AWARE_PLACEMENT)
        self.metrics = metrics  # PlacementMetrics or None
        self.sched_metrics = sched_metrics  # SchedulerMetrics or None
        if resync_period is None:
            try:
                resync_period = float(os.environ.get(
                    "TPU_DRA_SCHED_RESYNC", DEFAULT_RESYNC_S))
            except ValueError:
                resync_period = DEFAULT_RESYNC_S
        self.resync_period = resync_period
        # Cross-domain claim spillover (pkg/schedcache annotations):
        # enabled for pool-restricted domains with configured siblings
        # unless the master switch turns it off. Knobs:
        # TPU_DRA_SPILLOVER (default on), TPU_DRA_SPILLOVER_MAX_HOPS,
        # TPU_DRA_SPILLOVER_ORDER_WEIGHT / _UTIL_WEIGHT.
        self._spillover_enabled = os.environ.get(
            "TPU_DRA_SPILLOVER", "1") not in ("0", "false", "False")
        self._spillover_max_hops = _env_int(
            "TPU_DRA_SPILLOVER_MAX_HOPS", 1)
        # Migration-cost weights (2502.01909's multi-objective
        # placement framing, collapsed to the spill decision's two
        # live terms): the operator's sibling ORDER is the stated
        # preference, the sibling's current utilization is the
        # congestion cost of moving there.
        try:
            self._spill_order_weight = float(os.environ.get(
                "TPU_DRA_SPILLOVER_ORDER_WEIGHT", "1.0"))
        except ValueError:
            self._spill_order_weight = 1.0
        try:
            self._spill_util_weight = float(os.environ.get(
                "TPU_DRA_SPILLOVER_UTIL_WEIGHT", "10.0"))
        except ValueError:
            self._spill_util_weight = 10.0
        # Sibling-capacity memo: (expires, {sibling -> (free, total)}).
        # Spill decisions are rare (exhaustion events), but an
        # exhausted-domain claim FLOOD must not scan claims per claim.
        # The lock makes rank+debit atomic across sharded workers --
        # two workers spilling concurrently must not both judge the
        # same pre-debit free count and overshoot the sibling.
        self._spill_capacity_memo: tuple[float, dict] | None = None
        self._spill_lock = threading.Lock()
        # All reads in sync paths go through the view (lint TPUDRA009):
        # informer caches in event mode, list-through in direct mode.
        self.view = ClusterView(
            kube, on_event=self._on_informer_event,
            on_relist=self._on_informer_relist,
            default_node=default_node,
            pool_filter=(self.domain.owns_pool
                         if self.domain is not None and self.domain.pools
                         else None),
            on_snapshot_build=self._on_snapshot_build,
            on_snapshot_delta=self._on_snapshot_delta,
            on_relist_backoff=self._on_relist_backoff)
        # Inventory snapshot + incrementally-maintained allocation
        # state; rebuilt whenever the snapshot changes and on every
        # full pass (the safety property of the resync).
        self._snap: InventorySnapshot | None = None
        self._alloc: AllocationState | None = None
        # Registry lock: guards the snapshot/alloc-state IDENTITY, the
        # commit log, and the pod<->claim indexes. Held briefly only --
        # never across kube I/O or a fit (lint TPUDRA010). Fine-grained
        # allocation safety lives in the per-node locks + the
        # AllocationState's atomic try_commit instead, so disjoint
        # allocations commit in parallel. Documented hierarchy:
        # node locks -> _state_lock -> AllocationState._alloc_lock.
        self._state_lock = threading.RLock()
        # Per-node allocation locks: same-node contenders serialize,
        # gang/CD-window claims take their window as one sorted lock
        # set, commit kube I/O is sanctioned under these only.
        self._node_locks = NodeLockManager()
        # Allocations THIS scheduler committed recently, replayed into
        # every rebuilt AllocationState: with a real apiserver the
        # informer cache can lag our own allocation patch, and a
        # rebuild from that stale cache would otherwise see the devices
        # as free and double-allocate them. Entries retire when the
        # cache catches up (the claim's watch event carries the
        # allocation) or after the TTL.
        self._commit_log: dict[tuple[str, str], tuple[float, dict]] = {}
        # Event mode plumbing.
        self._queue = None  # WorkQueue, created by start_event_driven
        self._resync_thread: threading.Thread | None = None
        # pod <-> claim reverse index (event mode): which pods to
        # re-check when a claim changes, without scanning all pods.
        self._pods_of_claim: dict[tuple[str, str], set[str]] = {}
        self._claims_of_pod: dict[tuple[str, str], set[str]] = {}
        # Permanent-failure recovery (pkg/recovery.EvictionController):
        # attached controllers ride this scheduler's sync loop (node /
        # slice / claim events + the safety resync) and veto allocation
        # onto permanently failed nodes.
        self.recovery = None
        # Active defragmentation (pkg/defrag.DefragController): rides
        # the same loop (full passes + claim events while moves are in
        # flight); its device reservations veto allocation off carve
        # cells and move targets, and its placement hints steer the
        # re-placement of moving claims.
        self.defrag = None
        # Serving autoscaler (pkg/autoscale.AutoscaleController):
        # rides the same loop (full passes + PartitionSet CRD events)
        # and re-plans the fleet's partition layout from live tenant
        # demand; its rollouts land as CRD writes the node plugins'
        # watchers converge on.
        self.autoscaler = None
        # Cooperative migration (pkg/migration.MigrationController):
        # rides the same loop (full passes + claim events while
        # handshakes are in flight); its destination reservations veto
        # allocation exactly like defrag's, and its switch stage
        # stamps the defrag placement hint to steer the re-placement.
        self.migration = None
        # Claim-lifecycle flight recorder (pkg/flightrecorder): every
        # dirty-key enqueue / fit outcome / commit conflict / patch
        # lands in the bounded ring served at /debug/claims.
        self.flight = flightrecorder.default()
        # Fleet telemetry aggregator (pkg/fleetstate): every full pass
        # folds the inventory snapshot + allocation state + published
        # node-telemetry attributes into per-pool utilization /
        # fragmentation time-series (served at /debug/fleet, exported
        # through FleetMetrics when the registry is wired). The
        # process default (/debug/fleet, doctor bundles) is claimed
        # lazily on the FIRST fold -- a merely-constructed scheduler
        # (tests build several per process) never repoints the live
        # one's endpoint at an empty aggregator.
        self.fleet = fleetstate.FleetAggregator(metrics=fleet_metrics)
        self._fleet_installed = False
        # Per-worker fit-phase start time (SLO phase accounting).
        self._fit_tls = threading.local()

    @property
    def _slo(self):
        """The claim-lifecycle SLO histogram (ClaimSLOMetrics), or
        None when this scheduler runs metrics-less."""
        return (self.sched_metrics.slo
                if self.sched_metrics is not None else None)

    def attach_recovery(self, controller) -> "DraScheduler":
        """Drive a pkg/recovery.EvictionController from this
        scheduler's loop: its sync runs inside every full pass and on
        node / slice / eviction-relevant claim dirty keys, its reads
        come from this scheduler's informer-backed view (zero kube
        lists per pass in event mode), and allocation
        (``_candidate_nodes``) excludes the nodes it has declared
        permanently failed."""
        controller.view = self.view
        # Eviction e2e latency reports into the shared claim-SLO
        # histogram (phase="evict") on this scheduler's registry.
        if self.sched_metrics is not None:
            controller.slo = self.sched_metrics.slo
        self.recovery = controller
        return self

    def attach_defrag(self, controller) -> "DraScheduler":
        """Drive a pkg/defrag.DefragController from this scheduler's
        loop: its sync runs inside every full pass (after the fleet
        fold, so the frag rings it triggers on are fresh) and on claim
        dirty keys while moves are in flight; its reads come from this
        scheduler's informer-backed view; allocation honors its
        placement hints and vetoes its device reservations."""
        controller.view = self.view
        if controller.fleet is None:
            # The trigger signal reads THIS scheduler's fleet rings.
            controller.fleet = self.fleet
        self.defrag = controller
        return self

    def attach_migration(self, controller) -> "DraScheduler":
        """Drive a pkg/migration.MigrationController from this
        scheduler's loop: its sync runs inside every full pass (right
        after recovery, so a freshly switched claim re-places in the
        SAME pass) and on claim dirty keys while handshakes are in
        flight; its reads come from this scheduler's informer-backed
        view; allocation vetoes its destination reservations alongside
        the defrag controller's."""
        controller.view = self.view
        self.migration = controller
        return self

    def attach_autoscaler(self, controller) -> "DraScheduler":
        """Drive a pkg/autoscale.AutoscaleController from this
        scheduler's loop: its sync runs inside every full pass (after
        the fleet fold, so the pending-demand ring it consults is
        fresh) and on PartitionSet CRD dirty keys; its reads come from
        this scheduler's informer-backed view; its TenantProfileStore
        percentiles surface at /debug/fleet next to the rings."""
        controller.view = self.view
        if controller.fleet is None:
            controller.fleet = self.fleet
        if self.fleet is not None:
            self.fleet.attach_profile_store(controller.store)
        self.autoscaler = controller
        return self

    # -- sharding plumbing ----------------------------------------------------

    @property
    def _sharded(self) -> bool:
        """Multi-worker event mode: per-object work (claim allocation,
        pod generation/binding) must run on its key's shard, so full
        passes fan out dirty keys instead of doing that work inline."""
        return self._queue is not None and self.sched_workers > 1

    def _shard_of(self, key: tuple):
        """Control keys pin to worker 0 (the recovery/resync lane,
        immune to claim floods); claim/pod keys hash namespace/name
        over the remaining workers."""
        kind = key[0]
        if kind in _CTL_KINDS or self.sched_workers == 1:
            return 0
        from .workqueue import stable_shard_hash  # noqa: PLC0415

        h = stable_shard_hash(f"{key[1]}/{key[2]}" if len(key) >= 3
                              else kind)
        return 1 + h % (self.sched_workers - 1)

    @staticmethod
    def _stealable(key: tuple) -> bool:
        """Only per-object data keys (claim/pod) may migrate to an idle
        worker; control keys keep their dedicated worker-0 lane."""
        return isinstance(key, tuple) and bool(key) and \
            key[0] not in _CTL_KINDS

    def _on_snapshot_build(self, seconds: float) -> None:
        if self.sched_metrics is not None:
            self.sched_metrics.snapshot_build.observe(seconds)

    def _on_snapshot_delta(self, pool_label: str,
                           seconds: float) -> None:
        if self.sched_metrics is not None:
            self.sched_metrics.snapshot_delta.labels(
                pool_label).observe(seconds)

    def _on_relist_backoff(self, resource: str, seconds: float) -> None:
        if self.sched_metrics is not None:
            self.sched_metrics.relist_backoff.labels(
                resource).observe(seconds)

    def _owns(self, obj: dict) -> bool:
        """Domain routing for claims and pods; domainless schedulers
        own everything."""
        return self.domain is None or self.domain.owns_object(obj)

    # -- claim generation (kcm resourceclaim controller) ----------------------

    def _pods(self) -> list[dict]:
        try:
            return self.view.pods()
        except KubeError:
            return []

    def _generate_claims(self):
        for pod in self._pods():
            if not self._owns(pod):
                continue
            refs = pod.get("spec", {}).get("resourceClaims") or []
            have = {s["name"] for s in pod.get("status", {}).get(
                "resourceClaimStatuses") or []}
            if not any(r.get("resourceClaimTemplateName")
                       and r["name"] not in have for r in refs):
                continue
            if self._sharded:
                # Per-pod work belongs to the pod's shard: two workers
                # generating for one pod would double-create the
                # uuid-suffixed claims.
                self._enqueue(("pod", _meta(pod).get("namespace",
                                                     "default"),
                               _meta(pod)["name"]))
                continue
            if self.view.event_driven:
                # Generated claim names carry a uuid suffix, so a
                # ConflictError can never dedupe them: in event mode
                # the cached pod may lag our OWN status patch, and
                # generating off it would orphan the first claim.
                # Re-read the pod before deciding.
                try:
                    pod = self.kube.get(
                        "", "v1", "pods", _meta(pod)["name"],
                        namespace=_meta(pod).get("namespace", "default"))
                except NotFoundError:
                    continue
            self._generate_claims_for(pod)

    def _generate_claims_for(self, pod) -> bool:
        """Template-driven claim generation for one pod. Returns True
        when the pod's claim statuses were extended."""
        refs = pod.get("spec", {}).get("resourceClaims") or []
        statuses = pod.get("status", {}).get(
            "resourceClaimStatuses") or []
        have = {s["name"] for s in statuses}
        ns = _meta(pod).get("namespace", "default")
        new_statuses = []
        for ref in refs:
            tmpl = ref.get("resourceClaimTemplateName")
            if not tmpl or ref["name"] in have:
                continue
            try:
                template = self.view.get_template(tmpl, namespace=ns)
            except NotFoundError:
                continue  # template not applied yet; retry next pass
            claim_name = (f"{_meta(pod)['name']}-{ref['name']}-"
                          f"{uuid.uuid4().hex[:5]}")
            annotations = {
                "resource.kubernetes.io/pod-claim-name": ref["name"],
            }
            # Generated claims inherit the pod's scheduling domain so
            # the owning domain scheduler allocates them.
            pod_domain = (_meta(pod).get("annotations") or {}).get(
                DOMAIN_ANNOTATION)
            if pod_domain:
                annotations[DOMAIN_ANNOTATION] = pod_domain
            claim = {
                "apiVersion": "resource.k8s.io/v1",
                "kind": "ResourceClaim",
                "metadata": {
                    "name": claim_name,
                    "namespace": ns,
                    "uid": f"claim-{uuid.uuid4().hex[:12]}",
                    "annotations": annotations,
                    "ownerReferences": [{
                        "apiVersion": "v1", "kind": "Pod",
                        "name": _meta(pod)["name"],
                        "uid": _meta(pod).get("uid", ""),
                        "controller": True,
                    }],
                },
                "spec": template.get("spec", {}).get("spec", {}),
            }
            try:
                self.kube.create(*RESOURCE, "resourceclaims", claim,
                                 namespace=ns)
            except ConflictError:
                pass
            new_statuses.append(
                {"name": ref["name"], "resourceClaimName": claim_name})
        if new_statuses:
            self.kube.patch(
                "", "v1", "pods", _meta(pod)["name"],
                {"status": {"resourceClaimStatuses":
                            statuses + new_statuses}},
                namespace=ns)
            return True
        return False

    def _generate_extended_resource_claims(self):
        """KEP-5004 (DRAExtendedResource): a pod requesting an extended
        resource that a DeviceClass advertises via
        ``spec.extendedResourceName`` gets an auto-generated
        ResourceClaim against that class, recorded in
        ``pod.status.extendedResourceClaimStatus`` -- the legacy
        ``google.com/tpu: N`` surface (reference analog: the
        'nvidia.com/gpu with DRAExtendedResource' bats scenario, which
        delegates to kube-scheduler; here the in-tree scheduler does
        it so demo/specs/extended-resources executes for real)."""
        try:
            by_resource = self._extended_resource_classes()
        except KubeError:
            return
        if not by_resource:
            return
        for pod in self._pods():
            if not self._owns(pod):
                continue
            if self._sharded:
                if self._pod_wants_extended_claim(pod, by_resource):
                    self._enqueue(("pod",
                                   _meta(pod).get("namespace", "default"),
                                   _meta(pod)["name"]))
                continue
            self._generate_extended_resource_claims_for(pod, by_resource)

    @staticmethod
    def _pod_wants_extended_claim(pod, by_resource) -> bool:
        """Cheap pre-filter for the sharded fan-out: would
        _generate_extended_resource_claims_for even consider this pod?"""
        if pod.get("status", {}).get("extendedResourceClaimStatus"):
            return False
        if pod.get("spec", {}).get("nodeName"):
            return False
        if pod.get("status", {}).get("phase") not in (None, "", "Pending"):
            return False
        return any(
            rname in by_resource
            for c in pod.get("spec", {}).get("containers", [])
            for rname in ((c.get("resources") or {}).get("limits") or {})
        )

    def _generate_extended_resource_claims_for(self, pod,
                                               by_resource) -> bool:
        if pod.get("status", {}).get("extendedResourceClaimStatus"):
            return False
        # KEP-5004 generates claims only while a pod is still being
        # SCHEDULED: one already bound (spec.nodeName set -- e.g.
        # scheduled before the class advertised
        # extendedResourceName, or born bound like a DaemonSet pod)
        # or past Pending must not retroactively acquire devices
        # and double-count them under a running workload.
        if pod.get("spec", {}).get("nodeName"):
            return False
        if pod.get("status", {}).get("phase") not in (None, "",
                                                      "Pending"):
            return False
        if _meta(pod).get("deletionTimestamp"):
            return False
        requests, mappings = [], []
        bad_qty = None
        for c in pod.get("spec", {}).get("containers", []):
            limits = (c.get("resources") or {}).get("limits") or {}
            for rname, qty in limits.items():
                cls_name = by_resource.get(rname)
                if not cls_name:
                    continue
                # Extended-resource quantities must be whole
                # numbers; a malformed one must not wedge the
                # whole scheduling pass.
                try:
                    count = int(str(qty))
                except ValueError:
                    logger.warning(
                        "pod %s/%s: non-integer extended-resource "
                        "quantity %s=%r; skipping pod",
                        _meta(pod).get("namespace", "default"),
                        _meta(pod)["name"], rname, qty)
                    bad_qty = f"{rname}={qty!r}"
                    break
                req = f"request-{len(mappings)}"
                exactly: dict = {"deviceClassName": cls_name}
                if count != 1:
                    exactly["count"] = count
                requests.append({"name": req, "exactly": exactly})
                mappings.append({
                    "containerName": c.get("name", ""),
                    "resourceName": rname,
                    "requestName": req,
                })
            if bad_qty:
                break
        if bad_qty:
            # The pod can never schedule (the generation skip keeps
            # _pending_extended_resource blocking its bind forever):
            # surface that ON THE POD -- real k8s rejects
            # non-integer extended resources at admission, but this
            # control plane has no pod admission, so a condition +
            # event is the observable analog.
            self._flag_unschedulable_pod(
                pod, "InvalidExtendedResourceQuantity",
                f"extended-resource quantity {bad_qty} is not a "
                "whole number; the pod cannot be scheduled")
            return False
        if not requests:
            return False
        ns = _meta(pod).get("namespace", "default")
        # DETERMINISTIC name (pod uid, not uuid4): create + status
        # patch are not atomic, and a retried pass must converge on
        # the same claim instead of leaking allocated orphans.
        pod_uid = _meta(pod).get("uid", "") or _meta(pod)["name"]
        claim_name = (f"{_meta(pod)['name']}-extended-resources-"
                      f"{pod_uid[-5:]}")
        annotations = {}
        # Like template-generated claims: inherit the pod's scheduling
        # domain so the owning domain scheduler allocates it.
        pod_domain = (_meta(pod).get("annotations") or {}).get(
            DOMAIN_ANNOTATION)
        if pod_domain:
            annotations[DOMAIN_ANNOTATION] = pod_domain
        claim = {
            "apiVersion": "resource.k8s.io/v1",
            "kind": "ResourceClaim",
            "metadata": {
                "name": claim_name,
                "namespace": ns,
                "uid": f"claim-{uuid.uuid4().hex[:12]}",
                "annotations": annotations,
                "ownerReferences": [{
                    "apiVersion": "v1", "kind": "Pod",
                    "name": _meta(pod)["name"],
                    "uid": _meta(pod).get("uid", ""),
                    "controller": True,
                }],
            },
            "spec": {"devices": {"requests": requests}},
        }
        try:
            self.kube.create(*RESOURCE, "resourceclaims", claim,
                             namespace=ns)
        except ConflictError:
            pass  # an earlier pass created it; converge on it
        self.kube.patch(
            "", "v1", "pods", _meta(pod)["name"],
            {"status": {"extendedResourceClaimStatus": {
                "resourceClaimName": claim_name,
                "requestMappings": mappings,
            }}},
            namespace=ns)
        logger.info(
            "generated extended-resource claim %s/%s for pod %s",
            ns, claim_name, _meta(pod)["name"])
        return True

    def _flag_unschedulable_pod(self, pod, reason: str,
                                message: str) -> None:
        """Surface a permanent scheduling failure ON THE POD: a
        PodScheduled=False condition plus a Warning Event, so `kubectl
        describe pod` explains the wedge instead of only a scheduler
        log line. Deduped on (reason, message): a condition already
        saying exactly this is not re-emitted every sync pass."""
        ns = _meta(pod).get("namespace", "default")
        name = _meta(pod)["name"]
        conditions = pod.get("status", {}).get("conditions") or []
        for c in conditions:
            if c.get("type") == "PodScheduled" and \
                    c.get("reason") == reason and \
                    c.get("message") == message:
                return
        kept = [c for c in conditions if c.get("type") != "PodScheduled"]
        kept.append({
            "type": "PodScheduled",
            "status": "False",
            "reason": reason,
            "message": message,
        })
        try:
            self.kube.patch("", "v1", "pods", name,
                            {"status": {"conditions": kept}},
                            namespace=ns)
        except (NotFoundError, ConflictError):
            return
        emit_warning_event(
            self.kube, event_name=f"{name}.{uuid.uuid4().hex[:10]}",
            namespace=ns, reason=reason, message=message,
            involved_kind="Pod", involved_name=name,
            involved_uid=_meta(pod).get("uid", ""),
            component="tpu-dra-scheduler")

    # -- allocation (kube-scheduler DRA plugin) -------------------------------

    # Commit-log retention: long enough to outlive any realistic watch
    # lag between our allocation patch and its event, short enough to
    # bound memory. Replay is idempotent, so erring long is safe.
    COMMIT_LOG_TTL_S = 120.0

    def _replay_commits_locked(self, claims: list[dict]) -> None:
        """Fold recently committed allocations into the (freshly
        rebuilt) allocation state. Caller holds _state_lock; ``claims``
        is the list the rebuild used.

        In direct mode that list is a FRESH kube list, so an entry for
        an absent claim means the claim was deleted -- and an entry
        for a PRESENT claim with no allocation means it was
        deallocated (e.g. the recovery controller's drain): drop both
        (their devices are free again). In event mode the cache may
        lag our own claim's create, so entries survive until the
        claim's allocation-bearing event retires them or the TTL."""
        now = time.monotonic()
        present = {(c.get("metadata", {}).get("namespace", "default"),
                    c.get("metadata", {}).get("name", "")): c
                   for c in claims}
        authoritative = not self.view.event_driven
        for key in list(self._commit_log):
            t, claim_like = self._commit_log[key]
            live = present.get(key)
            stale = authoritative and (
                live is None
                or not live.get("status", {}).get("allocation"))
            if now - t > self.COMMIT_LOG_TTL_S or stale:
                del self._commit_log[key]
            else:
                self._alloc.observe(claim_like)

    def _ensure_alloc_state(self) -> tuple[InventorySnapshot,
                                           AllocationState]:
        """Current snapshot + allocation state. The snapshot read
        happens OUTSIDE _state_lock (it has its own lock + event-mode
        fast path), so the hot path costs one brief identity check.

        When the view can answer WHICH pools changed between the state
        we hold and the new snapshot (the per-pool delta log), the
        allocation state RETARGETS in O(changed pools) -- a slice
        event no longer costs an O(claims) rebuild. A full rebuild
        survives as the fallback for unstamped snapshots, full
        resyncs, and log gaps."""
        snap = self.view.snapshot()
        with self._state_lock:
            if snap is self._snap and self._alloc is not None:
                return self._snap, self._alloc
            changed = None
            if self._alloc is not None and self._snap is not None:
                changed = self.view.changed_pools_between(
                    self._snap, snap)
            if changed is not None:
                self._alloc.retarget(snap, changed)
                self._snap = snap
            else:
                self._snap = snap
                self._alloc = AllocationState(snap)
                claims = self.view.claims()
                self._alloc.rebuild(claims)
                self._replay_commits_locked(claims)
            return self._snap, self._alloc

    def _rebuild_alloc_state(self) -> tuple[InventorySnapshot,
                                            AllocationState]:
        """Full defensive rebuild (every full pass does this, which is
        what makes the safety resync actually safe)."""
        snap = self.view.snapshot()
        with self._state_lock:
            self._snap = snap
            self._alloc = AllocationState(snap)
            claims = self.view.claims()
            self._alloc.rebuild(claims)
            self._replay_commits_locked(claims)
            return self._snap, self._alloc

    def _device_matches(self, snap: InventorySnapshot, cand: _Candidate,
                        selectors: list[dict],
                        tolerations: list[dict]) -> bool:
        for taint in cand.blocking_taints:
            if not _tolerates(taint, tolerations):
                return False
        for sel in selectors:
            expr = (sel.get("cel") or {}).get("expression", "")
            prog = self._selectors.get(expr)
            if prog is None or not snap.cel_match(expr, prog, cand):
                return False
        return True

    def _device_classes(self) -> dict[str, dict]:
        return {
            _meta(c)["name"]: c
            for c in self.view.device_classes()
        }

    # Optimistic-commit retry budget: a conflict means another worker
    # reserved a device/counter between our fit and our try_commit;
    # each retry re-fits against fresh state. Same-node contenders are
    # already serialized by the node lock, so conflicts only come from
    # cross-node counter races and are rare.
    COMMIT_RETRIES = 4

    def _candidate_nodes(self, claim, snap: InventorySnapshot,
                         alloc: AllocationState, window: set,
                         pinned_node: str | None) -> list[str]:
        """Node probe order for one claim: CD window first, then
        least-allocated (the spreading a real scheduler gets from
        per-pod Filter/Score), with permanently failed nodes vetoed.

        The load ordering comes from the AllocationState's memoized
        ``ordered_nodes`` (re-sorted only every nodes/
        REORDER_NODES_PER_STEP load
        mutations) -- at 10k nodes the per-claim O(n log n) sort was
        the allocation hotspot, and the order is pure preference so a
        bounded staleness cannot misallocate. Pinned claims skip the
        walk entirely: real DRA allocates during the consumer pod's
        scheduling, so the node choice is already made."""
        if pinned_node is not None:
            nodes = ([pinned_node] if pinned_node in snap.by_node
                     else [])
        else:
            nodes = alloc.ordered_nodes()
            if window:
                nodes = ([n for n in nodes if n in window]
                         + [n for n in nodes if n not in window])
            hint = self._defrag_hint(claim)
            if hint is not None and hint[0] in snap.by_node:
                # A claim mid-defrag-move probes its planned target
                # node first (pure preference: every other node stays
                # in the walk, so a stale hint degrades instead of
                # wedging).
                nodes = ([hint[0]]
                         + [n for n in nodes if n != hint[0]])
        if self.recovery is not None:
            # Permanently failed nodes may still have slices published
            # (a dead kubelet can't retract them): allocation must
            # never re-place a claim onto them.
            excluded = self.recovery.excluded_nodes()
            if excluded:
                nodes = [n for n in nodes if n not in excluded]
        return nodes

    def _allocate_one(self, claim, snap: InventorySnapshot,
                      alloc: AllocationState, classes,
                      pinned_node: str | None = None) -> str:
        """One claim through the sharded allocation protocol:

        1. **Fit** per candidate node under that node's lock (gang /
           CD-window claims take the whole window as one sorted
           multi-node lock set), reading allocation state optimistically.
        2. **Reserve** atomically (``AllocationState.try_commit``): the
           planned devices must still be free and the counter budgets
           must still fit; a conflict re-fits against fresh state.
        3. **Commit** the kube patch while still holding the node lock
           (same-node contenders serialize; disjoint nodes proceed in
           parallel); a failed patch releases the reservation so a
           write that never landed never leaks a debit
           (commit-then-observe).

        Returns the final outcome ("committed" | "unfit" | "failed" |
        "conflict" | "norequests"). ``pinned_node`` restricts placement
        to the node a consumer pod is already bound to (real DRA
        allocates during that pod's scheduling, so the choice is
        inherently per-node)."""
        requests = claim.get("spec", {}).get("devices", {}).get(
            "requests", [])
        if not requests:
            return "norequests"
        # ComputeDomain gangs first try the ICI-adjacent host window
        # the CD controller picked; load still spreads the gang's
        # members WITHIN the window, and non-window nodes remain as
        # overflow so a full window degrades instead of wedging.
        window = set(self._preferred_gang_nodes(claim) or ())
        ns = _meta(claim).get("namespace", "default")
        uid = _meta(claim).get("uid", "")
        with tracing.span("sched.claim", attrs={
                "claim": f"{ns}/{_meta(claim).get('name', '?')}",
                "claim_uid": uid}) as claim_span:
            # Fit-phase clock for the SLO breakdown: everything from
            # here until the winning try_commit is "fit" (candidate
            # walk, constraint DFS, conflict re-fits). Thread-local:
            # N workers allocate concurrently.
            self._fit_tls.t0 = time.monotonic()
            outcome = "unfit"
            for _attempt in range(self.COMMIT_RETRIES):
                if _attempt:
                    # A conflict means our captured state is stale --
                    # typically a safety-resync rebuild swapped in a
                    # fresh AllocationState mid-batch and the old
                    # object stopped receiving observes. Re-fit
                    # against the LIVE state or every retry keeps
                    # picking the same stolen devices.
                    with self._state_lock:
                        if self._alloc is not None:
                            alloc = self._alloc
                        if self._snap is not None:
                            snap = self._snap
                nodes = self._candidate_nodes(claim, snap, alloc,
                                              window, pinned_node)
                # One ledger copy per attempt, shared across every
                # probed node: the fit is optimistic anyway (try_commit
                # re-judges budgets at reserve time), so a pending
                # claim walking all 1000 nodes doesn't pay 1000 locked
                # copies. The power-debit view rides along the same
                # way (one copy per attempt, re-judged at reserve).
                ledger = alloc.ledger_snapshot()
                power = alloc.power_snapshot()
                outcome = self._try_nodes(claim, nodes, window, snap,
                                          alloc, ledger, classes,
                                          power)
                if outcome == "committed":
                    self._clear_domain_exhausted(claim)
                    break
                if outcome != "conflict":
                    break
                if self.sched_metrics is not None:
                    self.sched_metrics.commit_conflicts.inc()
            claim_span.set_attr("outcome", outcome)
        self.flight.record(
            uid or f"{ns}/{_meta(claim).get('name', '?')}", "fit",
            alias=f"{ns}/{_meta(claim).get('name', '?')}",
            trace_id=(claim_span.context.trace_id
                      if claim_span.recording else ""),
            outcome=outcome)
        if outcome == "committed":
            return outcome
        if outcome == "conflict":
            logger.warning(
                "claim %s/%s: %d consecutive commit conflicts; leaving "
                "pending for the next sync",
                _meta(claim).get("namespace", "default"),
                _meta(claim).get("name", "?"), self.COMMIT_RETRIES)
        elif outcome == "unfit" and pinned_node is None:
            # A domain-pinned claim that found no fit spills to a
            # sibling domain (annotating intent) instead of pending
            # forever; only when it cannot spill does it surface the
            # exhaustion condition.
            if not self._maybe_spill(claim):
                self._flag_domain_exhausted(claim)
        return outcome

    def _try_nodes(self, claim, nodes: list[str], window: set,
                   snap: InventorySnapshot, alloc: AllocationState,
                   ledger: _CounterLedger, classes,
                   power: dict | None = None) -> str:
        """Walk the candidate nodes under per-node locks; window gangs
        take their whole (sorted) window lock set in ONE acquisition so
        two gangs overlapping on any node cannot deadlock. Returns
        "committed" | "conflict" | "failed" | "unfit"."""
        if window:
            win_nodes = [n for n in nodes if n in window]
            if win_nodes:
                with self._node_locks.hold(win_nodes):
                    out = self._fit_and_commit(claim, win_nodes, snap,
                                               alloc, ledger, classes,
                                               power)
                if out != "unfit":
                    return out
            rest = [n for n in nodes if n not in window]
        else:
            rest = nodes
        for node in rest:
            with self._node_locks.hold((node,)):
                out = self._fit_and_commit(claim, (node,), snap, alloc,
                                           ledger, classes, power)
            if out != "unfit":
                return out
        return "unfit"

    def _fit_and_commit(self, claim, nodes, snap: InventorySnapshot,
                        alloc: AllocationState, ledger: _CounterLedger,
                        classes, power: dict | None = None) -> str:
        """Fit + commit on the first of ``nodes`` that satisfies the
        claim. Caller holds the node locks for every entry, so the
        allocation state for these nodes is quiescent apart from
        cross-node counter races (which try_commit catches)."""
        for node in nodes:
            picks = self._fit_on_node(claim, node, snap, alloc.allocated,
                                      ledger, classes, power=power)
            if picks is None:
                continue
            alloc_obj = self._build_alloc_obj(claim, node, picks, classes)
            return self._commit_allocation(claim, alloc_obj, snap, alloc)
        return "unfit"

    def _build_alloc_obj(self, claim, node, picks, classes) -> dict:
        results, configs = [], []
        seen_classes = []
        for req_name, cand, class_name in picks:
            results.append({
                "request": req_name,
                "driver": cand.driver,
                "pool": cand.pool,
                "device": cand.name,
            })
            if class_name not in seen_classes:
                seen_classes.append(class_name)
        for class_name in seen_classes:
            for cfg in classes.get(class_name, {}).get(
                    "spec", {}).get("config", []) or []:
                if "opaque" in cfg:
                    configs.append({
                        "opaque": cfg["opaque"],
                        "requests": [],
                        "source": "FromClass",
                    })
        for cfg in claim.get("spec", {}).get("devices", {}).get(
                "config", []) or []:
            if "opaque" in cfg:
                configs.append({
                    "opaque": cfg["opaque"],
                    "requests": cfg.get("requests", []),
                    "source": "FromClaim",
                })
        bind_node = node or self.default_node
        alloc_obj = {
            "devices": {"results": results, "config": configs},
        }
        if bind_node:
            alloc_obj["nodeSelector"] = {"nodeSelectorTerms": [{
                "matchFields": [{
                    "key": "metadata.name",
                    "operator": "In",
                    "values": [bind_node],
                }],
            }]}
        return alloc_obj

    # DFS budget for the constraint-aware fit: a claim that cannot be
    # decided within this many visited states is treated as unsatisfiable
    # on the node (and logged). Topology claims are tiny (a handful of
    # requests over tens of devices); the bound only guards pathological
    # specs.
    MAX_FIT_STEPS = 20_000

    @staticmethod
    def _attr_value(cand: _Candidate, attr: str):
        """Typed attribute value as a comparable (type, value) tuple, or
        None when the device does not carry the attribute. ``attr`` may
        be plain ("iciY") or driver-qualified ("tpu.dra.dev/iciY") --
        a driver's own attributes are implicitly qualified by its name
        (upstream structured-parameters semantics)."""
        attrs = cand.device.get("attributes") or {}
        entry = attrs.get(attr)
        if entry is None and "/" in attr:
            domain, _, base = attr.partition("/")
            if domain == cand.driver:
                entry = attrs.get(base)
        if not isinstance(entry, dict):
            return None
        for kind in ("string", "int", "bool", "version"):
            if kind in entry:
                return (kind, entry[kind])
        return None

    # -- ICI topology-aware ordering (pkg/topology) ---------------------------

    @staticmethod
    def _grid_for(cands: list["_Candidate"]) -> TorusGrid:
        return TorusGrid.from_devices([c.device for c in cands])

    def _topology_order(self, snap: InventorySnapshot,
                        cands: list["_Candidate"],
                        want: int | None) -> list["_Candidate"]:
        """Reorder one request's candidates so the scorer's best
        sub-torus placements come first. Pure preference: every
        candidate stays in the list, so the backtracking fit (and
        therefore matchAttributes, counters, taints) is untouched --
        with no usable coordinates the original first-fit order
        survives verbatim. ``want`` None (All-mode) takes everything
        anyway; nothing to order. The ordering memo lives on the
        inventory snapshot: it is a pure function of the published
        devices, so it survives across passes and invalidates exactly
        when they change."""
        if want is None or want < 1 or len(cands) < 2:
            return cands
        by_pool: dict[tuple, list[_Candidate]] = {}
        for c in cands:
            by_pool.setdefault((c.driver, c.pool), []).append(c)
        out: list[_Candidate] = []
        any_signal = False
        for (driver, pool), group in by_pool.items():
            ordered = None
            if len(group) >= want:
                names = tuple(c.name for c in group)
                key = (driver, pool, names, want)
                # Memo access through the schedcache accessors only:
                # TPUDRA009 fences direct mutation of sub-snapshot
                # internals to pkg/schedcache.py delta paths.
                hit = snap.order_memo_get(key)
                if hit is not _ORDER_MISS:
                    ordered = hit
                else:
                    grid = self._grid_for(group)
                    # Power/thermal headroom term: placements touching
                    # degraded chips rank last (pure preference; the
                    # penalties derive from the same pool content the
                    # memo is invalidated on, so the memo stays safe).
                    penalties = {c.name: c.headroom_penalty
                                 for c in group if c.headroom_penalty}
                    ordered = topo_order_candidates(
                        grid, list(names), want,
                        penalties=penalties or None)
                    snap.order_memo_put(key, ordered)
            if ordered is None:
                out.extend(group)
            else:
                any_signal = True
                by_name = {c.name: c for c in group}
                out.extend(by_name[n] for n in ordered)
        # No group produced a ranking: keep the ORIGINAL interleaved
        # order, not the per-pool regrouping -- the documented fallback
        # is the pre-topology first-fit order, verbatim.
        return out if any_signal else cands

    @staticmethod
    def _defrag_hint(claim) -> tuple[str, list[str]] | None:
        """The defrag controller's placement hint for a moving claim:
        (target node, target device names), or None. Parsed from the
        ``resource.tpu.dra/defrag-target`` annotation the controller
        stamps before deallocating (pkg/defrag)."""
        raw = (_meta(claim).get("annotations") or {}).get(
            DEFRAG_TARGET_ANNOTATION)
        if not raw:
            return None
        return _parse_defrag_hint(raw)

    def _preferred_gang_nodes(self, claim) -> list[str] | None:
        """ComputeDomain channel claims prefer the ICI-adjacent host
        window the CD controller picked (its preferred-nodes
        annotation): the gang's workers land on consecutive workerIds
        instead of whatever nodes happened to be least loaded."""
        if not self._topology:
            return None
        for cfg in claim.get("spec", {}).get("devices", {}).get(
                "config", []) or []:
            params = (cfg.get("opaque") or {}).get("parameters") or {}
            if params.get("kind") != "ComputeDomainChannelConfig":
                continue
            uid = params.get("domainID")
            if not uid:
                continue
            return self.view.cd_windows().get(uid) or None
        return None

    def _observe_placement(self, alloc_obj, snap: InventorySnapshot,
                           alloc: AllocationState) -> None:
        """Export placement quality for a fresh allocation: compactness
        of the chosen set, plus the post-pick fragmentation / largest
        allocatable shape of every pool it drew from."""
        if self.metrics is None or not self._topology:
            return
        by_pool: dict[tuple, list[str]] = {}
        for res in alloc_obj.get("devices", {}).get("results", []):
            by_pool.setdefault((res.get("driver", ""), res.get("pool", "")),
                               []).append(res.get("device", ""))
        for (driver, pool), picked in by_pool.items():
            devs = [c for c in snap.candidates
                    if c.driver == driver and c.pool == pool]
            if not devs:
                continue
            grid = self._grid_for(devs)
            cells = {grid.coords[n] for n in picked if n in grid.coords}
            if not cells:
                continue  # uncoordinated pool: nothing to report
            label = f"{driver}/{pool}"
            hops, _ = set_compactness(grid, cells)
            self.metrics.compactness.labels(label).observe(hops)
            free = {grid.coords[c.name] for c in devs
                    if c.key not in alloc.allocated
                    and c.name in grid.coords}
            # One largest_free_shape sweep feeds both gauges (it is the
            # most expensive topology operation on big pools).
            _, chips = largest_free_shape(grid, free)
            self.metrics.frag_score.labels(label).set(
                frag_from_largest(chips, len(free)))
            self.metrics.largest_shape.labels(label).set(chips)

    def _fit_on_node(self, claim, node, snap: InventorySnapshot,
                     allocated: set, ledger: _CounterLedger, classes,
                     power: dict | None = None):
        """All requests of one claim against one node; returns
        [(request, candidate, class_name)] or None. ``allocated`` is
        only ever probed for membership (safe against concurrent
        commits on other nodes) and ``ledger`` is a private copy, so
        the fit itself runs lock-free; the atomic try_commit re-judges
        both before anything becomes visible. Counter fits are
        checked against a tentative ledger so multi-device claims can't
        double-spend. ``power`` is the per-node power-debit view: on a
        power-capped node the picks' summed expected draw must fit
        under the remaining budget (2501.17752's power-as-a-counter
        model; try_commit re-judges atomically).

        ``spec.devices.constraints[].matchAttribute`` (KEP-4381): every
        device allocated for the constraint's requests (all requests
        when the list is empty) must carry the SAME value for the named
        attribute; a device lacking the attribute never satisfies it.
        For a TPU driver this is THE topology primitive -- e.g.
        matchAttribute on iciY+iciZ pins a multi-chip claim to one ICI
        ring. Choices interact across requests, so the fit backtracks
        (bounded DFS) instead of picking greedily: the first candidate's
        attribute value must not doom an otherwise-satisfiable claim.
        """
        spec = claim.get("spec", {}).get("devices", {})
        node_cands = snap.by_node.get(node, ())
        reqs = []
        for req in spec.get("requests", []):
            exactly = req.get("exactly") or req  # v1 nests under exactly
            class_name = exactly.get("deviceClassName", "")
            cls = classes.get(class_name)
            if cls is None:
                return None
            selectors = list(cls.get("spec", {}).get("selectors") or [])
            selectors += list(exactly.get("selectors") or [])
            mode = exactly.get("allocationMode", "ExactCount")
            reqs.append({
                "name": req.get("name", "r"),
                "class": class_name,
                "want": (int(exactly.get("count", 1))
                         if mode != "All" else None),
                "cands": [
                    cand for cand in node_cands
                    if cand.key not in allocated
                    and self._device_matches(
                        snap, cand, selectors,
                        list(exactly.get("tolerations") or []))
                ],
            })
        if self.defrag is not None or self.migration is not None:
            # Device veto: defrag carve cells / move targets and
            # cooperative-migration destination windows are reserved
            # -- only the claim a device is reserved FOR may allocate
            # it while the move is in flight (everyone else fits
            # around the forming shape / the reserved window).
            reserved = {}
            if self.defrag is not None:
                reserved.update(self.defrag.reservations())
            if self.migration is not None:
                reserved.update(self.migration.reservations())
            if reserved:
                uid = _meta(claim).get("uid", "")
                for r in reqs:
                    r["cands"] = [
                        c for c in r["cands"]
                        if c.key not in reserved
                        or (uid and reserved[c.key] == uid)]
        if self._topology:
            for r in reqs:
                r["cands"] = self._topology_order(snap, r["cands"],
                                                 r["want"])
        # Thermal/straggler-aware bias: candidates in an active
        # anomaly episode (or out of power/thermal headroom) sort LAST
        # -- a stable partition, so within each health tier the
        # topology (or first-fit) order above survives verbatim. Pure
        # preference: a degraded chip is still picked when nothing
        # clean satisfies the request (the last-resort contract).
        for r in reqs:
            if any(c.headroom_penalty for c in r["cands"]):
                r["cands"] = sorted(r["cands"],
                                    key=lambda c: c.headroom_penalty)
        hint = self._defrag_hint(claim)
        if hint is not None and hint[0] == node:
            # Defrag placement hint: the controller's planned target
            # devices lead each request's candidate order. Applied
            # AFTER the topology reorder (the hint is the stronger,
            # claim-specific signal) and independent of the topology
            # gate; ordering only -- the backtracking fit still
            # decides.
            hinted = set(hint[1])
            for r in reqs:
                r["cands"] = (
                    [c for c in r["cands"] if c.name in hinted]
                    + [c for c in r["cands"] if c.name not in hinted])
        constraints = []
        for c in spec.get("constraints") or []:
            attr = c.get("matchAttribute")
            if not attr:
                # Unknown constraint type: fail closed like the upstream
                # allocator (an unenforceable constraint must not be
                # silently dropped).
                return None
            constraints.append({
                "requests": set(c.get("requests") or []) or None,
                "attr": attr,
            })

        # Private working copy: _FitBudgetExceeded can abandon the DFS
        # mid-undo, so the caller's ledger copy must stay pristine.
        spent = _CounterLedger()
        spent._avail = {k: dict(v) for k, v in ledger._avail.items()}
        cvals: list = [None] * len(constraints)
        state = {"steps": 0}
        # Remaining node power budget for this fit (None = uncapped).
        # A one-cell list so the DFS's try_pick/undo closures can
        # debit/credit it like the tentative counter ledger.
        power_cap = snap.power_cap_of(node)
        power_left = ([power_cap - (power or {}).get(node, 0)]
                      if power_cap > 0 else None)

        def applies(ci, req_name):
            want = constraints[ci]["requests"]
            return want is None or req_name in want

        def try_pick(req, cand, taken):
            """Constraint+counter check for one candidate; returns an
            undo closure or None."""
            consumes = cand.device.get("consumesCounters")
            if not spent.fits(cand.driver, cand.pool, consumes):
                return None
            if power_left is not None and cand.power_watts > 0 and \
                    cand.power_watts > power_left[0]:
                return None  # node power budget exhausted
            set_cis = []
            for ci, c in enumerate(constraints):
                if not applies(ci, req["name"]):
                    continue
                val = self._attr_value(cand, c["attr"])
                if val is None:
                    return None  # attribute absent: never satisfiable
                if cvals[ci] is None:
                    set_cis.append(ci)
                elif cvals[ci] != val:
                    return None
            for ci, c in enumerate(constraints):
                if ci in set_cis:
                    cvals[ci] = self._attr_value(cand, c["attr"])
            spent.debit(cand.driver, cand.pool, consumes)
            if power_left is not None:
                power_left[0] -= cand.power_watts
            taken.add(cand.key)

            def undo():
                taken.discard(cand.key)
                spent.credit(cand.driver, cand.pool, consumes)
                if power_left is not None:
                    power_left[0] += cand.power_watts
                for ci in set_cis:
                    cvals[ci] = None
            return undo

        def fit(ri, slot_start, got, taken):
            state["steps"] += 1
            if state["steps"] > self.MAX_FIT_STEPS:
                raise _FitBudgetExceeded
            if ri == len(reqs):
                return []
            req = reqs[ri]
            if req["want"] is None:
                # All-mode: every eligible device, and every one must
                # satisfy the constraints (no subsetting).
                picks, undos = [], []
                for cand in req["cands"]:
                    if cand.key in taken:
                        continue
                    undo = try_pick(req, cand, taken)
                    if undo is None:
                        for u in reversed(undos):
                            u()
                        return None
                    undos.append(undo)
                    picks.append((req["name"], cand, req["class"]))
                if not picks:
                    return None
                rest = fit(ri + 1, 0, 0, taken)
                if rest is None:
                    for u in reversed(undos):
                        u()
                    return None
                return picks + rest
            if got == req["want"]:
                return fit(ri + 1, 0, 0, taken)
            for i in range(slot_start, len(req["cands"])):
                cand = req["cands"][i]
                if cand.key in taken:
                    continue
                undo = try_pick(req, cand, taken)
                if undo is None:
                    continue
                rest = fit(ri, i + 1, got + 1, taken)
                if rest is not None:
                    return [(req["name"], cand, req["class"])] + rest
                undo()
            return None

        try:
            return fit(0, 0, 0, set())
        except _FitBudgetExceeded:
            logger.warning(
                "claim %s/%s: constraint fit exceeded %d states on node "
                "%s; treating as unsatisfiable there",
                _meta(claim).get("namespace", "default"),
                _meta(claim).get("name", "?"), self.MAX_FIT_STEPS, node)
            return None

    # -- domain-exhaustion surfacing (scheduler-per-pool sharding) ------------

    DOMAIN_EXHAUSTED_CONDITION = "DomainExhausted"
    DOMAIN_SPILLED_CONDITION = "DomainSpilled"

    def _flag_domain_exhausted(self, claim) -> None:
        """A claim PINNED into this scheduling domain found no fit in
        the domain's (pool-restricted) inventory. Without this it sits
        silently Pending forever -- the domain annotation stops it from
        spilling to other pools by design. Surface the wedge: a
        ``DomainExhausted`` condition on the claim plus a deduped
        Warning Event, and count it
        (tpu_dra_sched_domain_exhausted_total) so operators can alert
        on a full domain."""
        if self.domain is None or not self.domain.pools:
            return  # unrestricted inventory: not a domain wedge
        ann = (_meta(claim).get("annotations") or {}).get(
            DOMAIN_ANNOTATION, "")
        if not ann:
            return  # default-domain traffic is not pinned
        if self.sched_metrics is not None:
            self.sched_metrics.domain_exhausted.labels(ann).inc()
        ns = _meta(claim).get("namespace", "default")
        name = _meta(claim)["name"]
        message = (
            f"no device fit in scheduling domain {ann!r} (pools "
            f"{sorted(self.domain.pools)}); the claim stays pending "
            "until domain capacity frees or the annotation moves it"
        )
        conditions = claim.get("status", {}).get("conditions") or []
        for c in conditions:
            if c.get("type") == self.DOMAIN_EXHAUSTED_CONDITION and \
                    c.get("status") == "True" and \
                    c.get("message") == message:
                return  # already surfaced: deduped, no churn
        kept = [c for c in conditions
                if c.get("type") != self.DOMAIN_EXHAUSTED_CONDITION]
        kept.append({
            "type": self.DOMAIN_EXHAUSTED_CONDITION,
            "status": "True",
            "reason": "DomainExhausted",
            "message": message,
        })
        try:
            self.kube.patch(*RESOURCE, "resourceclaims", name,
                            {"status": {"conditions": kept}},
                            namespace=ns)
        except KubeError:
            # Cosmetic surfacing write: a flaky apiserver here must
            # never abort the sync pass that real allocations ride on.
            return
        # Deterministic name = create-once dedupe: repeat passes hit
        # ConflictError instead of spamming.
        emit_warning_event(
            self.kube, event_name=f"{name}.domain-exhausted",
            namespace=ns, reason="DomainExhausted", message=message,
            involved_kind="ResourceClaim", involved_name=name,
            involved_uid=_meta(claim).get("uid", ""),
            component="tpu-dra-scheduler")

    def _clear_domain_exhausted(self, claim) -> None:
        """An allocation landed for a claim that carried the
        exhaustion (or in-flight spill) condition: retire it (status
        False) so observers see the recovery."""
        conditions = claim.get("status", {}).get("conditions") or []
        retire = {self.DOMAIN_EXHAUSTED_CONDITION:
                  "domain capacity freed; claim allocated",
                  self.DOMAIN_SPILLED_CONDITION:
                  "claim allocated in the spill target domain"}
        live = {c.get("type") for c in conditions
                if c.get("type") in retire and c.get("status") == "True"}
        if not live:
            return
        kept = [c for c in conditions if c.get("type") not in live]
        for cond_type in sorted(live):
            kept.append({
                "type": cond_type,
                "status": "False",
                "reason": "Allocated",
                "message": retire[cond_type],
            })
        try:
            self.kube.patch(
                *RESOURCE, "resourceclaims", _meta(claim)["name"],
                {"status": {"conditions": kept}},
                namespace=_meta(claim).get("namespace", "default"))
        except (NotFoundError, ConflictError, KubeError):
            pass  # cosmetic: the allocation itself already landed

    # -- cross-domain claim spillover -----------------------------------------

    # Sibling-capacity memo TTL: bounds the claims+slices scan rate
    # under an exhausted-domain claim flood.
    SPILL_MEMO_TTL_S = 2.0

    @staticmethod
    def _claim_device_demand(claim) -> int:
        """Rough device count one claim needs (All-mode counts 1):
        a sibling with less free capacity than this can be skipped
        without a fit. ONE rule, shared with the defrag demand
        signal (pkg/defrag.claim_device_demand) so the two readers
        of 'how many chips does this claim want' can never drift."""
        return _defrag_claim_demand(claim)

    def _sibling_capacity(self) -> dict[str, tuple[int, int]]:
        """sibling name -> (free devices, total devices) across the
        sibling's pools, computed from the UNfiltered informer caches
        (this domain's snapshot is pool-restricted by design, so the
        spill decision is the one read that must see past the fence).
        Memoized briefly: spills are rare but arrive in floods when a
        domain fills."""
        memo = self._spill_capacity_memo
        now = time.monotonic()
        if memo is not None and memo[0] > now:
            return memo[1]
        try:
            slices = self.view.slices()
            claims = self.view.claims()
        except KubeError:
            return {}
        siblings = self.domain.siblings if self.domain else []
        # Newest-generation device keys per sibling.
        newest: dict[tuple, int] = {}
        for s in slices:
            pk = pool_key_of(s)
            gen = s.get("spec", {}).get("pool", {}).get("generation", 0)
            newest[pk] = max(newest.get(pk, 0), gen)
        totals: dict[str, set] = {sib.name: set() for sib in siblings}
        for s in slices:
            spec = s.get("spec", {})
            pk = pool_key_of(s)
            if spec.get("pool", {}).get("generation", 0) != newest[pk]:
                continue
            node = spec.get("nodeName", "")
            for sib in siblings:
                if sib.owns_pool(pk[1], node):
                    for dev in spec.get("devices", []):
                        totals[sib.name].add(
                            (pk[0], pk[1], dev.get("name", "")))
        allocated: set = set()
        for claim in claims:
            alloc = claim.get("status", {}).get("allocation") or {}
            for r in alloc.get("devices", {}).get("results", []):
                allocated.add((r.get("driver", ""), r.get("pool", ""),
                               r.get("device", "")))
        out = {
            name: (len(keys - allocated), len(keys))
            for name, keys in totals.items()
        }
        self._spill_capacity_memo = (now + self.SPILL_MEMO_TTL_S, out)
        return out

    def _rank_spill_target(self, claim) -> "SchedulingDomain | None":
        """Cheapest sibling by migration-cost score: configured order
        (weighted) + current utilization (weighted), siblings without
        enough free devices for the claim's rough demand skipped."""
        demand = self._claim_device_demand(claim)
        capacity = self._sibling_capacity()
        best, best_cost = None, None
        for idx, sib in enumerate(self.domain.siblings):
            free, total = capacity.get(sib.name, (0, 0))
            if total <= 0 or free < demand:
                continue
            util = 1.0 - free / total
            cost = (self._spill_order_weight * idx
                    + self._spill_util_weight * util)
            if best_cost is None or cost < best_cost:
                best, best_cost = sib, cost
        return best

    def _maybe_spill(self, claim) -> bool:
        """Re-home a domain-pinned, domain-exhausted claim to the
        cheapest sibling domain: ONE annotation patch moves the
        domain pin, records the original domain
        (``spilled-from``) and the hop count, and the sibling's
        scheduler picks the claim up off the resulting watch event.
        Deduped ``DomainSpilled`` Warning Event; claims annotated
        ``resource.tpu.dra/spillover: "false"`` never move. Returns
        True when the claim was spilled."""
        domain = self.domain
        if (not self._spillover_enabled or domain is None
                or not domain.pools or not domain.siblings):
            return False
        ann = _meta(claim).get("annotations") or {}
        if ann.get(DOMAIN_ANNOTATION, "") != domain.name:
            return False  # not pinned here: not ours to move
        if ann.get(SPILLOVER_ANNOTATION, "").lower() in (
                "false", "0", "off", "disabled"):
            return False  # operator opt-out
        try:
            hops = int(ann.get(SPILLOVER_HOPS_ANNOTATION, "0") or 0)
        except ValueError:
            hops = self._spillover_max_hops  # malformed: stop moving
        if hops >= self._spillover_max_hops:
            return False
        with self._spill_lock:
            # Rank + capacity debit are ATOMIC: concurrent workers
            # spilling a flood each consume their demand from the
            # memoized free count before the next one judges it. The
            # debit is conservative (a failed patch below leaves it
            # spent until the memo's 2s TTL) -- under-spilling briefly
            # beats overshooting the sibling.
            target = self._rank_spill_target(claim)
            if target is None:
                return False  # every sibling full too: stay + surface
            memo = self._spill_capacity_memo
            if memo is not None and target.name in memo[1]:
                free, total = memo[1][target.name]
                memo[1][target.name] = (
                    free - self._claim_device_demand(claim), total)
        ns = _meta(claim).get("namespace", "default")
        name = _meta(claim)["name"]
        origin = ann.get(SPILLED_FROM_ANNOTATION) or domain.name
        # The condition rides the SAME patch as the re-home: if the
        # target domain name is misconfigured (no scheduler owns it),
        # the claim still SHOWS what happened to it -- pre-spillover
        # it at least pended with a visible DomainExhausted.
        conditions = [c for c in claim.get("status", {}).get(
            "conditions") or []
            if c.get("type") != self.DOMAIN_SPILLED_CONDITION]
        conditions.append({
            "type": self.DOMAIN_SPILLED_CONDITION,
            "status": "True",
            "reason": "DomainSpilled",
            "message": (f"spilled from domain {origin!r} to sibling "
                        f"{target.name!r} (hop {hops + 1}); pending "
                        "here means no scheduler owns that domain"),
        })
        patch = {
            "metadata": {"annotations": {
                DOMAIN_ANNOTATION: target.name,
                SPILLED_FROM_ANNOTATION: origin,
                SPILLOVER_HOPS_ANNOTATION: str(hops + 1),
            }},
            "status": {"conditions": conditions},
        }
        try:
            self.kube.patch(*RESOURCE, "resourceclaims", name, patch,
                            namespace=ns)
        except KubeError:
            return False  # claim gone / conflicted: retry next pass
        if self.sched_metrics is not None:
            self.sched_metrics.domain_spilled.labels(
                domain.name, target.name).inc()
        self.flight.record(
            _meta(claim).get("uid", "") or f"{ns}/{name}", "spilled",
            alias=f"{ns}/{name}", src=domain.name, dst=target.name)
        message = (
            f"domain {domain.name!r} exhausted; claim spilled to "
            f"sibling domain {target.name!r} (hop {hops + 1}, origin "
            f"{origin!r}); annotate "
            f"{SPILLOVER_ANNOTATION}=false to opt out")
        # Deterministic name = create-once dedupe, like DomainExhausted.
        emit_warning_event(
            self.kube, event_name=f"{name}.domain-spilled",
            namespace=ns, reason="DomainSpilled", message=message,
            involved_kind="ResourceClaim", involved_name=name,
            involved_uid=_meta(claim).get("uid", ""),
            component="tpu-dra-scheduler")
        logger.info("claim %s/%s spilled: domain %s -> %s", ns, name,
                    domain.name, target.name)
        return True

    def _claim_pins(self) -> dict[tuple[str, str], str]:
        """(namespace, claim name) -> node, for claims whose consumer
        pod is already bound (DaemonSet pods are born bound)."""
        pins: dict[tuple[str, str], str] = {}
        for pod in self._pods():
            self._pins_from_pod(pod, pins)
        return pins

    @staticmethod
    def _pins_from_pod(pod, pins: dict[tuple[str, str], str]) -> None:
        node = pod.get("spec", {}).get("nodeName")
        if not node:
            return
        ns = _meta(pod).get("namespace", "default")
        statuses = {
            s["name"]: s.get("resourceClaimName")
            for s in pod.get("status", {}).get(
                "resourceClaimStatuses") or []
        }
        for ref in pod.get("spec", {}).get("resourceClaims") or []:
            claim_name = ref.get("resourceClaimName") or statuses.get(
                ref["name"])
            if claim_name:
                pins[(ns, claim_name)] = node
        ext = pod.get("status", {}).get(
            "extendedResourceClaimStatus") or {}
        if ext.get("resourceClaimName"):
            pins[(ns, ext["resourceClaimName"])] = node

    def _commit_allocation(self, claim, alloc_obj,
                           snap: InventorySnapshot,
                           alloc: AllocationState) -> str:
        """Reserve atomically, then patch. The reservation makes the
        devices visible to every other worker BEFORE the kube write, so
        nobody can plan against them in the patch window; a failed
        patch releases it (commit-then-observe: the incremental state
        only ever keeps allocations that landed). Returns
        "committed" | "conflict" | "failed"."""
        ns = _meta(claim).get("namespace", "default")
        claim_like = {
            "metadata": _meta(claim),
            "status": {"allocation": alloc_obj},
        }
        # Reserve against the LIVE state, atomically with the
        # commit-log insert, under _state_lock: state installs
        # (_ensure/_rebuild) take the same lock, so a rebuild that ran
        # after the caller captured ``alloc`` is the state we reserve
        # on, and any LATER rebuild replays the log entry -- either
        # way the reservation is visible before the patch is in
        # flight, so no worker can fit against a state that never saw
        # it (the double-allocation window). The fit itself stays
        # optimistic (it may have read a superseded state); try_commit
        # re-judges everything here.
        log_key = (ns, _meta(claim)["name"])
        uid = _meta(claim).get("uid", "")
        fit_t0 = getattr(self._fit_tls, "t0", None)
        t_commit0 = time.monotonic()
        with tracing.span("sched.commit", attrs={
                "claim_uid": uid}) as commit_sp:
            with self._state_lock:
                live = self._alloc if self._alloc is not None else alloc
                if not live.try_commit(claim_like):
                    commit_sp.set_attr("conflict", True)
                    self.flight.record(
                        uid or log_key[1], "commit_conflict",
                        alias=f"{ns}/{log_key[1]}",
                        trace_id=(commit_sp.context.trace_id
                                  if commit_sp.recording else ""))
                    return "conflict"
                self._commit_log[log_key] = (time.monotonic(), claim_like)
            trace_id = (commit_sp.context.trace_id
                        if commit_sp.recording else "")
            self._fit_tls.trace_id = trace_id
            # Cross-binary propagation: the traceparent annotation
            # rides the SAME patch as the allocation, so the kubelet
            # plugins' prepare spans become children of THIS commit
            # span -- one trace id, pod admission to carve-out.
            patch = {"status": {"allocation": alloc_obj}}
            # The patch rides the resourceVersion the fit READ: the
            # apiserver 409s if anything touched the claim since, which
            # is the only arbiter that stops a second active-active
            # scheduler (own informer, own ledger) from stamping a
            # conflicting allocation over this one. The ConflictError
            # path below releases the reservation and the claim comes
            # back through resync against the post-write state.
            rv = _meta(claim).get("resourceVersion")
            if rv is not None:
                patch["metadata"] = {"resourceVersion": rv}
            if commit_sp.recording:
                patch.setdefault("metadata", {})["annotations"] = (
                    tracing.inject(commit_sp, {}))
            elif tracing.TRACEPARENT_ANNOTATION in (
                    _meta(claim).get("annotations") or {}):
                # Unsampled re-allocation of a claim that still carries
                # a PREVIOUS allocation's traceparent (eviction ->
                # migration): clear it (merge-patch null), or the node
                # plugin would parent this prepare under the dead
                # first trace.
                patch.setdefault("metadata", {})["annotations"] = {
                    tracing.TRACEPARENT_ANNOTATION: None}
            t_patch0 = time.monotonic()
            try:
                # No dedicated patch span: the commit span carries
                # patch_ms instead (one fewer span on the hot path;
                # the SLO histogram still splits the phases).
                self.kube.patch(
                    *RESOURCE, "resourceclaims",
                    _meta(claim)["name"], patch, namespace=ns)
            except (NotFoundError, ConflictError):
                with self._state_lock:
                    self._commit_log.pop(log_key, None)
                    current = self._alloc
                live.forget(claim_like)
                if current is not None and current is not live:
                    # A rebuild swapped states mid-patch and replayed
                    # the now-dead reservation; release it there too.
                    current.forget(claim_like)
                return "failed"
            t_end = time.monotonic()
            if commit_sp.recording:
                # Set while the span is still open so the JSONL sink
                # (which dict-ifies at export) sees it too, not just
                # the read-time /debug/traces ring.
                commit_sp.set_attr("patch_ms",
                                   round((t_end - t_patch0) * 1e3, 3))
        if self._slo is not None:
            if fit_t0 is not None:
                self._slo.observe("fit", t_commit0 - fit_t0, trace_id)
            self._slo.observe("commit", t_patch0 - t_commit0, trace_id)
            self._slo.observe("patch", t_end - t_patch0, trace_id)
        self.flight.record(
            uid or log_key[1], "alloc_patched",
            alias=f"{ns}/{log_key[1]}", trace_id=trace_id,
            devices=[r["device"]
                     for r in alloc_obj["devices"]["results"]])
        self._observe_placement(alloc_obj, snap, alloc)
        logger.info(
            "allocated claim %s/%s -> %s", ns, _meta(claim)["name"],
            [r["device"] for r in alloc_obj["devices"]["results"]])
        return "committed"

    def _allocate_claims(self):
        snap, alloc = self._rebuild_alloc_state()
        if self._sharded:
            # Claim work belongs to its shard: fan the pending claims
            # out as dirty keys so allocation for one claim always runs
            # serialized on one worker (the full pass stays O(pending)).
            for claim in self.view.claims():
                if claim.get("status", {}).get("allocation"):
                    continue
                if _meta(claim).get("deletionTimestamp"):
                    continue
                if not self._owns(claim):
                    continue
                self._enqueue(("claim",
                               _meta(claim).get("namespace", "default"),
                               _meta(claim)["name"]))
            return
        classes = self._device_classes()
        pins = self._claim_pins()
        for claim in self.view.claims():
            if claim.get("status", {}).get("allocation"):
                continue
            if _meta(claim).get("deletionTimestamp"):
                continue
            if not self._owns(claim):
                continue
            pin = pins.get((_meta(claim).get("namespace", "default"),
                            _meta(claim)["name"]))
            self._allocate_one(claim, snap, alloc, classes,
                               pinned_node=pin)

    # -- binding --------------------------------------------------------------

    def _claims_for_pod(self, pod) -> list[tuple[str, dict | None]]:
        ns = _meta(pod).get("namespace", "default")
        statuses = {
            s["name"]: s.get("resourceClaimName")
            for s in pod.get("status", {}).get("resourceClaimStatuses") or []
        }
        out = []
        for ref in pod.get("spec", {}).get("resourceClaims") or []:
            claim_name = ref.get("resourceClaimName") or statuses.get(
                ref["name"])
            if not claim_name:
                out.append((ref["name"], None))
                continue
            try:
                out.append((claim_name, self.view.get_claim(
                    claim_name, namespace=ns)))
            except NotFoundError:
                out.append((claim_name, None))
        ext = pod.get("status", {}).get("extendedResourceClaimStatus") or {}
        if ext.get("resourceClaimName"):
            try:
                out.append((ext["resourceClaimName"], self.view.get_claim(
                    ext["resourceClaimName"], namespace=ns)))
            except NotFoundError:
                out.append((ext["resourceClaimName"], None))
        return out

    def _reserve(self, claim, pod):
        ns = _meta(claim).get("namespace", "default")
        reserved = claim.get("status", {}).get("reservedFor") or []
        entry = {
            "resource": "pods",
            "name": _meta(pod)["name"],
            "uid": _meta(pod).get("uid", ""),
        }
        if entry not in reserved:
            self.kube.patch(
                *RESOURCE, "resourceclaims", _meta(claim)["name"],
                {"status": {"reservedFor": reserved + [entry]}},
                namespace=ns)

    def _extended_resource_classes(self) -> dict[str, str]:
        """extended resource name -> DeviceClass name, for classes
        advertising ``spec.extendedResourceName`` (KEP-5004)."""
        return {
            cls["spec"]["extendedResourceName"]: name
            for name, cls in self._device_classes().items()
            if cls.get("spec", {}).get("extendedResourceName")
        }

    def _pending_extended_resource(self, pod,
                                   names: set[str] | None) -> bool:
        """True while a pod requests a DRA-served extended resource but
        its auto-generated claim has not been recorded yet -- binding
        before that would run the pod deviceless. ``names`` is the
        advertised-resource set (None = the lookup failed this pass:
        fail CLOSED for any domain-prefixed limit and retry)."""
        if pod.get("status", {}).get("extendedResourceClaimStatus"):
            return False
        limits = [
            rname
            for c in pod.get("spec", {}).get("containers", [])
            for rname in ((c.get("resources") or {}).get("limits") or {})
        ]
        if names is None:
            return any("/" in rname for rname in limits)
        return any(rname in names for rname in limits)

    def _bind_pods(self):
        try:
            ext_names: set[str] | None = set(
                self._extended_resource_classes())
        except KubeError:
            ext_names = None  # fail closed per-pod, retry next pass
        for pod in self._pods():
            if not self._owns(pod):
                continue
            if self._sharded:
                # Reservation + bind for one pod must run serialized on
                # the pod's shard (a racing duplicate would double-add
                # reservedFor entries).
                if not pod.get("spec", {}).get("nodeName") and \
                        pod.get("status", {}).get("phase") in (
                            None, "", "Pending"):
                    self._enqueue(("pod",
                                   _meta(pod).get("namespace", "default"),
                                   _meta(pod)["name"]))
                continue
            self._bind_pod(pod, ext_names)

    def _bind_pod(self, pod, ext_names: set[str] | None) -> bool:
        if pod.get("spec", {}).get("nodeName"):
            return False
        if pod.get("status", {}).get("phase") not in (
                None, "", "Pending"):
            return False
        if self._pending_extended_resource(pod, ext_names):
            return False
        nodes = set()
        ready = True
        claim_objs = []
        for _, claim in self._claims_for_pod(pod):
            if claim is None:
                ready = False
                break
            alloc = claim.get("status", {}).get("allocation")
            if not alloc:
                ready = False
                break
            claim_objs.append(claim)
            for term in alloc.get("nodeSelector", {}).get(
                    "nodeSelectorTerms", []):
                for mf in term.get("matchFields", []):
                    if mf.get("key") == "metadata.name":
                        nodes.add(mf["values"][0])
        if not ready:
            return False
        if len(nodes) > 1:
            # Claims allocated independently landed on different
            # nodes: binding anywhere would strand a device. The
            # real scheduler avoids this by filtering per-node
            # before allocating; surface it instead of mis-binding.
            logger.warning(
                "pod %s/%s claims span nodes %s; not binding",
                _meta(pod).get("namespace", "default"),
                _meta(pod)["name"], sorted(nodes))
            return False
        node = next(iter(nodes)) if nodes else None
        if node is None:
            node = self.default_node
        if node is None:
            return False
        ns = _meta(pod).get("namespace", "default")
        for claim in claim_objs:
            self._reserve(claim, pod)
        self.kube.patch("", "v1", "pods", _meta(pod)["name"],
                        {"spec": {"nodeName": node}}, namespace=ns)
        logger.info("bound pod %s/%s -> %s", ns,
                    _meta(pod)["name"], node)
        return True

    # -- DaemonSet controller (kcm daemonset controller) ----------------------

    def _sync_daemonsets(self):
        """One pod per matching node per DaemonSet (the CD controller's
        per-domain DaemonSet needs this to materialize daemon pods on
        labeled nodes). Pod name is deterministic per (ds, node) so the
        pass is idempotent; pods on no-longer-matching nodes drain."""
        try:
            daemonsets = self.view.daemonsets()
        except KubeError:
            return
        try:
            nodes = self.view.nodes()
        except KubeError:
            nodes = []
        pods = self._pods()
        # GC pods whose owning DaemonSet is gone (kcm orphan deletion).
        live = {(_meta(d).get("namespace", "default"), _meta(d)["name"])
                for d in daemonsets}
        for pod in pods:
            ns = _meta(pod).get("namespace", "default")
            for o in _meta(pod).get("ownerReferences") or []:
                if o.get("kind") == "DaemonSet" and \
                        (ns, o.get("name")) not in live:
                    try:
                        self.kube.delete("", "v1", "pods",
                                         _meta(pod)["name"], namespace=ns)
                    except NotFoundError:
                        pass
        for ds in daemonsets:
            ns = _meta(ds).get("namespace", "default")
            ds_name = _meta(ds)["name"]
            tmpl = ds.get("spec", {}).get("template", {})
            selector = tmpl.get("spec", {}).get("nodeSelector") or {}
            want = {
                _meta(n)["name"] for n in nodes
                if all((_meta(n).get("labels") or {}).get(k) == v
                       for k, v in selector.items())
            }
            existing: dict[str, dict] = {}
            for pod in pods:
                if _meta(pod).get("namespace", "default") != ns:
                    continue
                if any(o.get("kind") == "DaemonSet"
                       and o.get("name") == ds_name
                       for o in _meta(pod).get("ownerReferences") or []):
                    existing[pod.get("spec", {}).get("nodeName", "")] = pod
            for node in sorted(want - set(existing)):
                pod = {
                    "apiVersion": "v1", "kind": "Pod",
                    "metadata": {
                        "name": f"{ds_name}-{node}",
                        "namespace": ns,
                        "labels": dict(tmpl.get("metadata", {}).get(
                            "labels") or {}),
                        "ownerReferences": [{
                            "apiVersion": "apps/v1", "kind": "DaemonSet",
                            "name": ds_name,
                            "uid": _meta(ds).get("uid", ""),
                            "controller": True,
                        }],
                    },
                    "spec": {**json_copy(tmpl.get("spec", {})),
                             "nodeName": node},
                }
                try:
                    self.kube.create("", "v1", "pods", pod, namespace=ns)
                    logger.info("daemonset %s/%s -> pod on %s", ns,
                                ds_name, node)
                except ConflictError:
                    pass
            for node in sorted(set(existing) - want):
                pod = existing[node]
                try:
                    self.kube.delete("", "v1", "pods",
                                     _meta(pod)["name"], namespace=ns)
                except NotFoundError:
                    pass

    # -- Job controller (kcm job controller, completions=1 subset) ------------

    def _sync_jobs(self):
        """One pod per Job (the demo specs' workloads are Jobs); pod
        phase feeds Job status (succeeded/failed + Complete)."""
        try:
            jobs = self.view.jobs()
        except KubeError:
            return
        for job in jobs:
            ns = _meta(job).get("namespace", "default")
            name = _meta(job)["name"]
            pod_name = f"{name}-0"
            try:
                pod = self.kube.get("", "v1", "pods", pod_name,
                                    namespace=ns)
            except NotFoundError:
                status = job.get("status", {})
                if status.get("succeeded") or status.get("failed"):
                    continue  # finished Job: never re-run its pod
                tmpl = job.get("spec", {}).get("template", {})
                try:
                    self.kube.create("", "v1", "pods", {
                        "apiVersion": "v1", "kind": "Pod",
                        "metadata": {
                            "name": pod_name, "namespace": ns,
                            "labels": dict(tmpl.get("metadata", {}).get(
                                "labels") or {}),
                            "ownerReferences": [{
                                "apiVersion": "batch/v1", "kind": "Job",
                                "name": name,
                                "uid": _meta(job).get("uid", ""),
                                "controller": True,
                            }],
                        },
                        "spec": json_copy(tmpl.get("spec", {})),
                    }, namespace=ns)
                except ConflictError:
                    pass
                continue
            phase = pod.get("status", {}).get("phase", "")
            if phase == "Succeeded" and not job.get("status", {}).get(
                    "succeeded"):
                self.kube.patch("batch", "v1", "jobs", name, {
                    "status": {"succeeded": 1, "conditions": [
                        {"type": "Complete", "status": "True"}]},
                }, namespace=ns)
            elif phase == "Failed" and not job.get("status", {}).get(
                    "failed"):
                self.kube.patch("batch", "v1", "jobs", name, {
                    "status": {"failed": 1, "conditions": [
                        {"type": "Failed", "status": "True"}]},
                }, namespace=ns)

    # -- full pass ------------------------------------------------------------

    def sync_once(self):
        t0 = time.monotonic()
        self.view.begin_pass()
        if self._cluster_controllers:
            # Non-default domain instances only allocate/bind their
            # own objects; exactly one instance runs the cluster-wide
            # controllers.
            self._sync_recovery()
            # After recovery, before allocation: a claim the migration
            # controller switches this pass re-places (onto its
            # reserved window) in the SAME pass.
            self._sync_migration()
            self._sync_daemonsets()
            self._sync_jobs()
        self._generate_claims()
        self._generate_extended_resource_claims()
        self._allocate_claims()
        self._bind_pods()
        self._observe_fleet()
        if self._cluster_controllers:
            # After the fleet fold: the defrag trigger reads the frag
            # rings THIS pass just refreshed, and the autoscaler the
            # pending-demand ring.
            self._sync_defrag()
            self._sync_autoscale()
        if self.sched_metrics is not None:
            self.sched_metrics.sync_seconds.labels("full").observe(
                time.monotonic() - t0)

    def _observe_fleet(self) -> None:
        """Fold one pass's inventory + allocation state + pending
        demand into the fleet aggregator (pkg/fleetstate). Full-pass
        cadence only (the safety resync in event mode): fleet
        time-series want seconds-to-minutes resolution, not per-claim.
        Never lets a telemetry failure fail a sync."""
        if self.fleet is None:
            return
        try:
            if not self._fleet_installed:
                fleetstate.set_default_fleet(self.fleet)
                self._fleet_installed = True
            snap, alloc = self._ensure_alloc_state()
            pending = sum(
                1 for c in self.view.claims()
                if self._owns(c)
                and not c.get("status", {}).get("allocation")
                and not _meta(c).get("deletionTimestamp"))
            self.fleet.observe_pass(snap, alloc, pending,
                                    grid_fn=self._grid_for)
        except Exception:  # noqa: BLE001 - observability must not
            logger.exception("fleet telemetry fold failed")  # fail sync

    def _sync_recovery(self) -> None:
        """One recovery-controller pass, ahead of allocation so the
        failed-node exclusion and freshly deallocated claims are
        visible to the SAME pass. InjectedCrash (a BaseException) sails
        through on purpose -- the chaos suite's controller-death
        scenarios depend on it."""
        if self.recovery is None:
            return
        try:
            self.recovery.sync_once()
        except Exception:  # noqa: BLE001 - control loop
            logger.exception("recovery sync failed")

    def _sync_defrag(self) -> None:
        """One defrag-controller pass. InjectedCrash (a BaseException)
        sails through on purpose -- the crash-resume suite's
        controller-death scenarios depend on it."""
        if self.defrag is None:
            return
        try:
            self.defrag.sync_once()
        except Exception:  # noqa: BLE001 - control loop
            logger.exception("defrag sync failed")

    def _sync_autoscale(self) -> None:
        """One autoscale-controller pass. InjectedCrash (a
        BaseException) sails through on purpose -- the crash-resume
        suite's controller-death scenarios depend on it."""
        if self.autoscaler is None:
            return
        try:
            self.autoscaler.sync_once()
        except Exception:  # noqa: BLE001 - control loop
            logger.exception("autoscale sync failed")

    def _sync_migration(self) -> None:
        """One migration-controller pass. InjectedCrash (a
        BaseException) sails through on purpose -- the chaos suite's
        controller-death scenarios depend on it."""
        if self.migration is None:
            return
        try:
            self.migration.sync_once()
        except Exception:  # noqa: BLE001 - control loop
            logger.exception("migration sync failed")

    # -- event-driven incremental sync ----------------------------------------

    def start_event_driven(self) -> "DraScheduler":
        """Informer-fed dirty-set mode: per-object events enqueue keyed
        work; the periodic FULL resync survives only as the safety net
        (``resync_period``, default 30s / TPU_DRA_SCHED_RESYNC).
        ``sched_workers`` > 1 shards claim/pod keys over N-1 data
        workers (disjoint-node allocations commit in parallel) with
        control keys pinned to a dedicated worker."""
        from .workqueue import RateLimiter, WorkQueue  # noqa: PLC0415

        if self._queue is not None:
            return self
        self._queue = WorkQueue(
            limiter=RateLimiter(base_delay=0.05, max_delay=2.0),
            workers=self.sched_workers, name="sched-sync",
            shard_of=self._shard_of,
            metrics=(self.sched_metrics.workqueue
                     if self.sched_metrics is not None else None),
            # Work stealing between idle data workers: a pathological
            # single-namespace claim flood (every key hashing to one
            # shard) drains across the pool. Control keys stay pinned
            # to worker 0 -- the recovery/resync lane must never
            # migrate behind a claim flood.
            steal=(self._stealable if self.sched_workers > 1 else None),
            may_steal=lambda idx: idx != 0,
        )
        self.view.start()
        self._enqueue(("full",))
        self._resync_thread = threading.Thread(
            target=self._resync_loop, name="sched-resync", daemon=True)
        self._resync_thread.start()
        return self

    def _resync_loop(self) -> None:
        while not self._stop.wait(self.resync_period):
            self._enqueue(("full",))

    def drain(self, timeout: float = 10.0) -> bool:
        """Block until the dirty set is fully processed (tests/bench)."""
        if self._queue is None:
            return True
        return self._queue.wait_idle(timeout)

    def _enqueue(self, key: tuple) -> None:
        if self._queue is None or self._stop.is_set():
            return
        self._queue.enqueue(key, self._sync_key)
        if len(key) >= 3 and key[0] == "claim":
            # Flight-record the dirty-key enqueue under ns/name (the
            # UID is not known here; later events alias the two).
            self.flight.record(f"{key[1]}/{key[2]}", "enqueue")
        if self.sched_metrics is not None:
            self.sched_metrics.dirty_depth.set(self._queue.len())

    def _on_informer_relist(self, resource: str) -> None:
        if self.sched_metrics is not None:
            self.sched_metrics.informer_relists.labels(resource).inc()

    def _on_informer_event(self, resource: str, ev_type: str,
                           obj: dict) -> None:
        """Informer event -> dirty keys. Runs on watch/notify threads;
        does index + allocation-state bookkeeping inline (cheap, lock
        guarded) and defers all kube I/O to the queue worker."""
        md = _meta(obj)
        ns = md.get("namespace", "default")
        name = md.get("name", "")
        if resource == "pods":
            self._index_pod(ev_type, ns, name, obj)
            if self._owns(obj):
                self._enqueue(("pod", ns, name))
            owners = md.get("ownerReferences") or []
            if self._cluster_controllers:
                if any(o.get("kind") == "Job" for o in owners):
                    self._enqueue(("jobs",))
                if ev_type == "DELETED" and any(
                        o.get("kind") == "DaemonSet" for o in owners):
                    self._enqueue(("daemonsets",))
        elif resource == "resourceclaims":
            with self._state_lock:
                if self._alloc is not None:
                    if ev_type == "DELETED":
                        self._alloc.forget(obj)
                    else:
                        self._alloc.observe(obj)
                if ev_type == "DELETED" or obj.get("status", {}).get(
                        "allocation"):
                    # The cache caught up with (or outlived) our own
                    # committed allocation: the replay record retires.
                    self._commit_log.pop((ns, name), None)
            if ev_type == "DELETED":
                # Freed devices may unblock any pending claim.
                self._pods_of_claim.pop((ns, name), None)
                self._enqueue(("pending",))
            elif self._owns(obj):
                self._enqueue(("claim", ns, name))
            if self.recovery is not None and self.recovery.busy():
                # Allocation changes advance IN-FLIGHT evictions
                # (replaced claims retire; deleted claims cancel);
                # ordinary claim churn with nothing in flight never
                # pays a recovery pass. New victims only appear via
                # node/slice failures, which enqueue unconditionally.
                self._enqueue(("recovery",))
            if self.defrag is not None and self.defrag.busy():
                # Same gating for in-flight defrag moves: a moving
                # claim's re-allocation (or deletion) advances its
                # record without waiting for the safety resync; quiet
                # fleets never pay a defrag pass per claim event.
                self._enqueue(("defrag",))
            if self.migration is not None and self.migration.busy():
                # And for in-flight cooperative handshakes: the
                # workload's ack lands as a claim annotation patch, so
                # the claim event IS the handshake's forward edge.
                self._enqueue(("migration",))
            for pod_name in self._dependent_pods(ns, name, obj):
                self._enqueue(("pod", ns, pod_name))
        elif resource == "resourceslices":
            self._enqueue(("inventory",))
            if self.recovery is not None:
                # Fatal device taints arrive as slice writes.
                self._enqueue(("recovery",))
            if self.migration is not None and self.migration.busy():
                # A retired slice may take an in-flight handshake's
                # reserved destination with it (destination lost).
                self._enqueue(("migration",))
        elif resource == "deviceclasses":
            self._enqueue(("pending",))
        elif resource == "computedomains":
            self._enqueue(("pending",))
        elif resource == "partitionsets":
            # A layout CRD moved: the autoscaler may have a rollout to
            # confirm (or an operator edit to defer to), and pending
            # tenants get their retry once the nodes republish.
            if self.autoscaler is not None:
                self._enqueue(("autoscale",))
            self._enqueue(("pending",))
        elif resource in ("daemonsets", "nodes"):
            self._enqueue(("daemonsets",))
            if resource == "nodes" and self.recovery is not None:
                # NotReady transitions / node deletion feed escalation.
                self._enqueue(("recovery",))
            if resource == "nodes" and self.migration is not None:
                # The cooperative-evacuation annotation arrives as a
                # node write.
                self._enqueue(("migration",))
        elif resource == "jobs":
            self._enqueue(("jobs",))
        elif resource == "resourceclaimtemplates":
            self._enqueue(("pods-rescan",))

    def _index_pod(self, ev_type: str, ns: str, name: str,
                   pod: dict) -> None:
        pod_key = (ns, name)
        with self._state_lock:
            for claim_name in self._claims_of_pod.pop(pod_key, ()):
                peers = self._pods_of_claim.get((ns, claim_name))
                if peers is not None:
                    peers.discard(name)
            if ev_type == "DELETED":
                return
            claims: set[str] = set()
            statuses = pod.get("status", {}).get(
                "resourceClaimStatuses") or []
            by_ref = {s["name"]: s.get("resourceClaimName")
                      for s in statuses}
            for ref in pod.get("spec", {}).get("resourceClaims") or []:
                claim_name = ref.get("resourceClaimName") or by_ref.get(
                    ref["name"])
                if claim_name:
                    claims.add(claim_name)
            ext = pod.get("status", {}).get(
                "extendedResourceClaimStatus") or {}
            if ext.get("resourceClaimName"):
                claims.add(ext["resourceClaimName"])
            if claims:
                self._claims_of_pod[pod_key] = claims
                for claim_name in claims:
                    self._pods_of_claim.setdefault(
                        (ns, claim_name), set()).add(name)

    def _dependent_pods(self, ns: str, claim_name: str,
                        claim: dict) -> set[str]:
        with self._state_lock:
            pods = set(self._pods_of_claim.get((ns, claim_name), ()))
        for o in _meta(claim).get("ownerReferences") or []:
            if o.get("kind") == "Pod" and o.get("name"):
                pods.add(o["name"])
        return pods

    def _sync_key(self, key: tuple) -> None:
        t0 = time.monotonic()
        kind = key[0]
        try:
            if kind in ("daemonsets", "jobs", "recovery", "defrag",
                        "autoscale", "migration") and \
                    not self._cluster_controllers:
                return  # another domain owns the cluster controllers
            if kind == "full":
                self.sync_once()
                return  # sync_once observed itself as a full pass
            if kind == "pod":
                self._sync_pod_key(key[1], key[2])
            elif kind == "claim":
                self._sync_claim_keys_batched(key)
            elif kind == "pending":
                self._retry_pending_claims()
            elif kind == "inventory":
                # Slice events already marked their pools dirty in the
                # view (per-pool delta tracking): the next snapshot()
                # read rebuilds exactly those pools and the allocation
                # state retargets in O(changed pools). The old global
                # invalidate here forced an O(slices) full rebuild +
                # O(claims) state rebuild per slice event -- the
                # 10k-node hotspot this PR removes.
                self._retry_pending_claims()
            elif kind == "daemonsets":
                self._sync_daemonsets()
            elif kind == "jobs":
                self._sync_jobs()
            elif kind == "recovery":
                self._sync_recovery()
                # A recovery pass may have deallocated claims; give
                # them their re-placement attempt without waiting for
                # the safety resync.
                self._retry_pending_claims()
            elif kind == "defrag":
                self._sync_defrag()
                # A defrag pass deallocates moving claims; re-place
                # them (onto their hinted targets) immediately.
                self._retry_pending_claims()
            elif kind == "migration":
                self._sync_migration()
                # A switch deallocates the moving claim; re-place it
                # (onto its reserved window) immediately.
                self._retry_pending_claims()
            elif kind == "autoscale":
                self._sync_autoscale()
            elif kind == "pods-rescan":
                for pod in self._pods():
                    refs = pod.get("spec", {}).get("resourceClaims") or []
                    have = {s["name"] for s in pod.get("status", {}).get(
                        "resourceClaimStatuses") or []}
                    if any(r.get("resourceClaimTemplateName")
                           and r["name"] not in have for r in refs):
                        self._enqueue(("pod",
                                       _meta(pod).get("namespace",
                                                      "default"),
                                       _meta(pod)["name"]))
        finally:
            if self.sched_metrics is not None:
                if kind != "full":
                    self.sched_metrics.sync_seconds.labels(
                        "incremental").observe(time.monotonic() - t0)
                if self._queue is not None:
                    self.sched_metrics.dirty_depth.set(self._queue.len())

    def _sync_pod_key(self, ns: str, name: str) -> None:
        """Claim generation + binding for ONE pod. The pod is re-read
        from the apiserver (a GET, not a list): claim generation must
        never double-create off a stale cache."""
        try:
            pod = self.kube.get("", "v1", "pods", name, namespace=ns)
        except NotFoundError:
            return
        if not self._owns(pod):
            return
        try:
            by_resource = self._extended_resource_classes()
            ext_names: set[str] | None = set(by_resource)
        except KubeError:
            by_resource, ext_names = {}, None
        changed = self._generate_claims_for(pod)
        if by_resource:
            changed |= self._generate_extended_resource_claims_for(
                pod, by_resource)
        if changed:
            try:
                pod = self.kube.get("", "v1", "pods", name, namespace=ns)
            except NotFoundError:
                return
        self._bind_pod(pod, ext_names)

    def _sync_claim_keys_batched(self, key: tuple) -> None:
        """Batched multi-claim allocation: drain up to ``batch_max``
        due claim keys from this worker's heap (its home shard plus
        any work-stolen keys; per-key exclusion is the queue's
        running-set, not shard residency) against ONE
        inventory snapshot + device-class read, amortizing the
        signature check and the static-CEL memo warmup over the whole
        burst. Extra keys report their outcomes back to the queue via
        ``finish`` (per-key retry discipline preserved)."""
        extras: list[tuple] = []
        if self._queue is not None and self.batch_max > 1:
            extras = self._queue.take_ready(
                lambda k: isinstance(k, tuple) and k and k[0] == "claim",
                self.batch_max - 1)
        if not extras:
            self._sync_claim_key(key[1], key[2])
            return
        try:
            snap, alloc = self._ensure_alloc_state()
            classes = self._device_classes()
        except BaseException as e:
            # The taken extras are marked running in the queue; if the
            # shared setup dies they MUST still be reported or they
            # stay wedged (enqueues for a running key only set the
            # dirty flag). Hand each its own retry.
            for extra in extras:
                self._queue.finish(extra, e)
            raise
        primary_err: BaseException | None = None
        try:
            self._sync_claim_one(key[1], key[2], snap, alloc, classes)
        except Exception as e:  # noqa: BLE001 - re-raised after finishes
            primary_err = e
        for extra in extras:
            err: BaseException | None = None
            try:
                self._sync_claim_one(extra[1], extra[2], snap, alloc,
                                     classes)
            except Exception as e:  # noqa: BLE001 - per-key retry
                err = e
            self._queue.finish(extra, err)
        if primary_err is not None:
            raise primary_err

    def _sync_claim_key(self, ns: str, name: str) -> None:
        """Allocation attempt for ONE claim, re-read fresh so a stale
        cache can never double-allocate."""
        snap, alloc = self._ensure_alloc_state()
        self._sync_claim_one(ns, name, snap, alloc,
                             self._device_classes())

    def _sync_claim_one(self, ns: str, name: str,
                        snap: InventorySnapshot, alloc: AllocationState,
                        classes) -> None:
        try:
            claim = self.kube.get(*RESOURCE, "resourceclaims", name,
                                  namespace=ns)
        except NotFoundError:
            return
        if _meta(claim).get("deletionTimestamp"):
            return
        if claim.get("status", {}).get("allocation"):
            alloc.observe(claim)
            return
        if not self._owns(claim):
            return
        pin = self._pin_for_claim(ns, name)
        qwait = (self._queue.current_wait()
                 if self._queue is not None else None)
        outcome = self._allocate_one(claim, snap, alloc, classes,
                                     pinned_node=pin)
        if outcome == "conflict":
            # Retries exhausted against contended/stale state: hand
            # the claim back to the queue (dirty-flag requeue with the
            # normal backoff) so it re-fits against a FRESH
            # _ensure_alloc_state instead of pending until the next
            # full resync -- at a 10k-node resync cadence that wait
            # would be minutes.
            self._enqueue(("claim", ns, name))
        if outcome == "committed" and qwait is not None and \
                self._slo is not None:
            # The queued phase of THIS claim's winning attempt: dirty-
            # key enqueue -> sync start, including retry/hot backoff.
            # The trace id is the commit span's (stashed by
            # _commit_allocation on this worker thread).
            self._slo.observe("queued", qwait,
                              getattr(self._fit_tls, "trace_id", ""))

    def _pin_for_claim(self, ns: str, claim_name: str) -> str | None:
        """Bound-consumer pin for one claim via the reverse index (no
        full pod scan). Cache read: a lagging bind event only means an
        unpinned placement preference for one attempt, never a
        double-allocation, so the fresh-GET discipline of the claim
        itself does not apply here."""
        with self._state_lock:
            pod_names = set(self._pods_of_claim.get((ns, claim_name), ()))
        for pod_name in pod_names:
            try:
                pod = self.view.get_pod(pod_name, namespace=ns)
            except NotFoundError:
                continue
            node = pod.get("spec", {}).get("nodeName")
            if node:
                return node
        return None

    def _retry_pending_claims(self) -> None:
        """Re-try every still-pending claim (cache scan, then a fresh
        GET per pending claim inside _sync_claim_key). O(pending), and
        pending claims are exactly the ones worth O(1 GET) each. In
        sharded mode the retries fan out to their shards so claim work
        stays serialized per key."""
        for claim in self.view.claims():
            if claim.get("status", {}).get("allocation"):
                continue
            if _meta(claim).get("deletionTimestamp"):
                continue
            if not self._owns(claim):
                continue
            ns = _meta(claim).get("namespace", "default")
            name = _meta(claim)["name"]
            if self._sharded:
                self._enqueue(("claim", ns, name))
            else:
                self._sync_claim_key(ns, name)

    # -- loop -----------------------------------------------------------------

    def run(self, interval: float = 0.25):
        while not self._stop.is_set():
            try:
                self.sync_once()
            except Exception:  # noqa: BLE001 - control loop
                logger.exception("scheduler sync failed")
            self._stop.wait(interval)

    def start(self) -> "DraScheduler":
        self._thread = threading.Thread(
            target=self.run, name="dra-scheduler", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        if self._queue is not None:
            self._queue.shutdown(wait=True)
            self._queue = None
        self.view.stop()


def run_leader_elected(sched: DraScheduler, namespace: str = "kube-system",
                       identity: str | None = None,
                       stop: threading.Event | None = None,
                       lease_name: str | None = None,
                       **lease_kwargs) -> None:
    """Gate a (typically per-domain) scheduler instance behind a Lease:
    the instance idles as a hot standby until it wins
    ``tpu-dra-scheduler-<domain>``, runs event-driven while holding it,
    and stops cleanly when the lease is lost or ``stop`` is set. This
    is the horizontal-scale surface: one leader-elected scheduler pair
    per scheduling domain, each consuming only its own pools' dirty
    keys."""
    from .leaderelection import LeaderElector  # noqa: PLC0415

    stop = stop if stop is not None else threading.Event()
    if lease_name is None:
        lease_name = (sched.domain.lease_name if sched.domain is not None
                      else "tpu-dra-scheduler")
    if identity is None:
        identity = f"sched-{uuid.uuid4().hex[:8]}"
    elector = LeaderElector(sched.kube, lease_name, namespace, identity,
                            **lease_kwargs)

    def lead():
        sched.start_event_driven()
        while not stop.is_set():
            stop.wait(0.2)

    elector.run(lead, stop, on_stopped_leading=sched.stop)
    sched.stop()


def main(argv: list[str] | None = None) -> int:
    from .kubeclient import KubeClient

    p = argparse.ArgumentParser(prog="tpu-dra-scheduler")
    p.add_argument("--kube-api", required=True)
    p.add_argument("--default-node", default=None)
    p.add_argument("--interval", type=float, default=0.25)
    p.add_argument("--sched-mode",
                   choices=("events", "poll"),
                   default=os.environ.get("TPU_DRA_SCHED_MODE", "events"),
                   help="'events' (default): informer-fed incremental "
                        "sync with a low-frequency safety resync; "
                        "'poll': the legacy full-resync loop at "
                        "--interval [TPU_DRA_SCHED_MODE]")
    p.add_argument("--sched-workers", type=int,
                   default=_env_int("TPU_DRA_SCHED_WORKERS",
                                    DEFAULT_SCHED_WORKERS),
                   help="sync-queue workers in events mode: 1 = "
                        "serialized drain; N>1 shards claim/pod keys "
                        "over N-1 data workers plus a dedicated "
                        "control-key worker [TPU_DRA_SCHED_WORKERS]")
    p.add_argument("--sched-batch", type=int,
                   default=_env_int("TPU_DRA_SCHED_BATCH",
                                    DEFAULT_SCHED_BATCH),
                   help="max dirty claim keys drained against one "
                        "inventory snapshot [TPU_DRA_SCHED_BATCH]")
    p.add_argument("--sched-domain",
                   default=os.environ.get("TPU_DRA_SCHED_DOMAIN", ""),
                   help="scheduling-domain name for scheduler-per-pool "
                        "sharding; empty = this instance owns "
                        "everything [TPU_DRA_SCHED_DOMAIN]")
    p.add_argument("--sched-domain-pools",
                   default=os.environ.get("TPU_DRA_SCHED_DOMAIN_POOLS",
                                          ""),
                   help="comma-separated pool names / fnmatch globs "
                        "this domain's snapshot is restricted to "
                        "[TPU_DRA_SCHED_DOMAIN_POOLS]")
    p.add_argument("--sched-domain-default", action="store_true",
                   default=os.environ.get("TPU_DRA_SCHED_DOMAIN_DEFAULT",
                                          "") in ("1", "true", "True"),
                   help="this domain owns unannotated objects and the "
                        "cluster-wide controllers "
                        "[TPU_DRA_SCHED_DOMAIN_DEFAULT]")
    p.add_argument("--sched-domain-siblings",
                   default=os.environ.get(
                       "TPU_DRA_SCHED_DOMAIN_SIBLINGS", ""),
                   help="spillover siblings for this domain, "
                        "'name=poolglob|poolglob;name2=glob' in "
                        "preference order: a claim pinned here that "
                        "cannot fit re-homes to the cheapest sibling "
                        "(migration-cost ranked) instead of pending "
                        "forever [TPU_DRA_SCHED_DOMAIN_SIBLINGS]")
    p.add_argument("--leader-elect", action="store_true",
                   default=os.environ.get("TPU_DRA_SCHED_LEADER_ELECT",
                                          "") in ("1", "true", "True"),
                   help="gate this instance behind the per-domain "
                        "Lease (hot-standby HA) "
                        "[TPU_DRA_SCHED_LEADER_ELECT]")
    p.add_argument("--leader-elect-namespace",
                   default=os.environ.get(
                       "TPU_DRA_SCHED_LEASE_NAMESPACE", "kube-system"),
                   help="namespace of the leader-election Lease "
                        "[TPU_DRA_SCHED_LEASE_NAMESPACE]")
    p.add_argument("--metrics-port", type=int,
                   default=int(os.environ.get("METRICS_PORT", "0")),
                   help="serve /metrics (placement frag/compactness + "
                        "scheduler sync/dirty-queue) on this port; "
                        "0 = disabled [METRICS_PORT]")
    p.add_argument("--recovery-root",
                   default=os.environ.get("TPU_DRA_RECOVERY_ROOT", ""),
                   help="state root for the permanent-failure "
                        "eviction controller's durable eviction "
                        "records; empty = recovery disabled "
                        "[TPU_DRA_RECOVERY_ROOT]")
    p.add_argument("--defrag-root",
                   default=os.environ.get("TPU_DRA_DEFRAG_ROOT", ""),
                   help="state root for the active-defragmentation "
                        "controller's durable move records; empty = "
                        "defrag disabled [TPU_DRA_DEFRAG_ROOT]")
    p.add_argument("--migration-root",
                   default=os.environ.get("TPU_DRA_MIGRATION_ROOT", ""),
                   help="state root for the cooperative live-migration "
                        "controller's durable move records "
                        "(checkpoint-then-switch handshakes, "
                        "pkg/migration); empty = cooperative "
                        "migration disabled [TPU_DRA_MIGRATION_ROOT]")
    p.add_argument("--autoscale-root",
                   default=os.environ.get("TPU_DRA_AUTOSCALE_ROOT", ""),
                   help="state root for the serving autoscaler's "
                        "durable re-plan records (the demand-driven "
                        "PartitionSet controller, pkg/autoscale); "
                        "empty = autoscaler disabled "
                        "[TPU_DRA_AUTOSCALE_ROOT]")
    args = p.parse_args(argv)
    from . import logsetup  # noqa: PLC0415

    # Shared logging contract incl. the trace-id correlation filter
    # (pkg/logsetup): scheduler log lines carry the same trace ids the
    # node plugins log, so one grep follows a claim across binaries.
    logsetup.setup(_env_int("V", 4))
    metrics = None
    sched_metrics = None
    server = None
    fleet_metrics = None
    if args.metrics_port:
        from .metrics import (  # noqa: PLC0415
            FleetMetrics,
            MetricsServer,
            PlacementMetrics,
            SchedulerMetrics,
        )

        metrics = PlacementMetrics()
        sched_metrics = SchedulerMetrics(registry=metrics.registry)
        fleet_metrics = FleetMetrics(registry=metrics.registry)
        server = MetricsServer(metrics.registry, host="0.0.0.0",
                               port=args.metrics_port)
        server.start()
    from .retry import RetryingKubeClient  # noqa: PLC0415

    resilience = None
    if server is not None:
        from .metrics import ResilienceMetrics  # noqa: PLC0415

        resilience = ResilienceMetrics(registry=metrics.registry)
    domain = None
    if args.sched_domain:
        domain = SchedulingDomain(
            args.sched_domain,
            pools=[p.strip() for p in args.sched_domain_pools.split(",")
                   if p.strip()],
            default=args.sched_domain_default,
            siblings=SchedulingDomain.parse_siblings(
                args.sched_domain_siblings))
    sched = DraScheduler(RetryingKubeClient(KubeClient(host=args.kube_api),
                                            metrics=resilience),
                         default_node=args.default_node,
                         metrics=metrics, sched_metrics=sched_metrics,
                         workers=args.sched_workers,
                         batch_max=args.sched_batch, domain=domain,
                         fleet_metrics=fleet_metrics)
    if metrics is not None:
        from .metrics import register_build_info  # noqa: PLC0415

        register_build_info(metrics.registry, sched.gates)
    if args.recovery_root:
        from .metrics import RecoveryMetrics  # noqa: PLC0415
        from .recovery import EvictionController  # noqa: PLC0415

        recovery_metrics = (RecoveryMetrics(registry=metrics.registry)
                            if metrics is not None else None)
        sched.attach_recovery(EvictionController(
            sched.kube, args.recovery_root, metrics=recovery_metrics))
    if args.defrag_root:
        from .defrag import DefragController  # noqa: PLC0415
        from .metrics import DefragMetrics  # noqa: PLC0415

        defrag_metrics = (DefragMetrics(registry=metrics.registry)
                          if metrics is not None else None)
        sched.attach_defrag(DefragController(
            sched.kube, args.defrag_root, metrics=defrag_metrics))
    if args.migration_root:
        from .metrics import MigrationMetrics  # noqa: PLC0415
        from .migration import MigrationController  # noqa: PLC0415

        migration_metrics = (MigrationMetrics(registry=metrics.registry)
                             if metrics is not None else None)
        sched.attach_migration(MigrationController(
            sched.kube, args.migration_root,
            metrics=migration_metrics))
    if args.autoscale_root:
        from .autoscale import AutoscaleController  # noqa: PLC0415
        from .metrics import AutoscaleMetrics  # noqa: PLC0415

        autoscale_metrics = (AutoscaleMetrics(registry=metrics.registry)
                             if metrics is not None else None)
        sched.attach_autoscaler(AutoscaleController(
            sched.kube, args.autoscale_root,
            metrics=autoscale_metrics))
    print("scheduler running", flush=True)
    try:
        if args.sched_mode == "events" and args.leader_elect:
            run_leader_elected(sched,
                               namespace=args.leader_elect_namespace)
        elif args.sched_mode == "events":
            sched.start_event_driven()
            while True:
                time.sleep(60)
        else:
            sched.run(args.interval)
    except KeyboardInterrupt:
        pass
    finally:
        sched.stop()
        if server is not None:
            server.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
