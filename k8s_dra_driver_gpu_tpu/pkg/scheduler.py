"""A DRA-aware scheduler + resourceclaim controller stand-in.

The reference never ships this logic -- it relies on the real
kube-scheduler's DRA plugin and kube-controller-manager's resourceclaim
controller (vendored under k8s.io/dynamic-resource-allocation). Our
first-contact tier has no kubelet or scheduler binaries available, so
this module implements the two control-plane behaviors the e2e tier
needs, faithfully enough that the REAL driver binaries cannot tell the
difference:

1. **Claim generation** (kcm resourceclaim controller): a pod whose
   ``spec.resourceClaims[]`` entry names a ``resourceClaimTemplateName``
   gets a generated ResourceClaim (owner-ref'd to the pod) and a
   ``status.resourceClaimStatuses`` mapping.
2. **Allocation** (kube-scheduler DRA plugin, structured parameters
   KEP-4381): for each unallocated claim, walk published
   ResourceSlices at their newest pool generation, filter devices
   through DeviceClass + request CEL selectors (pkg/cel.py), skip
   devices already allocated or tainted NoSchedule/NoExecute (unless
   tolerated), enforce KEP-4815 shared-counter budgets so partitioned
   devices can never over-commit their parent, then write
   ``status.allocation`` (results + config + nodeSelector) and reserve
   the claim for its consumer pods.
3. **Binding**: pods whose claims are all allocated get
   ``spec.nodeName`` patched to the (single) node the allocation pins.

Used by the executable e2e tier (TPU_DRA_E2E=fake) and runnable as a
standalone control-plane binary:

    python -m k8s_dra_driver_gpu_tpu.pkg.scheduler --kube-api http://...
"""

from __future__ import annotations

import argparse
import logging
import threading
import time
import uuid

from .cel import CelEvalError, CelProgram, Quantity, compile_expression
from .featuregates import (
    TOPOLOGY_AWARE_PLACEMENT,
    FeatureGateError,
    FeatureGates,
)
from .kubeclient import ConflictError, KubeError, NotFoundError
from .topology import TorusGrid, largest_free_shape
from .topology.score import frag_from_largest
from .topology import order_candidates as topo_order_candidates
from .topology import set_compactness

logger = logging.getLogger(__name__)

RESOURCE = ("resource.k8s.io", "v1")


def _meta(obj):
    return obj.get("metadata", {})


# Deep-copy discipline for API objects lives in one place now
# (pkg.json_copy); re-exported here for the existing import sites.
from . import json_copy  # noqa: E402,F401


class _CompiledSelectors:
    """Expression -> CelProgram cache; a selector that fails to compile
    permanently matches nothing (and is logged once), like a CEL
    compile error surfaced in the scheduler.

    The cache is shared process-wide (class-level, lock-guarded) and
    keyed by source text: a scheduler instantiated per sync pass still
    reuses every previously compiled selector, and within one pass each
    distinct expression compiles at most once no matter how many
    candidate devices it filters. cel.compile_expression additionally
    memoizes the parsed AST, so even a fresh cache entry skips the
    lex+parse for text seen anywhere else in the process."""

    _shared: dict[str, CelProgram | None] = {}
    _shared_lock = threading.Lock()
    _MAX = 4096  # selectors are operator-authored; this is a leak bound

    def __init__(self):
        self._cache = self._shared

    def get(self, expression: str) -> CelProgram | None:
        with self._shared_lock:
            if expression in self._cache:
                return self._cache[expression]
        try:
            prog = compile_expression(expression)
        except Exception as e:  # noqa: BLE001 - compile boundary
            logger.error("selector does not compile (%s): %s",
                         e, expression)
            prog = None
        with self._shared_lock:
            if len(self._cache) >= self._MAX:
                self._cache.clear()
            self._cache[expression] = prog
        return prog


class _CounterLedger:
    """Available KEP-4815 counters per (driver, pool, counterSet),
    seeded from sharedCounters and debited by consumesCounters."""

    def __init__(self):
        self._avail: dict[tuple, dict[str, int]] = {}

    def seed(self, driver: str, pool: str, counter_sets: list[dict]):
        for cs in counter_sets or []:
            key = (driver, pool, cs.get("name", ""))
            if key in self._avail:
                continue
            self._avail[key] = {
                name: Quantity.parse(val.get("value", "0")).milli
                for name, val in (cs.get("counters") or {}).items()
            }

    def _iter_demand(self, driver, pool, consumes):
        for block in consumes or []:
            key = (driver, pool, block.get("counterSet", ""))
            for name, val in (block.get("counters") or {}).items():
                yield key, name, Quantity.parse(
                    val.get("value", "0")).milli

    def fits(self, driver: str, pool: str, consumes: list[dict]) -> bool:
        for key, name, milli in self._iter_demand(driver, pool, consumes):
            have = self._avail.get(key, {}).get(name)
            if have is None or have < milli:
                return False
        return True

    def debit(self, driver: str, pool: str, consumes: list[dict]):
        for key, name, milli in self._iter_demand(driver, pool, consumes):
            if key in self._avail and name in self._avail[key]:
                self._avail[key][name] -= milli

    def credit(self, driver: str, pool: str, consumes: list[dict]):
        """Undo a debit (the backtracking allocator un-picks devices)."""
        for key, name, milli in self._iter_demand(driver, pool, consumes):
            if key in self._avail and name in self._avail[key]:
                self._avail[key][name] += milli


class _Candidate:
    __slots__ = ("driver", "pool", "node", "device")

    def __init__(self, driver, pool, node, device):
        self.driver = driver
        self.pool = pool
        self.node = node
        self.device = device

    @property
    def name(self):
        return self.device["name"]

    @property
    def key(self):
        return (self.driver, self.pool, self.name)


class _FitBudgetExceeded(Exception):
    """The bounded constraint DFS ran out of states (see MAX_FIT_STEPS)."""


def _tolerates(taint: dict, tolerations: list[dict]) -> bool:
    for tol in tolerations or []:
        if tol.get("effect") and tol["effect"] != taint.get("effect"):
            continue
        op = tol.get("operator", "Equal")
        if op == "Exists":
            if not tol.get("key") or tol["key"] == taint.get("key"):
                return True
        elif tol.get("key") == taint.get("key") and \
                tol.get("value", "") == taint.get("value", ""):
            return True
    return False


class DraScheduler:
    """Single-pass-capable scheduler; call sync_once() or run()."""

    def __init__(self, kube, default_node: str | None = None,
                 gates: FeatureGates | None = None, metrics=None):
        self.kube = kube
        self.default_node = default_node
        self._selectors = _CompiledSelectors()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if gates is None:
            try:
                gates = FeatureGates.from_env()
            except FeatureGateError:
                # A malformed FEATURE_GATES env must not kill the
                # control plane; defaults are the safe fallback.
                logger.exception("FEATURE_GATES unparseable; using defaults")
                gates = FeatureGates()
        self.gates = gates
        # ICI topology-aware device picking (pkg/topology). Off = the
        # historical first-fit order, which also remains the automatic
        # fallback whenever devices publish no usable coordinates.
        self._topology = gates.is_enabled(TOPOLOGY_AWARE_PLACEMENT)
        self.metrics = metrics  # PlacementMetrics or None
        # Per-sync-pass memos (reset in _allocate_claims): scoring a
        # pool and resolving CD windows are pure functions of snapshot
        # state, and one pass asks the same questions per claim x node.
        self._pass_order_cache: dict[tuple, list[str] | None] = {}
        self._pass_cd_windows: dict[str, list[str]] | None = None

    # -- claim generation (kcm resourceclaim controller) ----------------------

    def _pods(self) -> list[dict]:
        try:
            return self.kube.list("", "v1", "pods")
        except KubeError:
            return []

    def _generate_claims(self):
        for pod in self._pods():
            refs = pod.get("spec", {}).get("resourceClaims") or []
            statuses = pod.get("status", {}).get(
                "resourceClaimStatuses") or []
            have = {s["name"] for s in statuses}
            ns = _meta(pod).get("namespace", "default")
            new_statuses = []
            for ref in refs:
                tmpl = ref.get("resourceClaimTemplateName")
                if not tmpl or ref["name"] in have:
                    continue
                try:
                    template = self.kube.get(
                        *RESOURCE, "resourceclaimtemplates", tmpl,
                        namespace=ns)
                except NotFoundError:
                    continue  # template not applied yet; retry next pass
                claim_name = (f"{_meta(pod)['name']}-{ref['name']}-"
                              f"{uuid.uuid4().hex[:5]}")
                claim = {
                    "apiVersion": "resource.k8s.io/v1",
                    "kind": "ResourceClaim",
                    "metadata": {
                        "name": claim_name,
                        "namespace": ns,
                        "uid": f"claim-{uuid.uuid4().hex[:12]}",
                        "annotations": {
                            "resource.kubernetes.io/pod-claim-name":
                                ref["name"],
                        },
                        "ownerReferences": [{
                            "apiVersion": "v1", "kind": "Pod",
                            "name": _meta(pod)["name"],
                            "uid": _meta(pod).get("uid", ""),
                            "controller": True,
                        }],
                    },
                    "spec": template.get("spec", {}).get("spec", {}),
                }
                try:
                    self.kube.create(*RESOURCE, "resourceclaims", claim,
                                     namespace=ns)
                except ConflictError:
                    pass
                new_statuses.append(
                    {"name": ref["name"], "resourceClaimName": claim_name})
            if new_statuses:
                self.kube.patch(
                    "", "v1", "pods", _meta(pod)["name"],
                    {"status": {"resourceClaimStatuses":
                                statuses + new_statuses}},
                    namespace=ns)

    def _generate_extended_resource_claims(self):
        """KEP-5004 (DRAExtendedResource): a pod requesting an extended
        resource that a DeviceClass advertises via
        ``spec.extendedResourceName`` gets an auto-generated
        ResourceClaim against that class, recorded in
        ``pod.status.extendedResourceClaimStatus`` -- the legacy
        ``google.com/tpu: N`` surface (reference analog: the
        'nvidia.com/gpu with DRAExtendedResource' bats scenario, which
        delegates to kube-scheduler; here the in-tree scheduler does
        it so demo/specs/extended-resources executes for real)."""
        try:
            by_resource = self._extended_resource_classes()
        except KubeError:
            return
        if not by_resource:
            return
        for pod in self._pods():
            if pod.get("status", {}).get("extendedResourceClaimStatus"):
                continue
            # KEP-5004 generates claims only while a pod is still being
            # SCHEDULED: one already bound (spec.nodeName set -- e.g.
            # scheduled before the class advertised
            # extendedResourceName, or born bound like a DaemonSet pod)
            # or past Pending must not retroactively acquire devices
            # and double-count them under a running workload.
            if pod.get("spec", {}).get("nodeName"):
                continue
            if pod.get("status", {}).get("phase") not in (None, "",
                                                          "Pending"):
                continue
            if _meta(pod).get("deletionTimestamp"):
                continue
            requests, mappings = [], []
            bad_qty = None
            for c in pod.get("spec", {}).get("containers", []):
                limits = (c.get("resources") or {}).get("limits") or {}
                for rname, qty in limits.items():
                    cls_name = by_resource.get(rname)
                    if not cls_name:
                        continue
                    # Extended-resource quantities must be whole
                    # numbers; a malformed one must not wedge the
                    # whole scheduling pass.
                    try:
                        count = int(str(qty))
                    except ValueError:
                        logger.warning(
                            "pod %s/%s: non-integer extended-resource "
                            "quantity %s=%r; skipping pod",
                            _meta(pod).get("namespace", "default"),
                            _meta(pod)["name"], rname, qty)
                        bad_qty = f"{rname}={qty!r}"
                        break
                    req = f"request-{len(mappings)}"
                    exactly: dict = {"deviceClassName": cls_name}
                    if count != 1:
                        exactly["count"] = count
                    requests.append({"name": req, "exactly": exactly})
                    mappings.append({
                        "containerName": c.get("name", ""),
                        "resourceName": rname,
                        "requestName": req,
                    })
                if bad_qty:
                    break
            if bad_qty:
                # The pod can never schedule (the generation skip keeps
                # _pending_extended_resource blocking its bind forever):
                # surface that ON THE POD -- real k8s rejects
                # non-integer extended resources at admission, but this
                # control plane has no pod admission, so a condition +
                # event is the observable analog.
                self._flag_unschedulable_pod(
                    pod, "InvalidExtendedResourceQuantity",
                    f"extended-resource quantity {bad_qty} is not a "
                    "whole number; the pod cannot be scheduled")
                continue
            if not requests:
                continue
            ns = _meta(pod).get("namespace", "default")
            # DETERMINISTIC name (pod uid, not uuid4): create + status
            # patch are not atomic, and a retried pass must converge on
            # the same claim instead of leaking allocated orphans.
            pod_uid = _meta(pod).get("uid", "") or _meta(pod)["name"]
            claim_name = (f"{_meta(pod)['name']}-extended-resources-"
                          f"{pod_uid[-5:]}")
            claim = {
                "apiVersion": "resource.k8s.io/v1",
                "kind": "ResourceClaim",
                "metadata": {
                    "name": claim_name,
                    "namespace": ns,
                    "uid": f"claim-{uuid.uuid4().hex[:12]}",
                    "ownerReferences": [{
                        "apiVersion": "v1", "kind": "Pod",
                        "name": _meta(pod)["name"],
                        "uid": _meta(pod).get("uid", ""),
                        "controller": True,
                    }],
                },
                "spec": {"devices": {"requests": requests}},
            }
            try:
                self.kube.create(*RESOURCE, "resourceclaims", claim,
                                 namespace=ns)
            except ConflictError:
                pass  # an earlier pass created it; converge on it
            self.kube.patch(
                "", "v1", "pods", _meta(pod)["name"],
                {"status": {"extendedResourceClaimStatus": {
                    "resourceClaimName": claim_name,
                    "requestMappings": mappings,
                }}},
                namespace=ns)
            logger.info(
                "generated extended-resource claim %s/%s for pod %s",
                ns, claim_name, _meta(pod)["name"])

    def _flag_unschedulable_pod(self, pod, reason: str,
                                message: str) -> None:
        """Surface a permanent scheduling failure ON THE POD: a
        PodScheduled=False condition plus a Warning Event, so `kubectl
        describe pod` explains the wedge instead of only a scheduler
        log line. Deduped on (reason, message): a condition already
        saying exactly this is not re-emitted every sync pass."""
        ns = _meta(pod).get("namespace", "default")
        name = _meta(pod)["name"]
        conditions = pod.get("status", {}).get("conditions") or []
        for c in conditions:
            if c.get("type") == "PodScheduled" and \
                    c.get("reason") == reason and \
                    c.get("message") == message:
                return
        kept = [c for c in conditions if c.get("type") != "PodScheduled"]
        kept.append({
            "type": "PodScheduled",
            "status": "False",
            "reason": reason,
            "message": message,
        })
        try:
            self.kube.patch("", "v1", "pods", name,
                            {"status": {"conditions": kept}},
                            namespace=ns)
        except (NotFoundError, ConflictError):
            return
        event = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {
                "name": f"{name}.{uuid.uuid4().hex[:10]}",
                "namespace": ns,
            },
            "type": "Warning",
            "reason": reason,
            "message": message,
            "involvedObject": {
                "kind": "Pod", "name": name, "namespace": ns,
                "uid": _meta(pod).get("uid", ""),
            },
            "source": {"component": "tpu-dra-scheduler"},
        }
        try:
            self.kube.create("", "v1", "events", event, namespace=ns)
        except KubeError:
            pass  # events are best-effort, the condition already landed

    # -- allocation (kube-scheduler DRA plugin) -------------------------------

    def _snapshot(self):
        """(candidates, ledger, allocated-device keys) from the newest
        generation of every published pool."""
        slices = self.kube.list(*RESOURCE, "resourceslices")
        newest: dict[tuple, int] = {}
        for s in slices:
            spec = s.get("spec", {})
            pool = spec.get("pool", {})
            key = (spec.get("driver", ""), pool.get("name", ""))
            newest[key] = max(newest.get(key, 0), pool.get("generation", 0))
        candidates: list[_Candidate] = []
        ledger = _CounterLedger()
        for s in slices:
            spec = s.get("spec", {})
            pool = spec.get("pool", {})
            driver = spec.get("driver", "")
            pool_name = pool.get("name", "")
            if pool.get("generation", 0) != newest[(driver, pool_name)]:
                continue  # stale generation: invisible to allocation
            node = spec.get("nodeName") or self.default_node or ""
            ledger.seed(driver, pool_name, spec.get("sharedCounters"))
            for dev in spec.get("devices", []):
                candidates.append(_Candidate(driver, pool_name, node, dev))

        allocated: set[tuple] = set()
        for claim in self.kube.list(*RESOURCE, "resourceclaims"):
            alloc = claim.get("status", {}).get("allocation")
            if not alloc:
                continue
            for res in alloc.get("devices", {}).get("results", []):
                key = (res.get("driver", ""), res.get("pool", ""),
                       res.get("device", ""))
                allocated.add(key)
        by_key = {c.key: c for c in candidates}
        for key in allocated:
            cand = by_key.get(key)
            if cand is not None:
                ledger.debit(cand.driver, cand.pool,
                             cand.device.get("consumesCounters"))
        return candidates, ledger, allocated, by_key

    def _device_matches(self, cand: _Candidate, selectors: list[dict],
                        tolerations: list[dict]) -> bool:
        for taint in cand.device.get("taints") or []:
            if taint.get("effect") in ("NoSchedule", "NoExecute") and \
                    not _tolerates(taint, tolerations):
                return False
        for sel in selectors:
            expr = (sel.get("cel") or {}).get("expression", "")
            prog = self._selectors.get(expr)
            if prog is None or not prog.matches_device(
                    cand.device, cand.driver):
                return False
        return True

    def _device_classes(self) -> dict[str, dict]:
        return {
            _meta(c)["name"]: c
            for c in self.kube.list(*RESOURCE, "deviceclasses")
        }

    def _try_allocate(self, claim, candidates, ledger, allocated,
                      classes, by_key, pinned_node: str | None = None
                      ) -> dict | None:
        """One claim against the snapshot. Returns the allocation or
        None; mutates ledger/allocated on success. ``pinned_node``
        restricts placement to the node a consumer pod is already bound
        to (real DRA allocates during that pod's scheduling, so the
        choice is inherently per-node)."""
        requests = claim.get("spec", {}).get("devices", {}).get(
            "requests", [])
        if not requests:
            return None
        # Node-local pools pin the whole claim to one node: try each
        # candidate node until every request fits (kube-scheduler does
        # this per-node in Filter). Least-allocated node first -- the
        # spreading a real scheduler gets from per-pod Filter/Score;
        # without it a multi-node gang would pile onto one node.
        load: dict[str, int] = {}
        for key in allocated:
            cand = by_key.get(key)
            if cand is not None:
                load[cand.node] = load.get(cand.node, 0) + 1
        # ComputeDomain gangs first try the ICI-adjacent host window
        # the CD controller picked; load still spreads the gang's
        # members WITHIN the window, and non-window nodes remain as
        # overflow so a full window degrades instead of wedging.
        window = set(self._preferred_gang_nodes(claim) or ())
        nodes = sorted({c.node for c in candidates},
                       key=lambda n: (0 if not window or n in window
                                      else 1, load.get(n, 0), n))
        if pinned_node is not None:
            nodes = [n for n in nodes if n == pinned_node]
        for node in nodes:
            picks = self._fit_on_node(
                claim, node, candidates, ledger, allocated, classes)
            if picks is None:
                continue
            results, configs = [], []
            seen_classes = []
            for req_name, cand, class_name in picks:
                results.append({
                    "request": req_name,
                    "driver": cand.driver,
                    "pool": cand.pool,
                    "device": cand.name,
                })
                allocated.add(cand.key)
                ledger.debit(cand.driver, cand.pool,
                             cand.device.get("consumesCounters"))
                if class_name not in seen_classes:
                    seen_classes.append(class_name)
            for class_name in seen_classes:
                for cfg in classes.get(class_name, {}).get(
                        "spec", {}).get("config", []) or []:
                    if "opaque" in cfg:
                        configs.append({
                            "opaque": cfg["opaque"],
                            "requests": [],
                            "source": "FromClass",
                        })
            for cfg in claim.get("spec", {}).get("devices", {}).get(
                    "config", []) or []:
                if "opaque" in cfg:
                    configs.append({
                        "opaque": cfg["opaque"],
                        "requests": cfg.get("requests", []),
                        "source": "FromClaim",
                    })
            alloc = {
                "devices": {"results": results, "config": configs},
                "nodeSelector": {"nodeSelectorTerms": [{
                    "matchFields": [{
                        "key": "metadata.name",
                        "operator": "In",
                        "values": [node],
                    }],
                }]},
            }
            return alloc
        return None

    # DFS budget for the constraint-aware fit: a claim that cannot be
    # decided within this many visited states is treated as unsatisfiable
    # on the node (and logged). Topology claims are tiny (a handful of
    # requests over tens of devices); the bound only guards pathological
    # specs.
    MAX_FIT_STEPS = 20_000

    @staticmethod
    def _attr_value(cand: _Candidate, attr: str):
        """Typed attribute value as a comparable (type, value) tuple, or
        None when the device does not carry the attribute. ``attr`` may
        be plain ("iciY") or driver-qualified ("tpu.dra.dev/iciY") --
        a driver's own attributes are implicitly qualified by its name
        (upstream structured-parameters semantics)."""
        attrs = cand.device.get("attributes") or {}
        entry = attrs.get(attr)
        if entry is None and "/" in attr:
            domain, _, base = attr.partition("/")
            if domain == cand.driver:
                entry = attrs.get(base)
        if not isinstance(entry, dict):
            return None
        for kind in ("string", "int", "bool", "version"):
            if kind in entry:
                return (kind, entry[kind])
        return None

    # -- ICI topology-aware ordering (pkg/topology) ---------------------------

    @staticmethod
    def _grid_for(cands: list["_Candidate"]) -> TorusGrid:
        return TorusGrid.from_devices([c.device for c in cands])

    def _topology_order(self, cands: list["_Candidate"],
                        want: int | None) -> list["_Candidate"]:
        """Reorder one request's candidates so the scorer's best
        sub-torus placements come first. Pure preference: every
        candidate stays in the list, so the backtracking fit (and
        therefore matchAttributes, counters, taints) is untouched --
        with no usable coordinates the original first-fit order
        survives verbatim. ``want`` None (All-mode) takes everything
        anyway; nothing to order."""
        if want is None or want < 1 or len(cands) < 2:
            return cands
        by_pool: dict[tuple, list[_Candidate]] = {}
        for c in cands:
            by_pool.setdefault((c.driver, c.pool), []).append(c)
        out: list[_Candidate] = []
        any_signal = False
        for (driver, pool), group in by_pool.items():
            ordered = None
            if len(group) >= want:
                names = tuple(c.name for c in group)
                key = (driver, pool, names, want)
                if key in self._pass_order_cache:
                    ordered = self._pass_order_cache[key]
                else:
                    grid = self._grid_for(group)
                    ordered = topo_order_candidates(grid, list(names),
                                                    want)
                    self._pass_order_cache[key] = ordered
            if ordered is None:
                out.extend(group)
            else:
                any_signal = True
                by_name = {c.name: c for c in group}
                out.extend(by_name[n] for n in ordered)
        # No group produced a ranking: keep the ORIGINAL interleaved
        # order, not the per-pool regrouping -- the documented fallback
        # is the pre-topology first-fit order, verbatim.
        return out if any_signal else cands

    def _preferred_gang_nodes(self, claim) -> list[str] | None:
        """ComputeDomain channel claims prefer the ICI-adjacent host
        window the CD controller picked (its preferred-nodes
        annotation): the gang's workers land on consecutive workerIds
        instead of whatever nodes happened to be least loaded."""
        if not self._topology:
            return None
        for cfg in claim.get("spec", {}).get("devices", {}).get(
                "config", []) or []:
            params = (cfg.get("opaque") or {}).get("parameters") or {}
            if params.get("kind") != "ComputeDomainChannelConfig":
                continue
            uid = params.get("domainID")
            if not uid:
                continue
            return self._cd_window_map().get(uid) or None
        return None

    def _cd_window_map(self) -> dict[str, list[str]]:
        """uid -> preferred-node window for every ComputeDomain, listed
        once per sync pass (N pending channel claims must not mean N
        full CD lists against the apiserver)."""
        if self._pass_cd_windows is not None:
            return self._pass_cd_windows
        from ..computedomain import (  # noqa: PLC0415 - leaf consts
            API_GROUP,
            API_VERSION,
            PREFERRED_NODES_ANNOTATION,
        )

        try:
            cds = self.kube.list(API_GROUP, API_VERSION,
                                 "computedomains")
        except KubeError:
            # Transient failure: cache the empty answer for the REST of
            # this pass (don't hammer a struggling apiserver once per
            # pending claim); the next pass retries fresh.
            self._pass_cd_windows = {}
            return self._pass_cd_windows
        windows: dict[str, list[str]] = {}
        for cd in cds:
            uid = _meta(cd).get("uid")
            ann = (_meta(cd).get("annotations") or {}).get(
                PREFERRED_NODES_ANNOTATION, "")
            if uid:
                windows[uid] = [n for n in ann.split(",") if n]
        self._pass_cd_windows = windows
        return windows

    def _observe_placement(self, alloc, candidates, allocated) -> None:
        """Export placement quality for a fresh allocation: compactness
        of the chosen set, plus the post-pick fragmentation / largest
        allocatable shape of every pool it drew from."""
        if self.metrics is None or not self._topology:
            return
        by_pool: dict[tuple, list[str]] = {}
        for res in alloc.get("devices", {}).get("results", []):
            by_pool.setdefault((res.get("driver", ""), res.get("pool", "")),
                               []).append(res.get("device", ""))
        for (driver, pool), picked in by_pool.items():
            devs = [c for c in candidates
                    if c.driver == driver and c.pool == pool]
            if not devs:
                continue
            grid = self._grid_for(devs)
            cells = {grid.coords[n] for n in picked if n in grid.coords}
            if not cells:
                continue  # uncoordinated pool: nothing to report
            label = f"{driver}/{pool}"
            hops, _ = set_compactness(grid, cells)
            self.metrics.compactness.labels(label).observe(hops)
            free = {grid.coords[c.name] for c in devs
                    if c.key not in allocated and c.name in grid.coords}
            # One largest_free_shape sweep feeds both gauges (it is the
            # most expensive topology operation on big pools).
            _, chips = largest_free_shape(grid, free)
            self.metrics.frag_score.labels(label).set(
                frag_from_largest(chips, len(free)))
            self.metrics.largest_shape.labels(label).set(chips)

    def _fit_on_node(self, claim, node, candidates, ledger, allocated,
                     classes):
        """All requests of one claim against one node; returns
        [(request, candidate, class_name)] or None. Counter fits are
        checked against a tentative ledger so multi-device claims can't
        double-spend.

        ``spec.devices.constraints[].matchAttribute`` (KEP-4381): every
        device allocated for the constraint's requests (all requests
        when the list is empty) must carry the SAME value for the named
        attribute; a device lacking the attribute never satisfies it.
        For a TPU driver this is THE topology primitive -- e.g.
        matchAttribute on iciY+iciZ pins a multi-chip claim to one ICI
        ring. Choices interact across requests, so the fit backtracks
        (bounded DFS) instead of picking greedily: the first candidate's
        attribute value must not doom an otherwise-satisfiable claim.
        """
        spec = claim.get("spec", {}).get("devices", {})
        reqs = []
        for req in spec.get("requests", []):
            exactly = req.get("exactly") or req  # v1 nests under exactly
            class_name = exactly.get("deviceClassName", "")
            cls = classes.get(class_name)
            if cls is None:
                return None
            selectors = list(cls.get("spec", {}).get("selectors") or [])
            selectors += list(exactly.get("selectors") or [])
            mode = exactly.get("allocationMode", "ExactCount")
            reqs.append({
                "name": req.get("name", "r"),
                "class": class_name,
                "want": (int(exactly.get("count", 1))
                         if mode != "All" else None),
                "cands": [
                    cand for cand in candidates
                    if cand.node == node and cand.key not in allocated
                    and self._device_matches(
                        cand, selectors,
                        list(exactly.get("tolerations") or []))
                ],
            })
        if self._topology:
            for r in reqs:
                r["cands"] = self._topology_order(r["cands"], r["want"])
        constraints = []
        for c in spec.get("constraints") or []:
            attr = c.get("matchAttribute")
            if not attr:
                # Unknown constraint type: fail closed like the upstream
                # allocator (an unenforceable constraint must not be
                # silently dropped).
                return None
            constraints.append({
                "requests": set(c.get("requests") or []) or None,
                "attr": attr,
            })

        spent = _CounterLedger()
        spent._avail = {k: dict(v) for k, v in ledger._avail.items()}
        cvals: list = [None] * len(constraints)
        state = {"steps": 0}

        def applies(ci, req_name):
            want = constraints[ci]["requests"]
            return want is None or req_name in want

        def try_pick(req, cand, taken):
            """Constraint+counter check for one candidate; returns an
            undo closure or None."""
            consumes = cand.device.get("consumesCounters")
            if not spent.fits(cand.driver, cand.pool, consumes):
                return None
            set_cis = []
            for ci, c in enumerate(constraints):
                if not applies(ci, req["name"]):
                    continue
                val = self._attr_value(cand, c["attr"])
                if val is None:
                    return None  # attribute absent: never satisfiable
                if cvals[ci] is None:
                    set_cis.append(ci)
                elif cvals[ci] != val:
                    return None
            for ci, c in enumerate(constraints):
                if ci in set_cis:
                    cvals[ci] = self._attr_value(cand, c["attr"])
            spent.debit(cand.driver, cand.pool, consumes)
            taken.add(cand.key)

            def undo():
                taken.discard(cand.key)
                spent.credit(cand.driver, cand.pool, consumes)
                for ci in set_cis:
                    cvals[ci] = None
            return undo

        def fit(ri, slot_start, got, taken):
            state["steps"] += 1
            if state["steps"] > self.MAX_FIT_STEPS:
                raise _FitBudgetExceeded
            if ri == len(reqs):
                return []
            req = reqs[ri]
            if req["want"] is None:
                # All-mode: every eligible device, and every one must
                # satisfy the constraints (no subsetting).
                picks, undos = [], []
                for cand in req["cands"]:
                    if cand.key in taken:
                        continue
                    undo = try_pick(req, cand, taken)
                    if undo is None:
                        for u in reversed(undos):
                            u()
                        return None
                    undos.append(undo)
                    picks.append((req["name"], cand, req["class"]))
                if not picks:
                    return None
                rest = fit(ri + 1, 0, 0, taken)
                if rest is None:
                    for u in reversed(undos):
                        u()
                    return None
                return picks + rest
            if got == req["want"]:
                return fit(ri + 1, 0, 0, taken)
            for i in range(slot_start, len(req["cands"])):
                cand = req["cands"][i]
                if cand.key in taken:
                    continue
                undo = try_pick(req, cand, taken)
                if undo is None:
                    continue
                rest = fit(ri, i + 1, got + 1, taken)
                if rest is not None:
                    return [(req["name"], cand, req["class"])] + rest
                undo()
            return None

        try:
            return fit(0, 0, 0, set())
        except _FitBudgetExceeded:
            logger.warning(
                "claim %s/%s: constraint fit exceeded %d states on node "
                "%s; treating as unsatisfiable there",
                _meta(claim).get("namespace", "default"),
                _meta(claim).get("name", "?"), self.MAX_FIT_STEPS, node)
            return None

    def _claim_pins(self) -> dict[tuple[str, str], str]:
        """(namespace, claim name) -> node, for claims whose consumer
        pod is already bound (DaemonSet pods are born bound)."""
        pins: dict[tuple[str, str], str] = {}
        for pod in self._pods():
            node = pod.get("spec", {}).get("nodeName")
            if not node:
                continue
            ns = _meta(pod).get("namespace", "default")
            statuses = {
                s["name"]: s.get("resourceClaimName")
                for s in pod.get("status", {}).get(
                    "resourceClaimStatuses") or []
            }
            for ref in pod.get("spec", {}).get("resourceClaims") or []:
                claim_name = ref.get("resourceClaimName") or statuses.get(
                    ref["name"])
                if claim_name:
                    pins[(ns, claim_name)] = node
            ext = pod.get("status", {}).get(
                "extendedResourceClaimStatus") or {}
            if ext.get("resourceClaimName"):
                pins[(ns, ext["resourceClaimName"])] = node
        return pins

    def _allocate_claims(self):
        self._pass_order_cache = {}
        self._pass_cd_windows = None
        candidates, ledger, allocated, by_key = self._snapshot()
        classes = self._device_classes()
        pins = self._claim_pins()
        for claim in self.kube.list(*RESOURCE, "resourceclaims"):
            if claim.get("status", {}).get("allocation"):
                continue
            if _meta(claim).get("deletionTimestamp"):
                continue
            pin = pins.get((_meta(claim).get("namespace", "default"),
                            _meta(claim)["name"]))
            alloc = self._try_allocate(
                claim, candidates, ledger, allocated, classes, by_key,
                pinned_node=pin)
            if alloc is None:
                continue
            ns = _meta(claim).get("namespace", "default")
            try:
                self.kube.patch(
                    *RESOURCE, "resourceclaims", _meta(claim)["name"],
                    {"status": {"allocation": alloc}}, namespace=ns)
            except (NotFoundError, ConflictError):
                continue
            self._observe_placement(alloc, candidates, allocated)
            logger.info(
                "allocated claim %s/%s -> %s", ns, _meta(claim)["name"],
                [r["device"] for r in alloc["devices"]["results"]])

    # -- binding --------------------------------------------------------------

    def _claims_for_pod(self, pod) -> list[tuple[str, dict | None]]:
        ns = _meta(pod).get("namespace", "default")
        statuses = {
            s["name"]: s.get("resourceClaimName")
            for s in pod.get("status", {}).get("resourceClaimStatuses") or []
        }
        out = []
        for ref in pod.get("spec", {}).get("resourceClaims") or []:
            claim_name = ref.get("resourceClaimName") or statuses.get(
                ref["name"])
            if not claim_name:
                out.append((ref["name"], None))
                continue
            try:
                out.append((claim_name, self.kube.get(
                    *RESOURCE, "resourceclaims", claim_name,
                    namespace=ns)))
            except NotFoundError:
                out.append((claim_name, None))
        ext = pod.get("status", {}).get("extendedResourceClaimStatus") or {}
        if ext.get("resourceClaimName"):
            try:
                out.append((ext["resourceClaimName"], self.kube.get(
                    *RESOURCE, "resourceclaims",
                    ext["resourceClaimName"], namespace=ns)))
            except NotFoundError:
                out.append((ext["resourceClaimName"], None))
        return out

    def _reserve(self, claim, pod):
        ns = _meta(claim).get("namespace", "default")
        reserved = claim.get("status", {}).get("reservedFor") or []
        entry = {
            "resource": "pods",
            "name": _meta(pod)["name"],
            "uid": _meta(pod).get("uid", ""),
        }
        if entry not in reserved:
            self.kube.patch(
                *RESOURCE, "resourceclaims", _meta(claim)["name"],
                {"status": {"reservedFor": reserved + [entry]}},
                namespace=ns)

    def _extended_resource_classes(self) -> dict[str, str]:
        """extended resource name -> DeviceClass name, for classes
        advertising ``spec.extendedResourceName`` (KEP-5004)."""
        return {
            cls["spec"]["extendedResourceName"]: name
            for name, cls in self._device_classes().items()
            if cls.get("spec", {}).get("extendedResourceName")
        }

    def _pending_extended_resource(self, pod,
                                   names: set[str] | None) -> bool:
        """True while a pod requests a DRA-served extended resource but
        its auto-generated claim has not been recorded yet -- binding
        before that would run the pod deviceless. ``names`` is the
        advertised-resource set (None = the lookup failed this pass:
        fail CLOSED for any domain-prefixed limit and retry)."""
        if pod.get("status", {}).get("extendedResourceClaimStatus"):
            return False
        limits = [
            rname
            for c in pod.get("spec", {}).get("containers", [])
            for rname in ((c.get("resources") or {}).get("limits") or {})
        ]
        if names is None:
            return any("/" in rname for rname in limits)
        return any(rname in names for rname in limits)

    def _bind_pods(self):
        try:
            ext_names: set[str] | None = set(
                self._extended_resource_classes())
        except KubeError:
            ext_names = None  # fail closed per-pod, retry next pass
        for pod in self._pods():
            if pod.get("spec", {}).get("nodeName"):
                continue
            if pod.get("status", {}).get("phase") not in (
                    None, "", "Pending"):
                continue
            if self._pending_extended_resource(pod, ext_names):
                continue
            nodes = set()
            ready = True
            claim_objs = []
            for _, claim in self._claims_for_pod(pod):
                if claim is None:
                    ready = False
                    break
                alloc = claim.get("status", {}).get("allocation")
                if not alloc:
                    ready = False
                    break
                claim_objs.append(claim)
                for term in alloc.get("nodeSelector", {}).get(
                        "nodeSelectorTerms", []):
                    for mf in term.get("matchFields", []):
                        if mf.get("key") == "metadata.name":
                            nodes.add(mf["values"][0])
            if not ready:
                continue
            if len(nodes) > 1:
                # Claims allocated independently landed on different
                # nodes: binding anywhere would strand a device. The
                # real scheduler avoids this by filtering per-node
                # before allocating; surface it instead of mis-binding.
                logger.warning(
                    "pod %s/%s claims span nodes %s; not binding",
                    _meta(pod).get("namespace", "default"),
                    _meta(pod)["name"], sorted(nodes))
                continue
            node = next(iter(nodes)) if nodes else None
            if node is None:
                node = self.default_node
            if node is None:
                continue
            ns = _meta(pod).get("namespace", "default")
            for claim in claim_objs:
                self._reserve(claim, pod)
            self.kube.patch("", "v1", "pods", _meta(pod)["name"],
                            {"spec": {"nodeName": node}}, namespace=ns)
            logger.info("bound pod %s/%s -> %s", ns,
                        _meta(pod)["name"], node)

    # -- DaemonSet controller (kcm daemonset controller) ----------------------

    def _sync_daemonsets(self):
        """One pod per matching node per DaemonSet (the CD controller's
        per-domain DaemonSet needs this to materialize daemon pods on
        labeled nodes). Pod name is deterministic per (ds, node) so the
        pass is idempotent; pods on no-longer-matching nodes drain."""
        try:
            daemonsets = self.kube.list("apps", "v1", "daemonsets")
        except KubeError:
            return
        try:
            nodes = self.kube.list("", "v1", "nodes")
        except KubeError:
            nodes = []
        pods = self._pods()
        # GC pods whose owning DaemonSet is gone (kcm orphan deletion).
        live = {(_meta(d).get("namespace", "default"), _meta(d)["name"])
                for d in daemonsets}
        for pod in pods:
            ns = _meta(pod).get("namespace", "default")
            for o in _meta(pod).get("ownerReferences") or []:
                if o.get("kind") == "DaemonSet" and \
                        (ns, o.get("name")) not in live:
                    try:
                        self.kube.delete("", "v1", "pods",
                                         _meta(pod)["name"], namespace=ns)
                    except NotFoundError:
                        pass
        for ds in daemonsets:
            ns = _meta(ds).get("namespace", "default")
            ds_name = _meta(ds)["name"]
            tmpl = ds.get("spec", {}).get("template", {})
            selector = tmpl.get("spec", {}).get("nodeSelector") or {}
            want = {
                _meta(n)["name"] for n in nodes
                if all((_meta(n).get("labels") or {}).get(k) == v
                       for k, v in selector.items())
            }
            existing: dict[str, dict] = {}
            for pod in pods:
                if _meta(pod).get("namespace", "default") != ns:
                    continue
                if any(o.get("kind") == "DaemonSet"
                       and o.get("name") == ds_name
                       for o in _meta(pod).get("ownerReferences") or []):
                    existing[pod.get("spec", {}).get("nodeName", "")] = pod
            for node in sorted(want - set(existing)):
                pod = {
                    "apiVersion": "v1", "kind": "Pod",
                    "metadata": {
                        "name": f"{ds_name}-{node}",
                        "namespace": ns,
                        "labels": dict(tmpl.get("metadata", {}).get(
                            "labels") or {}),
                        "ownerReferences": [{
                            "apiVersion": "apps/v1", "kind": "DaemonSet",
                            "name": ds_name,
                            "uid": _meta(ds).get("uid", ""),
                            "controller": True,
                        }],
                    },
                    "spec": {**json_copy(tmpl.get("spec", {})),
                             "nodeName": node},
                }
                try:
                    self.kube.create("", "v1", "pods", pod, namespace=ns)
                    logger.info("daemonset %s/%s -> pod on %s", ns,
                                ds_name, node)
                except ConflictError:
                    pass
            for node in sorted(set(existing) - want):
                pod = existing[node]
                try:
                    self.kube.delete("", "v1", "pods",
                                     _meta(pod)["name"], namespace=ns)
                except NotFoundError:
                    pass

    # -- Job controller (kcm job controller, completions=1 subset) ------------

    def _sync_jobs(self):
        """One pod per Job (the demo specs' workloads are Jobs); pod
        phase feeds Job status (succeeded/failed + Complete)."""
        try:
            jobs = self.kube.list("batch", "v1", "jobs")
        except KubeError:
            return
        for job in jobs:
            ns = _meta(job).get("namespace", "default")
            name = _meta(job)["name"]
            pod_name = f"{name}-0"
            try:
                pod = self.kube.get("", "v1", "pods", pod_name,
                                    namespace=ns)
            except NotFoundError:
                status = job.get("status", {})
                if status.get("succeeded") or status.get("failed"):
                    continue  # finished Job: never re-run its pod
                tmpl = job.get("spec", {}).get("template", {})
                try:
                    self.kube.create("", "v1", "pods", {
                        "apiVersion": "v1", "kind": "Pod",
                        "metadata": {
                            "name": pod_name, "namespace": ns,
                            "labels": dict(tmpl.get("metadata", {}).get(
                                "labels") or {}),
                            "ownerReferences": [{
                                "apiVersion": "batch/v1", "kind": "Job",
                                "name": name,
                                "uid": _meta(job).get("uid", ""),
                                "controller": True,
                            }],
                        },
                        "spec": json_copy(tmpl.get("spec", {})),
                    }, namespace=ns)
                except ConflictError:
                    pass
                continue
            phase = pod.get("status", {}).get("phase", "")
            if phase == "Succeeded" and not job.get("status", {}).get(
                    "succeeded"):
                self.kube.patch("batch", "v1", "jobs", name, {
                    "status": {"succeeded": 1, "conditions": [
                        {"type": "Complete", "status": "True"}]},
                }, namespace=ns)
            elif phase == "Failed" and not job.get("status", {}).get(
                    "failed"):
                self.kube.patch("batch", "v1", "jobs", name, {
                    "status": {"failed": 1, "conditions": [
                        {"type": "Failed", "status": "True"}]},
                }, namespace=ns)

    # -- loop -----------------------------------------------------------------

    def sync_once(self):
        self._sync_daemonsets()
        self._sync_jobs()
        self._generate_claims()
        self._generate_extended_resource_claims()
        self._allocate_claims()
        self._bind_pods()

    def run(self, interval: float = 0.25):
        while not self._stop.is_set():
            try:
                self.sync_once()
            except Exception:  # noqa: BLE001 - control loop
                logger.exception("scheduler sync failed")
            self._stop.wait(interval)

    def start(self) -> "DraScheduler":
        self._thread = threading.Thread(
            target=self.run, name="dra-scheduler", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)


def main(argv: list[str] | None = None) -> int:
    import os

    from .kubeclient import KubeClient

    p = argparse.ArgumentParser(prog="tpu-dra-scheduler")
    p.add_argument("--kube-api", required=True)
    p.add_argument("--default-node", default=None)
    p.add_argument("--interval", type=float, default=0.25)
    p.add_argument("--metrics-port", type=int,
                   default=int(os.environ.get("METRICS_PORT", "0")),
                   help="serve /metrics (placement frag/compactness) "
                        "on this port; 0 = disabled [METRICS_PORT]")
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    metrics = None
    server = None
    if args.metrics_port:
        from .metrics import MetricsServer, PlacementMetrics

        metrics = PlacementMetrics()
        server = MetricsServer(metrics.registry, host="0.0.0.0",
                               port=args.metrics_port)
        server.start()
    from .retry import RetryingKubeClient  # noqa: PLC0415

    resilience = None
    if server is not None:
        from .metrics import ResilienceMetrics  # noqa: PLC0415

        resilience = ResilienceMetrics(registry=metrics.registry)
    sched = DraScheduler(RetryingKubeClient(KubeClient(host=args.kube_api),
                                            metrics=resilience),
                         default_node=args.default_node,
                         metrics=metrics)
    print("scheduler running", flush=True)
    try:
        sched.run(args.interval)
    except KeyboardInterrupt:
        pass
    finally:
        if server is not None:
            server.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
