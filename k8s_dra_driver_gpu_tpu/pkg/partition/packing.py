"""ParvaGPU-style demand-matched spatial packing.

ParvaGPU (2409.14447) meets large-scale DNN-inference SLOs by choosing
per-tenant GPU "spatial shares" and then CO-LOCATING complementary
tenants so chips run full instead of fragmenting. The TPU translation
packs sized tenants (per-tenant HBM budgets from
pkg/partition/profiles.SizingPolicy) onto chips with
best-fit-decreasing: large tenants seed chips, small complementary
tenants top them off, and the plan reports the waste the layout leaves
so the planner can compare candidate partition sets.

Deterministic on purpose: the same demands always produce the same
plan (bench gates and tests replay it).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .spec import PartitionDemand


@dataclass
class ChipPlan:
    """Tenants co-located on one chip."""

    index: int
    capacity_hbm: int
    used_hbm: int = 0
    tenants: list[PartitionDemand] = field(default_factory=list)

    @property
    def free_hbm(self) -> int:
        return self.capacity_hbm - self.used_hbm


@dataclass
class PackingPlan:
    chips: list[ChipPlan]
    unplaced: list[PartitionDemand]

    @property
    def chips_used(self) -> int:
        return sum(1 for c in self.chips if c.tenants)

    @property
    def tenants_placed(self) -> int:
        return sum(len(c.tenants) for c in self.chips)

    @property
    def tenants_per_chip(self) -> float:
        used = self.chips_used
        return self.tenants_placed / used if used else 0.0

    @property
    def waste_fraction(self) -> float:
        """Unused HBM across the chips the plan touched (the ParvaGPU
        objective: lower = tighter co-location)."""
        cap = sum(c.capacity_hbm for c in self.chips if c.tenants)
        if not cap:
            return 0.0
        used = sum(c.used_hbm for c in self.chips if c.tenants)
        return 1.0 - used / cap

    def to_dict(self) -> dict:
        return {
            "chipsUsed": self.chips_used,
            "tenantsPlaced": self.tenants_placed,
            "tenantsPerChip": round(self.tenants_per_chip, 2),
            "wasteFraction": round(self.waste_fraction, 4),
            "unplaced": len(self.unplaced),
        }


def pack_tenants(demands: list[PartitionDemand], chip_hbm: int,
                 chips: int, max_tenants_per_chip: int | None = None,
                 avoid: set[int] | None = None) -> PackingPlan:
    """Best-fit-decreasing co-location of tenants onto ``chips`` chips
    of ``chip_hbm`` HBM each.

    Tenants sort by HBM demand descending (ties broken by tenant key
    for determinism); each picks the chip whose remaining HBM fits it
    TIGHTEST -- which is exactly what pairs a large tenant with the
    complementary small ones instead of spreading smalls across fresh
    chips. ``max_tenants_per_chip`` caps co-tenancy (the cooperative
    time-slice client bound); None = HBM-bound only.

    ``avoid`` names chip indices in an active telemetry anomaly
    episode (power-cap throttling, duty-cycle straggling, thermal
    drift -- pkg/anomaly.py): a tenant packs onto one ONLY when no
    clean chip fits it. Pure preference -- a degraded chip still
    carries load before a tenant goes unplaced."""
    expanded: list[PartitionDemand] = []
    for d in demands:
        for _ in range(max(d.count, 0)):
            expanded.append(PartitionDemand(
                hbm_bytes=d.hbm_bytes, cores=d.cores, count=1,
                tenant=d.tenant))
    expanded.sort(key=lambda d: (-d.hbm_bytes, d.tenant))
    plan = PackingPlan(
        chips=[ChipPlan(index=i, capacity_hbm=chip_hbm)
               for i in range(chips)],
        unplaced=[],
    )
    avoid = avoid or set()
    for demand in expanded:
        best: ChipPlan | None = None
        best_avoided = True
        for chip in plan.chips:
            if chip.free_hbm < demand.hbm_bytes:
                continue
            if max_tenants_per_chip is not None and \
                    len(chip.tenants) >= max_tenants_per_chip:
                continue
            avoided = chip.index in avoid
            # A clean chip always out-ranks an avoided one; within a
            # tier the historical tightest-fit rule decides.
            if best is None or (best_avoided and not avoided) or (
                    best_avoided == avoided
                    and (chip.free_hbm < best.free_hbm
                         or (chip.free_hbm == best.free_hbm
                             and chip.index < best.index))):
                best = chip
                best_avoided = avoided
        if best is None:
            plan.unplaced.append(demand)
            continue
        best.tenants.append(demand)
        best.used_hbm += demand.hbm_bytes
    return plan
