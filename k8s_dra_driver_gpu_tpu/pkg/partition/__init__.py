"""Multi-tenant partition engine (the dynamic MIG/MPS analog).

Layers:

- ``spec``: PartitionSet / PartitionProfile -- the declarative partition
  layout (operator file or planner output).
- ``profiles``: MISO-grounded tenant-profile store + sizing policy
  (observed demand percentiles -> smallest satisfying profile).
- ``packing``: ParvaGPU-style best-fit-decreasing tenant co-location.
- ``engine``: node-side dynamic carve-out lifecycle (crash-safe via the
  ``partition`` TransitionPolicy) + the publishable device projection.

See docs/architecture.md "Partition engine" and docs/operations.md
"Partitioning & serving runbook".
"""

from .packing import PackingPlan, pack_tenants
from .profiles import (
    DEFAULT_TENANT_DEMANDS,
    TENANT_PROFILE_ANNOTATION,
    SizingPolicy,
    TenantProfileStore,
)
from .spec import (
    PartitionDemand,
    PartitionProfile,
    PartitionSet,
    PartitionSpecError,
    parse_partition_device_name,
    partition_device_name,
)

__all__ = [
    "DEFAULT_TENANT_DEMANDS",
    "TENANT_PROFILE_ANNOTATION",
    "PackingPlan",
    "PartitionDemand",
    "PartitionProfile",
    "PartitionSet",
    "PartitionSpecError",
    "SizingPolicy",
    "TenantProfileStore",
    "pack_tenants",
    "parse_partition_device_name",
    "partition_device_name",
]
