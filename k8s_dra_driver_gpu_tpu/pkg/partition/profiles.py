"""Tenant-profile store + profile-guided partition sizing (MISO).

MISO (2207.11428) sizes MIG partitions by PROFILING each tenant's
resource demand and then choosing the smallest partition that satisfies
it, instead of letting users guess. The TPU translation:

- :class:`TenantProfileStore` records observed HBM/core demand per
  TENANT KEY -- a DeviceClass name or the value of the claim annotation
  ``resource.tpu.dra/tenant-profile`` -- and answers percentile
  queries. It seeds from a static profile file (the operator's prior)
  and from bench-measured defaults (:data:`DEFAULT_TENANT_DEMANDS`,
  numbers measured by the in-repo model stack on v5e-class HBM
  footprints), so sizing works before any live observation exists.
- :class:`SizingPolicy` picks the SMALLEST profile in a
  :class:`~.spec.PartitionSet` catalog whose per-tenant budget covers
  the demand percentile (HBM first -- the binding constraint for
  inference serving -- then cores).

The store is node- and scheduler-side shareable: it is pure state with
a JSON file form, no kube or device dependencies.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass

from .. import positive_float_env
from .spec import PartitionDemand, PartitionProfile, PartitionSpecError


def _default_window_s() -> float:
    """The demand sliding window (``TPU_DRA_PROFILE_WINDOW_S``, default
    3600s): percentile reads consider only samples this recent, so a
    traffic burst that has since decayed stops inflating the sized
    profile once its samples age out. 0 disables aging (all-history,
    the pre-window behavior)."""
    return positive_float_env("TPU_DRA_PROFILE_WINDOW_S",
                              default=3600.0, floor=0.0)

#: Claim annotation naming the tenant profile a claim belongs to.
TENANT_PROFILE_ANNOTATION = "resource.tpu.dra/tenant-profile"

#: Bench-measured per-tenant working sets (HBM bytes, cores) for the
#: in-repo serving stack: decode-only llama-class serving at small
#: batch fits comfortably in a fraction of a chip's HBM. These are the
#: cold-start priors; live observations supersede them.
DEFAULT_TENANT_DEMANDS: dict[str, PartitionDemand] = {
    "serving-small": PartitionDemand(hbm_bytes=2 << 30, cores=1,
                                     tenant="serving-small"),
    "serving-medium": PartitionDemand(hbm_bytes=6 << 30, cores=1,
                                      tenant="serving-medium"),
    "serving-large": PartitionDemand(hbm_bytes=12 << 30, cores=1,
                                     tenant="serving-large"),
}

_MAX_SAMPLES = 4096  # per tenant key; serving fleets churn constantly


class TenantProfileStore:
    """Observed demand samples per tenant key, with percentile reads.

    Thread-safe: the node plugin's prepare path and the planner read/
    write concurrently."""

    def __init__(self, defaults: dict[str, PartitionDemand] | None = None,
                 window_s: float | None = None):
        self._lock = threading.Lock()
        # tenant key -> (ts, HBM bytes) samples in ARRIVAL order (a
        # bounded count-limited buffer ALSO aged by the time window
        # below) + core demand.
        self._hbm: dict[str, list[tuple[float, int]]] = {}
        self._cores: dict[str, int] = {}
        # Sliding TIME window for percentile reads: samples older than
        # this never count (but the single freshest sample survives as
        # the last-known-demand fallback -- see demand()). None = env
        # default; 0 = all-history.
        self.window_s = (_default_window_s() if window_s is None
                         else max(float(window_s), 0.0))
        defaults = (DEFAULT_TENANT_DEMANDS if defaults is None
                    else defaults)
        now = time.time()
        for key, demand in defaults.items():
            self._hbm[key] = [(now, demand.hbm_bytes)]
            self._cores[key] = demand.cores

    def observe(self, tenant: str, hbm_bytes: int, cores: int = 1,
                now: float | None = None) -> None:
        """Fold one observed demand sample into the tenant's bounded
        sliding window. Eviction is by ARRIVAL (count bound) and by AGE
        (``window_s``), not by magnitude: a tenant whose working set
        shrinks must see its percentiles come down once the old large
        samples age out of the window. ``now`` is a test seam."""
        if not tenant or hbm_bytes < 0:
            return
        ts = time.time() if now is None else float(now)
        with self._lock:
            samples = self._hbm.setdefault(tenant, [])
            samples.append((ts, hbm_bytes))
            if len(samples) > _MAX_SAMPLES:
                samples.pop(0)
            self._cores[tenant] = max(self._cores.get(tenant, 1), cores)

    def _windowed(self, samples: list[tuple[float, int]],
                  now: float) -> list[int]:
        """Samples inside the time window, falling back to the single
        freshest sample when everything aged out: a tenant that WAS
        observed keeps its last known demand (better than falling back
        to a whole-chip claim), it just stops compounding stale
        history into the percentile."""
        if not samples:
            return []
        if self.window_s <= 0:
            return [v for _, v in samples]
        cutoff = now - self.window_s
        live = [v for ts, v in samples if ts >= cutoff]
        return live if live else [samples[-1][1]]

    def record(self, tenant: str, hbm_bytes: int, cores: int = 1) -> None:
        """Live-telemetry ingest (the kubelet plugin's health-poll
        loop feeds tpulib per-tenant usage samples here -- see
        kubeletplugin/health.ChipHealthMonitor.sample_telemetry). Same
        sliding-window semantics as :meth:`observe`; the separate name
        marks the producer: ``record`` is measured usage, ``observe``
        is declared/derived demand."""
        self.observe(tenant, hbm_bytes, cores=cores)

    def demand(self, tenant: str, percentile: float = 0.95,
               now: float | None = None) -> PartitionDemand | None:
        """The demand percentile for one tenant key over the sliding
        time window, or None when the key has never been observed (and
        has no default)."""
        ts = time.time() if now is None else float(now)
        with self._lock:
            windowed = self._windowed(self._hbm.get(tenant, []), ts)
            if not windowed:
                return None
            ordered = sorted(windowed)
            idx = min(len(ordered) - 1,
                      max(0, int(percentile * len(ordered) + 0.5) - 1))
            # count stays 1 (one tenant's demand): pack_tenants reads
            # it as tenant multiplicity, not as the sample size.
            return PartitionDemand(
                hbm_bytes=ordered[idx],
                cores=self._cores.get(tenant, 1),
                tenant=tenant,
            )

    def tenants(self) -> list[str]:
        with self._lock:
            return sorted(self._hbm)

    def fresh_tenants(self, now: float | None = None) -> list[str]:
        """Tenant keys with at least one sample STRICTLY inside the
        time window (no last-sample fallback): the autoscale planner's
        retention signal -- a tenant with neither fresh samples nor
        live claims has genuinely left and its profiles may retire."""
        ts = time.time() if now is None else float(now)
        with self._lock:
            if self.window_s <= 0:
                return sorted(k for k, s in self._hbm.items() if s)
            cutoff = ts - self.window_s
            return sorted(
                key for key, samples in self._hbm.items()
                if samples and samples[-1][0] >= cutoff)

    # -- static profile file --------------------------------------------------

    def load_file(self, path: str) -> int:
        """Merge a static profile file: ``{"tenants": {key:
        {"hbmBytes": N, "cores": M}}}``. Returns entries loaded."""
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise PartitionSpecError(
                f"unreadable tenant profile file {path!r}: {e}"
            ) from e
        tenants = doc.get("tenants") or {}
        for key, entry in tenants.items():
            self.observe(key, int(entry.get("hbmBytes", 0)),
                         cores=int(entry.get("cores", 1)))
        return len(tenants)

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "tenants": {
                    key: {"hbmBytes": max(v for _, v in samples),
                          "cores": self._cores.get(key, 1),
                          "samples": len(samples)}
                    for key, samples in self._hbm.items()
                    if samples
                }
            }

    def percentiles(self, percentiles: tuple[float, ...] = (0.5, 0.95),
                    now: float | None = None) -> dict[str, dict]:
        """Per-tenant demand percentiles over the sliding window (the
        ``/debug/fleet`` operator surface: what the autoscale planner
        sees). ``{tenant: {"p50_hbm_bytes": N, "p95_hbm_bytes": N,
        "cores": M, "samples": K}}``."""
        ts = time.time() if now is None else float(now)
        out: dict[str, dict] = {}
        with self._lock:
            for key, samples in self._hbm.items():
                windowed = sorted(self._windowed(samples, ts))
                if not windowed:
                    continue
                entry: dict = {"samples": len(windowed),
                               "cores": self._cores.get(key, 1)}
                for pct in percentiles:
                    idx = min(len(windowed) - 1,
                              max(0, int(pct * len(windowed) + 0.5) - 1))
                    entry[f"p{int(pct * 100)}_hbm_bytes"] = windowed[idx]
                out[key] = entry
        return out


@dataclass(frozen=True)
class SizedChoice:
    """One sizing decision: the chosen profile + the budget it grants
    (per-tenant HBM bytes, and the per-core TIME share in milli --
    PartitionInfo.tenant_core_milli, the virtual-capacity
    multiplier)."""

    profile: PartitionProfile
    per_tenant_hbm: int
    per_tenant_core_milli: int


class SizingPolicy:
    """MISO's choose step: the smallest catalog profile whose
    PER-TENANT budget satisfies the demand percentile.

    "Smallest" orders by per-tenant HBM first (the serving-workload
    binding constraint), then by per-tenant core share -- so a demand
    of 1.8Gi on a 16Gi chip picks the 8-slot/2Gi profile, not the
    4-slot/4Gi one, and the fleet packs 8 tenants per chip instead
    of 4."""

    def __init__(self, percentile: float = 0.95):
        self.percentile = percentile

    def pick(self, demand: PartitionDemand,
             catalog: list
             ) -> SizedChoice | None:
        """``catalog``: (profile, resolved PartitionInfo) pairs -- the
        caller resolves subslice shapes against the actual host
        (pkg/partition/engine.catalog_for). Budgets are read off the
        PartitionInfo the publisher budgets counters from
        (tenant_hbm_bytes / tenant_core_milli), so the policy can
        never admit a tenant past the published per-slot capacity.
        Returns the smallest satisfying choice, or None when nothing in
        the catalog covers the demand (the tenant needs a whole chip /
        sub-slice claim instead).

        Core coverage is PHYSICAL SPAN, not temporal share: a tenant
        demanding N cores needs a backing carve-out spanning >= N
        cores (its parallelism cannot fold onto fewer), while the
        per-core milli share only divides TIME on those cores -- that
        is what oversubscription means."""
        best: SizedChoice | None = None
        for profile, info in catalog:
            per_hbm = info.tenant_hbm_bytes
            per_core_milli = info.tenant_core_milli
            if per_hbm < demand.hbm_bytes:
                continue
            if info.cores < max(demand.cores, 1):
                continue
            if per_core_milli < 1:
                continue
            choice = SizedChoice(profile, per_hbm, per_core_milli)
            if best is None or (choice.per_tenant_hbm,
                                choice.per_tenant_core_milli) < (
                    best.per_tenant_hbm, best.per_tenant_core_milli):
                best = choice
        return best
