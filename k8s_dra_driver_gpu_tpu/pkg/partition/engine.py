"""The node-side partition engine: dynamic carve-out lifecycle.

The reference driver's dynamically-creatable MIG path creates a GPU
instance at Prepare and destroys it at Unprepare
(device_state.go:229-334). This engine generalizes that for the
multi-tenant serving workload:

- A :class:`~.spec.PartitionSet` declares the desired partition
  profiles; :func:`partition_devices` projects them onto this host's
  sub-slice placements as first-class partition devices (published in
  the node's partitions ResourceSlice with KEP-4815 counter budgets
  against the parent chips -- see kubeletplugin/partitions.py).
- The BACKING CARVE-OUT of a partition is realized lazily at
  NodePrepare time (first tenant attach) and torn back down when the
  last tenant detaches, so an idle pool returns to whole-chip
  allocatability without operator action.
- Every create/destroy is driven through a durable record in a
  dedicated CheckpointManager under the ``partition`` TransitionPolicy
  (pkg/analysis/statemachine.py): absent -> PartitionCreating ->
  PartitionReady -> PartitionDestroying -> absent. A crash at ANY
  point (fault seams ``partition.create`` / ``partition.destroy``)
  resumes idempotently: a Creating record with live tenants completes
  its create, an orphaned Creating/Destroying record finishes its
  teardown, and the carve-out uuid is pinned in the record so a
  half-created carve-out is found again instead of leaked.

Holder counting is DERIVED, not stored: the tenants of a partition are
exactly the node checkpoint's claims referencing the partition device,
so the engine's records never duplicate (and can never disagree with)
the claim state machine.

Carve-out create/destroy lives ONLY here and in
kubeletplugin/device_state.py -- lint rule TPUDRA011 enforces it.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import uuid as uuidlib
from dataclasses import dataclass

from ...kubeletplugin.checkpoint import (
    CheckpointedClaim,
    CheckpointedDevice,
    CheckpointManager,
)
from ...kubeletplugin.deviceinfo import (
    AllocatableDevice,
    DeviceKind,
    PartitionInfo,
)
from ...kubeletplugin.subslice import (
    SubSliceLiveTuple,
    SubSliceSpecTuple,
    enumerate_subslice_devices,
)
from ..analysis.statemachine import (
    PARTITION_CREATING,
    PARTITION_DESTROYING,
    PARTITION_POLICY,
    PARTITION_READY,
)
from .. import flightrecorder, positive_float_env, tracing
from ..faults import fault_point
from .spec import (
    PartitionProfile,
    PartitionSet,
    PartitionSpecError,
    parse_partition_device_name,
)

logger = logging.getLogger(__name__)

def prewarm_max() -> int:
    """Upper bound on carve-outs kept warm ahead of demand per node
    (``TPU_DRA_PREWARM_MAX``; pkg/autoscale's forecaster hint drives
    set_prewarm); 0 disables pre-warming entirely -- every attach
    pays the lazy create. Read live per application (the controller
    reads the same env live per pass -- the two halves of the feature
    must never disagree across an operator flip)."""
    return int(positive_float_env(
        "TPU_DRA_PREWARM_MAX", default=8, floor=0))


class PartitionEngineError(RuntimeError):
    """A partition attach/detach that cannot proceed (retriable at the
    claim level: the kubelet re-drives Prepare)."""


@dataclass(frozen=True)
class ResolvedProfile:
    """A PartitionProfile resolved against this host's carve-out
    placements."""

    profile: PartitionProfile
    infos: tuple[PartitionInfo, ...]


def resolve_partition_set(host, tpu_profiles, partition_set: PartitionSet,
                          pool: str | None = None
                          ) -> list[ResolvedProfile]:
    """Project a PartitionSet onto one host. Raises PartitionSpecError
    when a profile names a backing sub-slice this host cannot carve
    (config error -- fail loudly, like a bad static_subslices name)."""
    if pool is not None and not partition_set.applies_to_pool(pool):
        return []
    by_name = {p.name: p for p in tpu_profiles}
    out: list[ResolvedProfile] = []
    for prof in partition_set.profiles:
        base = by_name.get(prof.subslice)
        if base is None:
            raise PartitionSpecError(
                f"partition profile {prof.name!r}: backing sub-slice "
                f"{prof.subslice!r} is not a valid carve-out for this "
                f"host ({host.accelerator_type or 'unknown'})"
            )
        specs = enumerate_subslice_devices(host, (base,))
        infos = tuple(
            PartitionInfo(profile=prof, spec=spec, host=host, placement=k)
            for k, spec in enumerate(specs)
        )
        out.append(ResolvedProfile(profile=prof, infos=infos))
    return out


def partition_devices(host, tpu_profiles, partition_set: PartitionSet,
                      pool: str | None = None
                      ) -> dict[str, AllocatableDevice]:
    """name -> AllocatableDevice for every desired partition on this
    host (the publishable projection; shared by the engine and the
    serving bench's fleet simulation)."""
    out: dict[str, AllocatableDevice] = {}
    for rp in resolve_partition_set(host, tpu_profiles, partition_set,
                                    pool=pool):
        for info in rp.infos:
            out[info.canonical_name] = AllocatableDevice(
                kind=DeviceKind.PARTITION, partition=info
            )
    return out


def catalog_for(host, tpu_profiles, partition_set: PartitionSet
                ) -> list[tuple[PartitionProfile, object]]:
    """(profile, resolved PartitionInfo) pairs -- the SizingPolicy
    input (pkg/partition/profiles.py). Handing the policy the SAME
    PartitionInfo the publisher budgets from keeps sizing and the
    published per-slot capacity in lock-step (no re-derived formula
    to drift)."""
    out = []
    for rp in resolve_partition_set(host, tpu_profiles, partition_set):
        if not rp.infos:
            continue
        out.append((rp.profile, rp.infos[0]))
    return out


class PartitionEngine:
    """Per-node dynamic partition lifecycle, attached to a DeviceState.

    Thread model: attach/detach run under the owning claim's chip shard
    locks (device_state.prepare/unprepare); the engine adds a per-
    partition-device lock so resume()/apply()/reap_idle() -- which run
    without shard locks -- serialize against them. Lock order is
    shard locks -> partition device lock -> checkpoint/registry flocks;
    nothing inside a device lock ever takes a shard lock back.
    """

    def __init__(self, state, partition_set: PartitionSet,
                 pool: str | None = None, metrics=None):
        self._state = state
        self.metrics = metrics
        self.partition_set = partition_set
        self._pool = pool
        root = os.path.join(state.config_root, "partition")
        self._checkpoint = CheckpointManager(
            root, boot_id=state.boot_id,
            transition_policy=PARTITION_POLICY)
        self._mutex = threading.Lock()
        self._dev_locks: dict[str, threading.Lock] = {}
        self._devices: dict[str, AllocatableDevice] = {}
        # Predictive pre-warming (set_prewarm): names that SHOULD stay
        # warm per the current forecast hint (reap_idle leaves their
        # zero-holder records alone), and the subset this engine
        # created ahead of demand that no tenant has attached yet (the
        # hit/reaped metric bookkeeping). In-memory on purpose: a
        # restart settles records via resume() and the CRD watcher
        # re-applies the hint right after.
        self._prewarm_desired: set[str] = set()
        self._prewarm_idle: set[str] = set()
        self._rebuild_devices()

    # -- desired devices ------------------------------------------------------

    def _project_devices(self, partition_set: PartitionSet
                         ) -> dict[str, AllocatableDevice]:
        host = self._state.host
        expected = min(host.num_slice_chips, host.chips_per_host)
        if len(host.chips) < expected:
            # Same rule as the raw sub-slice path: a degraded host's
            # placement grid cannot be trusted against a hole.
            logger.warning(
                "degraded host (%d/%d chips): not publishing partition "
                "devices", len(host.chips), expected,
            )
            return {}
        return partition_devices(
            host, self._state.subslice_profiles, partition_set,
            pool=self._pool)

    def _rebuild_devices(self) -> None:
        self._devices = self._project_devices(self.partition_set)

    def devices(self) -> dict[str, AllocatableDevice]:
        """The desired (publishable) partition device set."""
        with self._mutex:
            return dict(self._devices)

    def apply(self, partition_set: PartitionSet
              ) -> dict[str, AllocatableDevice]:
        """Swap in a new PartitionSet (profile-guided re-plan): the
        desired device set is recomputed, partitions no longer desired
        are reaped once idle, and the caller republishes. Returns the
        new device set.

        A re-plan that keeps a profile NAME but changes its backing
        sub-slice would silently re-shape a device whose old carve-out
        is still pinned by live tenants (overlap validation and the
        container edits would read the new shape while the workload
        runs on the old one) -- that is rejected loudly; drain the
        tenants or retire the profile name instead. Held-with-old-shape
        but idle records are settled by the reap below before any new
        attach can reuse them."""
        partition_set.validate()
        new_devices = self._project_devices(partition_set)
        # Validate-and-swap holds every affected device's lifecycle
        # lock: a concurrent attach either pinned its record before we
        # look (seen by the loop below -> rejected loudly) or blocks
        # here and re-reads the swapped-in spec (attach reads _devices
        # under the device lock). Sorted acquisition; every other
        # taker holds at most one device lock, so this cannot deadlock.
        with self._mutex:
            current = set(self._devices)
        names = sorted(current | set(new_devices)
                       | set(self._checkpoint.get().claims))
        with contextlib.ExitStack() as stack:
            for name in names:
                stack.enter_context(self._dev_lock(name))
            for name, rec in self._checkpoint.get().claims.items():
                dev = new_devices.get(name)
                pinned = self._pinned_spec(rec)
                if dev is None or dev.partition is None or pinned is None:
                    continue
                want = dev.partition.spec.canonical_name()
                if pinned != want and self._holders(name) > 0:
                    raise PartitionSpecError(
                        f"re-plan changes the backing carve-out of "
                        f"{name!r} ({pinned} -> {want}) while tenants "
                        "still hold it; drain the tenants or retire the "
                        "profile name instead"
                    )
            with self._mutex:
                self.partition_set = partition_set
                self._rebuild_devices()
                devices = dict(self._devices)
        self.reap_idle()
        return devices

    # -- lifecycle ------------------------------------------------------------

    def _dev_lock(self, name: str) -> threading.Lock:
        with self._mutex:
            lock = self._dev_locks.get(name)
            if lock is None:
                lock = self._dev_locks[name] = threading.Lock()
            return lock

    def _record(self, name: str) -> CheckpointedClaim | None:
        return self._checkpoint.get().claims.get(name)

    @staticmethod
    def _pinned_spec(rec: CheckpointedClaim) -> str | None:
        """The backing sub-slice canonical name pinned in a lifecycle
        record at create time (None on records from before the spec
        was pinned)."""
        if rec.devices and rec.devices[0].live:
            return rec.devices[0].live.get("spec")
        return None

    def _holders(self, name: str, exclude: set[str] = frozenset()
                 ) -> int:
        """Tenant claims currently holding this partition device,
        derived from the node checkpoint (reservations count: an
        in-flight prepare's tenant must pin the carve-out)."""
        count = 0
        for uid, claim in self._state.prepared_claims().items():
            if uid in exclude:
                continue
            if any(dev.canonical_name == name for dev in claim.devices):
                count += 1
        return count

    def live_uuids(self) -> set[str]:
        """Carve-out uuids owned by partition records in ANY state --
        the unknown-state sweep must never eat a partition mid-
        lifecycle."""
        return {
            dev.live["uuid"]
            for rec in self._checkpoint.get().claims.values()
            for dev in rec.devices
            if dev.live and "uuid" in dev.live
        }

    def recorded_devices(self) -> set[str]:
        """Partition device names with a lifecycle record in ANY state
        -- the set whose backing carve-outs (and tenant claims) still
        exist. A re-plan must keep these visible to overlap validation
        and the counter model until their last tenant detaches."""
        return set(self._checkpoint.get().claims)

    def active_partitions(self) -> int:
        return sum(
            1 for rec in self._checkpoint.get().claims.values()
            if rec.state == PARTITION_READY
        )

    def attach(self, claim_uid: str, device_name: str) -> dict:
        """Ensure the backing carve-out of ``device_name`` exists and
        return its live identity for the claim's checkpoint record.
        Idempotent and crash-resumable: the carve-out uuid is pinned in
        the PartitionCreating record BEFORE the carve-out is realized,
        so a crash in between resumes onto the same identity."""
        # Child of the prepare pipeline's prep_attach_partition segment
        # span (same thread), which itself chains to the scheduler's
        # commit span via the claim's traceparent annotation.
        with tracing.span("partition.attach", attrs={
                "device": device_name, "claim_uid": claim_uid}) as sp:
            live = self._attach_inner(claim_uid, device_name)
            flightrecorder.default().record(
                claim_uid, "partition_attach",
                trace_id=(sp.context.trace_id if sp.recording else ""),
                device=device_name, uuid=live.get("uuid", ""))
            return live

    def _attach_inner(self, claim_uid: str, device_name: str) -> dict:
        with self._dev_lock(device_name):
            # Spec read under the device lock (dev-lock -> mutex, the
            # resume() order): apply() holds this lock across a
            # re-plan's validate+swap, so the spec pinned below can
            # never be concurrently invalidated by a re-shape.
            with self._mutex:
                dev = self._devices.get(device_name)
            if dev is None or dev.partition is None:
                raise PartitionEngineError(
                    f"unknown partition device {device_name!r}"
                )
            rec = self._record(device_name)
            if rec is not None and rec.state == PARTITION_DESTROYING:
                # A crashed teardown owns the old carve-out; finish it
                # before creating fresh (never share a dying identity).
                self._teardown_locked(device_name, rec)
                rec = None
            if rec is not None:
                pinned = self._pinned_spec(rec)
                want = dev.partition.spec.canonical_name()
                if pinned is not None and pinned != want:
                    # A re-plan re-shaped this device while the old
                    # carve-out still exists: never hand a tenant the
                    # old identity under the new contract. Retriable --
                    # once the old record settles (last detach /
                    # reap_idle) the next attach creates fresh.
                    raise PartitionEngineError(
                        f"partition {device_name!r} backing carve-out "
                        f"changed ({pinned} -> {want}); old carve-out "
                        "still settling"
                    )
            # Pre-warm hit accounting: an attach that finds a READY
            # record this engine realized ahead of demand just skipped
            # the partition.create fsyncs on its claim path.
            warm_hit = (rec is not None
                        and rec.state == PARTITION_READY)
            live = self._realize_locked(device_name, dev, rec)
            if warm_hit:
                with self._mutex:
                    warm_hit = device_name in self._prewarm_idle
                    self._prewarm_idle.discard(device_name)
                if warm_hit and self.metrics is not None:
                    self.metrics.inc_prewarm_hit()
            return live

    def _realize_locked(self, device_name: str, dev,
                        rec: CheckpointedClaim | None) -> dict:
        """Create-or-complete the backing carve-out (caller holds the
        device lock and has settled any Destroying/re-shaped record).
        Shared by the attach path and set_prewarm, so a pre-warmed and
        a lazily-created carve-out are byte-identical in lifecycle."""
        if rec is None:
            live = {"uuid": f"tpu-pt-{uuidlib.uuid4()}",
                    "partition": device_name,
                    "spec": dev.partition.spec.canonical_name()}
            rec = CheckpointedClaim(
                uid=device_name,
                state=PARTITION_CREATING,
                devices=[CheckpointedDevice(
                    canonical_name=device_name,
                    kind=DeviceKind.PARTITION.value,
                    live=live,
                )],
            )
            self._checkpoint.update_claim(device_name, rec)
        live = rec.devices[0].live
        if rec.state == PARTITION_CREATING:
            fault_point("partition.create",
                        error=lambda m: PartitionEngineError(m))
            if live["uuid"] not in self._state.subslice_registry.list():
                self._state.subslice_registry.create(SubSliceLiveTuple(
                    spec=dev.partition.spec, uuid=live["uuid"]))
            ready = CheckpointedClaim(
                uid=device_name, state=PARTITION_READY,
                devices=rec.devices)
            self._checkpoint.update_claim(device_name, ready)
            if self.metrics is not None:
                self.metrics.inc_create()
                self.metrics.set_active(self.active_partitions())
            logger.info("partition %s: carve-out %s created",
                        device_name, live["uuid"])
        return dict(live)

    def detach(self, claim_uid: str, device_name: str) -> None:
        """Drop one tenant's hold; the backing carve-out is destroyed
        when the LAST holder detaches (idle partitions return their
        chips to whole-chip allocatability) -- UNLESS the current
        pre-warm hint wants this device warm: then the Ready record
        simply returns to the warm-unattached set, so a standing
        forecast survives attach/detach churn instead of depleting
        (the next burst's first attach is a hit again, no re-create
        needed)."""
        with tracing.span("partition.detach", attrs={
                "device": device_name, "claim_uid": claim_uid}) as sp:
            kept_warm = False
            with self._dev_lock(device_name):
                rec = self._record(device_name)
                if rec is None:
                    return
                last = self._holders(device_name,
                                     exclude={claim_uid}) == 0
                if last:
                    with self._mutex:
                        kept_warm = (device_name in
                                     self._prewarm_desired
                                     and rec.state == PARTITION_READY)
                        if kept_warm:
                            self._prewarm_idle.add(device_name)
                    if not kept_warm:
                        self._teardown_locked(device_name, rec)
            flightrecorder.default().record(
                claim_uid, "partition_detach",
                trace_id=(sp.context.trace_id if sp.recording else ""),
                device=device_name, destroyed=last and not kept_warm,
                kept_warm=kept_warm)

    def _teardown_locked(self, name: str,
                         rec: CheckpointedClaim) -> None:
        """Durable-intent destroy: record PartitionDestroying first, so
        a crash mid-destroy resumes instead of leaking the carve-out.
        Caller holds the device lock."""
        if rec.state != PARTITION_DESTROYING:
            self._checkpoint.update_claim(name, CheckpointedClaim(
                uid=name, state=PARTITION_DESTROYING,
                devices=rec.devices))
        fault_point("partition.destroy",
                    error=lambda m: PartitionEngineError(m))
        for dev in rec.devices:
            if dev.live and "uuid" in dev.live:
                self._state.subslice_registry.destroy(dev.live["uuid"])
        self._checkpoint.update_claim(name, None)
        if self.metrics is not None:
            self.metrics.inc_destroy()
            self.metrics.set_active(self.active_partitions())
        logger.info("partition %s: carve-out destroyed", name)

    # -- predictive pre-warming (pkg/autoscale forecaster hint) ---------------

    def set_prewarm(self, counts: dict[str, int],
                    max_total: int | None = None) -> int:
        """Converge the warm set onto a forecast hint
        (``{profile name: devices to keep warm}``): realize carve-outs
        for up to that many record-less devices per profile, bounded
        by ``max_total`` (``TPU_DRA_PREWARM_MAX``), and release names
        the hint no longer wants so the EXISTING idle sweep
        (reap_idle) returns their chips. Devices already holding a
        record in any state count toward their profile's quota -- a
        held or already-warm partition is warm capacity, not a reason
        to carve more. Returns the number of carve-outs created;
        raises PartitionEngineError when a desired carve-out could
        not be realized (the partial warm set stays applied -- the
        raise tells the CRD watcher not to memoize the hint as
        converged, so the next reconcile retries the shortfall).

        Mutation fencing (lint rule TPUDRA015): only the node driver's
        CRD-watch path may call this -- a random call site would fork
        the warm set from the forecast hint."""
        cap = prewarm_max() if max_total is None \
            else max(int(max_total), 0)
        want: dict[str, int] = {
            str(p): int(n) for p, n in (counts or {}).items()
            if int(n) > 0}
        recorded = self._checkpoint.get().claims
        desired: set[str] = set()
        to_create: list[tuple[str, AllocatableDevice]] = []
        budget = cap
        with self._mutex:
            devices = dict(self._devices)
        by_profile: dict[str, list[str]] = {}
        for name in sorted(devices):
            parsed = parse_partition_device_name(name)
            if parsed is not None:
                by_profile.setdefault(parsed[0], []).append(name)
        for profile, quota in sorted(want.items()):
            names = by_profile.get(profile, ())
            kept = 0
            for name in names:
                if kept >= quota or budget <= 0:
                    break
                kept += 1
                budget -= 1
                desired.add(name)
                rec = recorded.get(name)
                if rec is not None and rec.state == PARTITION_READY:
                    continue  # held or already warm: quota satisfied
                # No record, or a non-Ready record (a crashed create/
                # teardown): the realize loop below settles and
                # completes it -- a wedged Creating record is NOT warm
                # capacity and must not satisfy the quota forever.
                to_create.append((name, devices[name]))
        # Publish the intended warm set BEFORE realizing: a concurrent
        # reap_idle (the reconcile sweep thread) snapshots keep_warm
        # up front, and a freshly created zero-holder Ready record
        # must already be covered or the sweep tears it straight back
        # down (and the watcher's hint memo would never re-create it).
        with self._mutex:
            self._prewarm_desired = set(desired)
        created = 0
        failed = 0
        for name, snap_dev in to_create:
            with self._dev_lock(name):
                rec = self._record(name)
                if rec is not None and rec.state == PARTITION_READY:
                    continue  # an attach beat us to it: already warm
                if rec is not None and self._holders(name) > 0:
                    continue  # an in-flight attach owns the record
                # Re-read the spec under the device lock (the attach
                # path's discipline, dev-lock -> mutex): a re-plan
                # racing this hint may have re-shaped or retired the
                # device since the pre-lock snapshot -- realizing the
                # STALE spec would pin a carve-out every attach then
                # refuses and the reap (keep-warm) never settles.
                with self._mutex:
                    dev = self._devices.get(name)
                if dev is None or dev.partition is None or \
                        dev.partition.spec.canonical_name() != \
                        snap_dev.partition.spec.canonical_name():
                    desired.discard(name)
                    continue
                try:
                    if rec is not None and (
                            rec.state == PARTITION_DESTROYING
                            or (self._pinned_spec(rec) or "") not in
                            ("", dev.partition.spec.canonical_name())):
                        # A crashed teardown owns the old identity --
                        # or a crashed create pinned a PRE-re-plan
                        # spec: finish/settle it, then warm fresh
                        # (never share a dying or stale-shape
                        # carve-out; the attach path's rule).
                        self._teardown_locked(name, rec)
                        rec = None
                    # rec None -> fresh warm create; rec CREATING ->
                    # complete the crashed create onto its pinned uuid
                    # (resume()'s semantic).
                    self._realize_locked(name, dev, rec)
                except PartitionEngineError:
                    # A refused create (fault injection, registry
                    # pressure) downgrades to the lazy path for this
                    # device; surfaced below so the CRD watcher does
                    # NOT memoize the hint as applied and retries it.
                    desired.discard(name)
                    failed += 1
                    continue
                created += 1
                with self._mutex:
                    self._prewarm_idle.add(name)
                if self.metrics is not None:
                    self.metrics.inc_prewarm_created()
        with self._mutex:
            # Re-publish the PRUNED set (failed/re-shaped names drop
            # out). The idle set is NOT intersected with it: a
            # warm-but-no-longer-wanted carve-out stays tracked until
            # the idle sweep reaps it (the reaped-counter accounting)
            # or a late tenant attaches (a hit anyway).
            self._prewarm_desired = desired
        if created or want:
            logger.info(
                "prewarm: %d carve-out(s) created, %d desired warm "
                "(cap %d)", created, len(desired), cap)
        if failed:
            # Partial application: everything realizable IS warm, but
            # the caller must not record the hint as converged.
            raise PartitionEngineError(
                f"prewarm: {failed} carve-out(s) failed to realize "
                f"({created} created); retry on the next hint "
                "application")
        return created

    def prewarm_state(self) -> tuple[set[str], set[str]]:
        """(desired-warm names, created-but-unattached names) -- test
        and /debug surface; copies."""
        with self._mutex:
            return set(self._prewarm_desired), set(self._prewarm_idle)

    # -- reconciliation -------------------------------------------------------

    def resume(self) -> int:
        """Crash recovery at plugin start: every record resolves to a
        settled state. Returns the number of records repaired."""
        repaired = 0
        for name in sorted(self._checkpoint.get().claims):
            with self._dev_lock(name):
                rec = self._record(name)
                if rec is None:
                    continue
                holders = self._holders(name)
                with self._mutex:
                    desired = name in self._devices
                if rec.state == PARTITION_DESTROYING:
                    # Destroy intent was durable: finish it.
                    self._teardown_locked(name, rec)
                    repaired += 1
                elif rec.state == PARTITION_CREATING:
                    if holders > 0 and desired:
                        # Crash mid-create with a tenant reservation:
                        # complete the create onto the pinned uuid --
                        # and the pinned SPEC, which wins over the
                        # current desired shape if a re-plan changed
                        # the layout file across the restart (the
                        # tenant attached under the old contract).
                        live = rec.devices[0].live
                        dev = self._devices.get(name)
                        spec = None
                        if live and live.get("spec"):
                            spec = SubSliceSpecTuple.from_canonical_name(
                                live["spec"])
                        if spec is None and dev is not None:
                            spec = dev.partition.spec
                        if live and spec is not None and \
                                live["uuid"] not in \
                                self._state.subslice_registry.list():
                            self._state.subslice_registry.create(
                                SubSliceLiveTuple(
                                    spec=spec, uuid=live["uuid"]))
                        self._checkpoint.update_claim(
                            name, CheckpointedClaim(
                                uid=name, state=PARTITION_READY,
                                devices=rec.devices))
                    else:
                        self._teardown_locked(name, rec)
                    repaired += 1
                elif rec.state == PARTITION_READY and (
                        holders == 0 or not desired):
                    if holders == 0:
                        self._teardown_locked(name, rec)
                        repaired += 1
                    # not-desired with holders: reaped on last detach
                elif rec.state == PARTITION_READY:
                    pinned = self._pinned_spec(rec)
                    with self._mutex:
                        dev = self._devices.get(name)
                    if pinned is not None and dev is not None and \
                            dev.partition is not None and \
                            pinned != dev.partition.spec.canonical_name():
                        # Layout file re-shaped this device across the
                        # restart while tenants hold the old carve-out.
                        # The held identity stays authoritative; new
                        # attaches fail until the tenants drain.
                        logger.error(
                            "partition %s: desired backing carve-out "
                            "changed across restart (%s -> %s) with "
                            "%d live tenant(s); keeping the held "
                            "carve-out until they drain", name, pinned,
                            dev.partition.spec.canonical_name(), holders)
        if self.metrics is not None:
            self.metrics.set_active(self.active_partitions())
        return repaired

    def reap_idle(self) -> int:
        """Settle lifecycle records with ZERO tenant holders: Ready
        partitions idle since their last detach (or no longer desired
        after an apply()), plus orphaned Creating/Destroying records
        whose tenant rolled back or was GC'd without an unprepare --
        without this a half-created carve-out would occupy its chips
        until the next plugin restart. Safe against in-flight
        attaches: a live prepare's claim reservation exists before
        attach runs, so a zero-holder record observed under the device
        lock is genuinely orphaned. Records the current pre-warm hint
        wants kept warm (set_prewarm) are deliberately zero-holder and
        are skipped; once the forecast decays out of the hint, this
        same sweep returns their chips. Returns partitions reaped."""
        reaped = 0
        with self._mutex:
            keep_warm = set(self._prewarm_desired)
        for name in sorted(self._checkpoint.get().claims):
            with self._dev_lock(name):
                rec = self._record(name)
                if rec is None or self._holders(name) > 0:
                    continue
                if name in keep_warm and \
                        rec.state == PARTITION_READY:
                    # Intentionally warm: the forecast holds it. ONLY
                    # Ready records qualify -- a zero-holder Creating/
                    # Destroying record on a hint-desired name is a
                    # crashed lifecycle this sweep must still settle,
                    # never warm capacity.
                    continue
                self._teardown_locked(name, rec)
                reaped += 1
                with self._mutex:
                    was_idle_warm = name in self._prewarm_idle
                    self._prewarm_idle.discard(name)
                if was_idle_warm and self.metrics is not None:
                    # A forecasted-but-never-needed carve-out going
                    # back: the forecaster's false-positive counter.
                    self.metrics.inc_prewarm_reaped()
        return reaped
