"""PartitionSet: the declarative partition layout for a pool.

The MIG analog of a mig-parted config, but DYNAMIC: instead of an admin
pre-carving a static device list, a PartitionSet declares per-pool
desired partition PROFILES ("split v5e chips into 1-core tenants with
1/2 the HBM, 4 tenants per carve-out") and the node-side engine
(pkg/partition/engine.py) realizes/retires the backing carve-outs on
demand at NodePrepare time.

Grounding (PAPERS.md): MISO (2207.11428) profiles tenant demand and
picks the smallest satisfying partition; ParvaGPU (2409.14447)
co-locates complementary DNN-inference tenants spatially. The profile
catalog here is the vocabulary both policies choose from
(pkg/partition/profiles.py, pkg/partition/packing.py).

A profile names a backing sub-slice carve-out (tpulib SubSliceProfile:
"1c" core-level, or a chip-grid shape like "1x1" / "2x1x1"), an HBM
fraction of that carve-out budgeted to the partition's tenants, and a
tenant-slot count. ``max_tenants`` > 1 makes the partition an
OVERSUBSCRIPTION device: the published KEP-4815 counter consumption is
divided by the slot count (the virtual-capacity multiplier), so N
tenant allocations together consume exactly the carve-out's budget and
the scheduler can never over-commit cores/HBM between tenants and
whole-chip claims.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field

_PROFILE_NAME_RE = re.compile(r"^[a-z0-9]([a-z0-9-]*[a-z0-9])?$")
_SUBSLICE_RE = re.compile(r"^(1c|\d+x\d+(?:x\d+)?)$")


class PartitionSpecError(ValueError):
    """A PartitionSet that can never be realized (config error)."""


@dataclass(frozen=True)
class PartitionProfile:
    """One desired partition shape.

    ``hbm_fraction`` budgets a share of the backing carve-out's HBM to
    the partition's tenants (ParvaGPU-style right-sizing: a 1-chip
    carve-out sold at 1/2 HBM leaves headroom the packer can give a
    complementary co-tenant). ``max_tenants`` is the oversubscription
    slot count; per-tenant HBM ceiling = carve-out HBM * hbm_fraction /
    max_tenants, enforced at allocation by the scaled counters and at
    runtime by the tenancy env contract."""

    name: str
    subslice: str  # backing carve-out profile ("1c", "1x1", "2x1x1", ...)
    max_tenants: int = 1
    hbm_fraction: float = 1.0

    def validate(self) -> None:
        if not _PROFILE_NAME_RE.match(self.name):
            raise PartitionSpecError(
                f"invalid partition profile name {self.name!r} "
                "(lowercase alphanumerics and dashes)"
            )
        if not _SUBSLICE_RE.match(self.subslice):
            raise PartitionSpecError(
                f"profile {self.name!r}: invalid backing sub-slice "
                f"{self.subslice!r} (want '1c' or a grid like '2x1x1')"
            )
        if self.max_tenants < 1:
            raise PartitionSpecError(
                f"profile {self.name!r}: maxTenants must be >= 1"
            )
        if not 0.0 < self.hbm_fraction <= 1.0:
            raise PartitionSpecError(
                f"profile {self.name!r}: hbmFraction must be in (0, 1]"
            )

    @property
    def oversubscribed(self) -> bool:
        return self.max_tenants > 1

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "subslice": self.subslice,
            "maxTenants": self.max_tenants,
            "hbmFraction": self.hbm_fraction,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "PartitionProfile":
        prof = cls(
            name=d.get("name", ""),
            subslice=d.get("subslice", ""),
            max_tenants=int(d.get("maxTenants", 1)),
            hbm_fraction=float(d.get("hbmFraction", 1.0)),
        )
        prof.validate()
        return prof


@dataclass(frozen=True)
class PartitionSet:
    """Desired partition profiles for the pools matching ``pools``
    (fnmatch globs over POOL names, same contract as SchedulingDomain;
    empty = every pool)."""

    profiles: tuple[PartitionProfile, ...] = ()
    pools: tuple[str, ...] = ()

    def validate(self) -> None:
        seen: set[str] = set()
        for prof in self.profiles:
            prof.validate()
            if prof.name in seen:
                raise PartitionSpecError(
                    f"duplicate partition profile name {prof.name!r}"
                )
            seen.add(prof.name)

    def applies_to_pool(self, pool: str) -> bool:
        if not self.pools:
            return True
        from fnmatch import fnmatch  # noqa: PLC0415

        return any(fnmatch(pool, pat) for pat in self.pools)

    def to_dict(self) -> dict:
        return {
            "profiles": [p.to_dict() for p in self.profiles],
            "pools": list(self.pools),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "PartitionSet":
        ps = cls(
            profiles=tuple(
                PartitionProfile.from_dict(p)
                for p in d.get("profiles", [])
            ),
            pools=tuple(d.get("pools", [])),
        )
        ps.validate()
        return ps

    @classmethod
    def from_file(cls, path: str) -> "PartitionSet":
        """Load the operator-authored partition layout (the mig-parted
        config analog; see docs/operations.md for the format)."""
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise PartitionSpecError(
                f"unreadable partition set {path!r}: {e}"
            ) from e
        if not isinstance(doc, dict):
            raise PartitionSpecError(
                f"partition set {path!r}: expected a JSON object"
            )
        return cls.from_dict(doc)


@dataclass
class PartitionDemand:
    """Observed or declared per-tenant demand (the sizing input)."""

    hbm_bytes: int = 0
    cores: int = 1
    count: int = 1  # tenants with this demand (packing weight)
    tenant: str = ""  # tenant key (DeviceClass / annotation value)

    def to_dict(self) -> dict:
        return {"hbmBytes": self.hbm_bytes, "cores": self.cores,
                "count": self.count, "tenant": self.tenant}


def partition_device_name(profile: str, placement: int) -> str:
    """Canonical partition device name (distinct from chip-/ss- names
    so nothing can collide with the raw sub-slice devices)."""
    return f"pt-{profile}-{placement}"


_PT_RE = re.compile(r"^pt-([a-z0-9](?:[a-z0-9-]*[a-z0-9])?)-(\d+)$")


def parse_partition_device_name(name: str) -> tuple[str, int] | None:
    m = _PT_RE.match(name)
    if not m:
        return None
    return m.group(1), int(m.group(2))
