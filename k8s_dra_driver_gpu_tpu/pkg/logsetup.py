"""Shared logging contract for every binary.

The contract (docs/install.md, mirroring the reference klog levels the
bats suite asserts, tests/bats/test_cd_logging.bats):

- startup banner + config dump: ALWAYS visible, even at verbosity 0
  (the reference asserts config detail in level-0 logs);
- 0: errors only;
- 4 (default): claim/domain lifecycle (INFO);
- 6: per-claim ``t_prep_*`` segment timings and other DEBUG detail;
- 7: wire dumps.
"""

from __future__ import annotations

import logging

FORMAT = "%(asctime)s %(name)s %(levelname)s %(message)s"


def level_for(verbosity: int) -> int:
    return (logging.ERROR if verbosity <= 0
            else logging.WARNING if verbosity < 4
            else logging.INFO if verbosity < 6
            else logging.DEBUG)


def setup(verbosity: int) -> None:
    logging.basicConfig(level=level_for(verbosity), format=FORMAT)


def startup_logger(name: str) -> logging.Logger:
    """A logger whose INFO records bypass the verbosity gate: records
    pass their ORIGINATING logger's level, and handlers default to
    NOTSET, so pinning this child to INFO keeps the startup config
    visible at verbosity 0."""
    lg = logging.getLogger(f"{name}.startup")
    lg.setLevel(logging.INFO)
    return lg


def log_startup(name: str, binary: str, version: str, args) -> None:
    """Banner + structured config dump (reference pkg/flags/utils.go;
    asserted at verbosity 0 by the logging-contract tests)."""
    lg = startup_logger(name)
    lg.info("%s %s starting", binary, version)
    for key, val in sorted(vars(args).items()):
        lg.info("config %s=%r", key, val)
