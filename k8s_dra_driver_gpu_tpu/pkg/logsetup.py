"""Shared logging contract for every binary.

The contract (docs/install.md, mirroring the reference klog levels the
bats suite asserts, tests/bats/test_cd_logging.bats):

- startup banner + config dump: ALWAYS visible, even at verbosity 0
  (the reference asserts config detail in level-0 logs);
- 0: errors only;
- 4 (default): claim/domain lifecycle (INFO);
- 6: per-claim ``t_prep_*`` segment timings and other DEBUG detail;
- 7: wire dumps.

Trace correlation (pkg/tracing.py): every record carries the active
span's ``trace_id`` and ``claim_uid`` (empty when no span is active),
injected by :class:`TraceContextFilter` -- so grepping a trace id from
``/debug/traces`` finds the matching log lines in every binary without
changing a single call site.
"""

from __future__ import annotations

import logging

from . import tracing

FORMAT = ("%(asctime)s %(name)s %(levelname)s "
          "[trace=%(trace_id)s] %(message)s")


class TraceContextFilter(logging.Filter):
    """Stamps ``trace_id`` / ``claim_uid`` from the calling thread's
    active span onto every record (empty strings when none), so FORMAT
    can reference them and log lines correlate with traces for free.
    Attached to handlers by :func:`setup`; always passes the record."""

    def filter(self, record: logging.LogRecord) -> bool:
        sp = tracing.current_span()
        if sp is not None and sp.recording:
            record.trace_id = sp.context.trace_id
            record.claim_uid = str(sp.attrs.get("claim_uid", ""))
        else:
            record.trace_id = ""
            record.claim_uid = ""
        return True


def install_trace_filter() -> TraceContextFilter:
    """Attach the trace filter to every root-logger handler (idempotent
    per handler); returns the filter for callers wiring custom
    handlers."""
    filt = TraceContextFilter()
    for handler in logging.getLogger().handlers:
        if not any(isinstance(f, TraceContextFilter)
                   for f in handler.filters):
            handler.addFilter(filt)
    return filt


def level_for(verbosity: int) -> int:
    return (logging.ERROR if verbosity <= 0
            else logging.WARNING if verbosity < 4
            else logging.INFO if verbosity < 6
            else logging.DEBUG)


def setup(verbosity: int) -> None:
    logging.basicConfig(level=level_for(verbosity), format=FORMAT)
    install_trace_filter()


def startup_logger(name: str) -> logging.Logger:
    """A logger whose INFO records bypass the verbosity gate: records
    pass their ORIGINATING logger's level, and handlers default to
    NOTSET, so pinning this child to INFO keeps the startup config
    visible at verbosity 0."""
    lg = logging.getLogger(f"{name}.startup")
    lg.setLevel(logging.INFO)
    return lg


def log_startup(name: str, binary: str, version: str, args) -> None:
    """Banner + structured config dump (reference pkg/flags/utils.go;
    asserted at verbosity 0 by the logging-contract tests)."""
    lg = startup_logger(name)
    lg.info("%s %s starting", binary, version)
    for key, val in sorted(vars(args).items()):
        lg.info("config %s=%r", key, val)
