"""Shared best-effort Warning-Event emission.

Three producers (the scheduler's unschedulable-pod and
domain-exhausted surfacing, the CD plugin's gang-abort) emit the same
core/v1 Event shape; this is the one builder so the dedupe convention
lives in one place. Two dedupe styles, chosen by the caller's
``event_name``:

- a DETERMINISTIC name (``<obj>.domain-exhausted``) makes the create
  itself the dedupe -- repeats hit 409 and are swallowed (create-once);
- a UNIQUE name (uuid suffix) emits every time; the caller dedupes at
  a different layer (e.g. on the object's condition).

Emission is always best-effort: events are cosmetic surfacing, and
the state write they accompany (a condition patch, an unwind) must
proceed even when the apiserver is the thing that is down.
"""

from __future__ import annotations

from .kubeclient import KubeError


def emit_warning_event(kube, *, event_name: str, namespace: str,
                       reason: str, message: str, involved_kind: str,
                       involved_name: str, involved_uid: str = "",
                       component: str) -> None:
    event = {
        "apiVersion": "v1",
        "kind": "Event",
        "metadata": {
            "name": event_name,
            "namespace": namespace,
        },
        "type": "Warning",
        "reason": reason,
        "message": message,
        "involvedObject": {
            "kind": involved_kind, "name": involved_name,
            "namespace": namespace, "uid": involved_uid,
        },
        "source": {"component": component},
    }
    try:
        kube.create("", "v1", "events", event, namespace=namespace)
    except KubeError:
        pass  # best-effort (409 = already surfaced, or API down)
