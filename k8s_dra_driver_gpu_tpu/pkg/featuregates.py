"""Versioned k8s-style feature gates with cross-gate validation.

Reference: pkg/featuregates/featuregates.go (gates TimeSlicingSettings,
MPSSupport, IMEXDaemonsWithDNSNames, PassthroughSupport,
NVMLDeviceHealthCheck, DynamicMIG, ComputeDomainCliques,
CrashOnNVLinkFabricErrors, DeviceMetadata at :44-67; dependency /
mutual-exclusion validation ValidateFeatureGates() :222-248;
emulation-version pinning :26-40).

TPU mapping: DynamicMIG -> DynamicSubSlice (ICI sub-slice carve-outs),
MPSSupport -> MultiTenancySupport (co-tenant chip sharing),
IMEXDaemonsWithDNSNames -> DomainDaemonsWithDNSNames (stable DNS names for
the JAX coordination service), NVMLDeviceHealthCheck -> ChipHealthCheck,
CrashOnNVLinkFabricErrors -> CrashOnICIFabricErrors.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from enum import Enum


class Stage(str, Enum):
    ALPHA = "ALPHA"
    BETA = "BETA"
    GA = "GA"


@dataclass(frozen=True)
class FeatureSpec:
    name: str
    default: bool
    stage: Stage
    # Gates that must be enabled for this gate to be enabled.
    requires: tuple[str, ...] = ()
    # Gates that must NOT be enabled together with this gate.
    conflicts_with: tuple[str, ...] = ()
    # Introduced-at emulation version (major, minor); a gate is unknown
    # below its introduction version.
    since: tuple[int, int] = (0, 1)


# -- Gate names ---------------------------------------------------------------

TIME_SLICING_SETTINGS = "TimeSlicingSettings"
MULTI_TENANCY_SUPPORT = "MultiTenancySupport"
DOMAIN_DAEMONS_WITH_DNS_NAMES = "DomainDaemonsWithDNSNames"
PASSTHROUGH_SUPPORT = "PassthroughSupport"
CHIP_HEALTH_CHECK = "ChipHealthCheck"
DYNAMIC_SUB_SLICE = "DynamicSubSlice"
COMPUTE_DOMAIN_CLIQUES = "ComputeDomainCliques"
CRASH_ON_ICI_FABRIC_ERRORS = "CrashOnICIFabricErrors"
DEVICE_METADATA = "DeviceMetadata"
# Multi-tenant partition engine (pkg/partition): PartitionSet-driven
# dynamic sub-slice lifecycle, profile-guided partition devices, and
# time-slice oversubscription slots for inference serving. Builds on
# the dynamic carve-out plumbing, hence the DynamicSubSlice dependency.
TENANT_PARTITIONING = "TenantPartitioning"
# ICI topology-aware placement (pkg/topology): the in-tree scheduler
# ranks candidate device sets by compactness + fragmentation cost and
# the CD controller prefers ICI-adjacent hosts for multi-host gangs.
# Off = the historical first-fit pick. No reference analog (the
# reference delegates placement entirely to kube-scheduler).
TOPOLOGY_AWARE_PLACEMENT = "TopologyAwarePlacement"

KNOWN_FEATURES: dict[str, FeatureSpec] = {
    s.name: s
    for s in [
        FeatureSpec(TIME_SLICING_SETTINGS, default=False, stage=Stage.ALPHA),
        FeatureSpec(
            MULTI_TENANCY_SUPPORT,
            default=False,
            stage=Stage.ALPHA,
            # Co-tenancy reuses the time-slicing policy plumbing; mirrors the
            # reference's MPSSupport/TimeSlicingSettings relationship.
            requires=(TIME_SLICING_SETTINGS,),
        ),
        FeatureSpec(DOMAIN_DAEMONS_WITH_DNS_NAMES, default=True, stage=Stage.BETA),
        FeatureSpec(
            PASSTHROUGH_SUPPORT,
            default=False,
            stage=Stage.ALPHA,
            # A chip handed to vfio passthrough cannot be dynamically
            # re-partitioned by this driver at the same time.
            conflicts_with=(DYNAMIC_SUB_SLICE,),
        ),
        FeatureSpec(CHIP_HEALTH_CHECK, default=True, stage=Stage.BETA),
        FeatureSpec(DYNAMIC_SUB_SLICE, default=False, stage=Stage.ALPHA),
        FeatureSpec(
            TENANT_PARTITIONING,
            default=False,
            stage=Stage.ALPHA,
            # The engine realizes partitions as dynamic carve-outs.
            requires=(DYNAMIC_SUB_SLICE,),
        ),
        FeatureSpec(COMPUTE_DOMAIN_CLIQUES, default=True, stage=Stage.BETA),
        FeatureSpec(CRASH_ON_ICI_FABRIC_ERRORS, default=True, stage=Stage.BETA),
        FeatureSpec(DEVICE_METADATA, default=False, stage=Stage.ALPHA),
        FeatureSpec(TOPOLOGY_AWARE_PLACEMENT, default=True,
                    stage=Stage.BETA),
    ]
}

# The emulation version tracks the vendored k8s minor the driver targets
# (reference pins to the vendored k8s minor, featuregates.go:26-40).
EMULATION_VERSION = (1, 34)


class FeatureGateError(ValueError):
    pass


@dataclass
class FeatureGates:
    """Immutable-after-parse set of enabled gates."""

    enabled: dict[str, bool] = field(default_factory=dict)
    emulation_version: tuple[int, int] = EMULATION_VERSION

    def is_enabled(self, name: str) -> bool:
        if name not in KNOWN_FEATURES:
            raise FeatureGateError(f"unknown feature gate {name!r}")
        # A gate is unknown (and therefore off) below its introduction
        # version, including via its default.
        if KNOWN_FEATURES[name].since > self.emulation_version:
            return False
        if name in self.enabled:
            return self.enabled[name]
        return KNOWN_FEATURES[name].default

    def validate(self) -> None:
        """Cross-gate dependency / mutual-exclusion validation.

        Reference: ValidateFeatureGates(), featuregates.go:222-248.
        """
        for name in self.enabled:
            if name not in KNOWN_FEATURES:
                raise FeatureGateError(f"unknown feature gate {name!r}")
            if KNOWN_FEATURES[name].since > self.emulation_version:
                raise FeatureGateError(
                    f"feature gate {name!r} is not available at emulation "
                    f"version {self.emulation_version}"
                )
        for name, spec in KNOWN_FEATURES.items():
            if not self.is_enabled(name):
                continue
            for dep in spec.requires:
                if not self.is_enabled(dep):
                    raise FeatureGateError(
                        f"feature gate {name} requires {dep} to be enabled"
                    )
            for other in spec.conflicts_with:
                if self.is_enabled(other):
                    raise FeatureGateError(
                        f"feature gates {name} and {other} are mutually exclusive"
                    )

    @classmethod
    def parse(cls, spec: str, emulation_version: tuple[int, int] | None = None) -> "FeatureGates":
        """Parse "Gate1=true,Gate2=false" (k8s-style) and validate.

        Empty string yields all-defaults. Reference: pkg/flags
        FeatureGateConfig with env mirror FEATURE_GATES.
        """
        enabled: dict[str, bool] = {}
        for item in filter(None, (p.strip() for p in spec.split(","))):
            if "=" not in item:
                raise FeatureGateError(
                    f"invalid feature gate spec {item!r}: expected Name=bool"
                )
            name, _, val = item.partition("=")
            name, val = name.strip(), val.strip().lower()
            if val not in ("true", "false"):
                raise FeatureGateError(
                    f"invalid value {val!r} for feature gate {name!r}"
                )
            enabled[name] = val == "true"
        fg = cls(enabled=enabled, emulation_version=emulation_version or EMULATION_VERSION)
        fg.validate()
        return fg

    @classmethod
    def from_env(
        cls,
        env_var: str = "FEATURE_GATES",
        emulation_version: tuple[int, int] | None = None,
    ) -> "FeatureGates":
        return cls.parse(
            os.environ.get(env_var, ""), emulation_version=emulation_version
        )


def default_gates() -> FeatureGates:
    return FeatureGates()
