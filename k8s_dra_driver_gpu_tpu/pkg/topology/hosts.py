"""Multi-host gang support: rank hosts so gangs land ICI-adjacent.

Hosts of one slice publish their position as the ``workerId`` device
attribute; ICI adjacency between hosts follows worker order (tpulib
assigns worker blocks along the slice grid, ``_chip_coords``). A gang
of N hosts therefore wants a run of N CONSECUTIVE worker ids -- the
host-level analog of a contiguous sub-torus.
"""

from __future__ import annotations


def rank_adjacent_hosts(host_workers: dict[str, int], gang_size: int
                        ) -> list[str]:
    """Order hosts so the best ICI-adjacent gang of ``gang_size`` comes
    first.

    Picks the window of ``gang_size`` hosts (in worker order) with the
    smallest worker-id span -- a tight window means physically adjacent
    hosts with no stranded worker inside the gang's ICI footprint.
    Remaining hosts follow in worker order, so a scheduler walking the
    list degrades gracefully when preferred hosts are full. Ties break
    toward the lowest worker id; a gang larger than the fleet just
    yields worker order.
    """
    hosts = sorted(host_workers, key=lambda h: (host_workers[h], h))
    if gang_size <= 1 or gang_size > len(hosts):
        return hosts
    best_start = 0
    best_span = None
    for start in range(len(hosts) - gang_size + 1):
        lo = host_workers[hosts[start]]
        hi = host_workers[hosts[start + gang_size - 1]]
        span = hi - lo
        if best_span is None or span < best_span:
            best_span = span
            best_start = start
    window = hosts[best_start:best_start + gang_size]
    rest = hosts[:best_start] + hosts[best_start + gang_size:]
    return window + rest
